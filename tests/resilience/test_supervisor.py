"""Recovery supervisor — the self-healing ladder (resilience/supervisor.py).

Covers the RecoveryPolicy contract, ladder escalation/probation units over
a scripted fake simulation, the quarantine roster + ledger persistence,
per-rung mitigations against real simulations, armed-but-never-engaged
bit-identity on BOTH execution modes, and THE pinned drill: under a
probability-1 scale-fault plan, unsupervised FedAvg diverges and halts via
the watchdog while the supervised run rolls back, quarantines exactly the
flight-recorder-named suspects, resumes and converges within pinned
tolerance of the fault-free trajectory — one postmortem bundle per
attempt, ``/healthz`` restored after probation.
"""

import json
import os

import jax
import numpy as np
import optax
import pytest
from flax import serialization

from fl4health_tpu.checkpointing.state import (
    CheckpointCorruptError,
    SimulationStateCheckpointer,
)
from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_classification
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.observability import (
    HealthPolicy,
    HealthWatchdog,
    MetricsRegistry,
    Observability,
    SigtermShutdown,
    Tracer,
    TrainingHealthError,
)
from fl4health_tpu.observability.bundle import list_bundles, load_bundle
from fl4health_tpu.resilience import (
    ClientFault,
    FaultPlan,
    QuarantinePolicy,
    QuarantiningStrategy,
    QuorumControl,
    RecoveryPolicy,
    RecoverySupervisor,
    RobustFedAvg,
    rank_suspects,
)
from fl4health_tpu.server.simulation import (
    ClientDataset,
    ClientFailuresError,
    FailurePolicy,
    FederatedSimulation,
)
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.transport import QuorumError

N_CLASSES = 3
N_CLIENTS = 6
POISONED = (1, 2)

# probability-1 scale fault on two clients from round 2 on — the drill's
# persistent Byzantine pair (same attack family as TestRobustnessClaim)
SCALE_FAULT = FaultPlan(seed=3, client_faults=(
    ClientFault(clients=POISONED, kind="scale", scale=-15.0,
                probability=1.0, start_round=2),
))


def _datasets(n=N_CLIENTS, poison_nan=()):
    out = []
    for i in range(n):
        x, y = synthetic_classification(
            jax.random.PRNGKey(20 + i), 32, (6,), N_CLASSES
        )
        x = np.asarray(x).copy()
        if i in poison_nan:
            x[:] = np.nan
        out.append(ClientDataset(x[:24], y[:24], x[24:], y[24:]))
    return out


def make_obs(output_dir=None, watchdog=True):
    return Observability(
        enabled=True, tracer=Tracer(), registry=MetricsRegistry(),
        sync_device=False,
        output_dir=str(output_dir) if output_dir else None,
        watchdog=HealthWatchdog(HealthPolicy(
            loss_divergence_window=1, loss_divergence_factor=1.4,
            on_loss_divergence="halt", on_nonfinite="halt",
        )) if watchdog else None,
    )


def make_sim(mode="chunked", *, ckpt_dir=None, fault=None, recovery=None,
             obs=None, datasets=None, strategy=None, n_rounds_ckpt=1,
             **kwargs):
    kw = dict(kwargs)
    if ckpt_dir is not None:
        kw["state_checkpointer"] = SimulationStateCheckpointer(
            str(ckpt_dir), checkpoint_every=n_rounds_ckpt, keep=8,
        )
    return FederatedSimulation(
        logic=engine.ClientLogic(
            engine.from_flax(Mlp(features=(8,), n_outputs=N_CLASSES)),
            engine.masked_cross_entropy,
        ),
        tx=optax.sgd(0.05),
        strategy=strategy if strategy is not None else FedAvg(),
        datasets=datasets if datasets is not None else _datasets(),
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=2, local_epochs=None, seed=9,
        execution_mode=mode,
        observability=obs if obs is not None else Observability(
            enabled=False
        ),
        fault_plan=fault, recovery=recovery, **kw,
    )


def _params_bytes(sim) -> bytes:
    return serialization.to_bytes(jax.device_get(sim.global_params))


# ---------------------------------------------------------------------------
class TestRecoveryPolicy:
    def test_defaults_validate(self):
        p = RecoveryPolicy()
        assert p.rungs == ("retry", "quarantine", "robustify", "degrade")

    @pytest.mark.parametrize("kw", [
        {"rungs": ()},
        {"rungs": ("nope",)},
        {"rungs": ("retry", "retry")},
        {"recover_kinds": ("sigterm",)},
        {"attempts_per_rung": 0},
        {"max_total_attempts": 0},
        {"probation_rounds": 0},
        {"quarantine_rounds": -1},
        {"max_suspects": 0},
        {"quorum_relax": 0.0},
        {"cohort_shrink": 1.5},
        {"server_lr_factor": 0.0},
        {"robust_method": "nope"},
    ])
    def test_rejects_bad_values(self, kw):
        with pytest.raises(ValueError):
            RecoveryPolicy(**kw)

    def test_simulation_rejects_duck_typed_policy(self):
        with pytest.raises(TypeError, match="RecoveryPolicy"):
            make_sim(recovery={"rungs": ("retry",)})


# ---------------------------------------------------------------------------
class _FakeManager:
    def __init__(self, fraction=0.5, n_clients=8):
        self.fraction = fraction
        self.n_clients = n_clients


class _FakeSim:
    """Scripted stand-in exposing exactly the surface the supervisor
    drives; ``failures`` lists the exception each successive fit attempt
    raises (None = clean completion)."""

    def __init__(self, failures, strategy=None, manager=None,
                 checkpointer=None):
        self._failures = list(failures)
        self.observability = Observability(
            enabled=False, tracer=Tracer(), registry=MetricsRegistry()
        )
        self.state_checkpointer = checkpointer
        self.strategy = strategy if strategy is not None else FedAvg()
        self.client_manager = manager
        self._async_active = False
        self._cohort_active = False
        self.n_clients = 8
        self._fit_n_rounds = 4
        self.fits = 0
        self.resets = 0
        self.rebuilds = 0

    def _fit_unsupervised(self, n_rounds):
        self.fits += 1
        if self._failures:
            exc = self._failures.pop(0)
            if exc is not None:
                raise exc
        return "done"

    def _reset_to_initial(self):
        self.resets += 1

    def _build_compiled(self):
        self.rebuilds += 1


def _the(round_=2, clients=(3,)):
    return TrainingHealthError(
        "halt", round=round_, clients=list(clients), check="nonfinite"
    )


class TestLadderUnits:
    def test_escalates_through_every_rung_then_halts(self):
        sim = _FakeSim([_the()] * 5, manager=_FakeManager())
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(attempts_per_rung=1, probation_rounds=100),
            quorum_control=QuorumControl(quorum=3),
        )
        with pytest.raises(TrainingHealthError):
            sup.run(4)
        # retry, quarantine, robustify, degrade each got exactly one
        # attempt; the 5th failure exhausted the ladder and re-raised
        assert sup._attempts == {"retry": 1, "quarantine": 1,
                                 "robustify": 1, "degrade": 1}
        assert sim.fits == 5
        assert sim.resets == 4  # no checkpointer: every rollback restarts
        assert isinstance(sim.strategy, RobustFedAvg)  # robustify rung
        assert sim.rebuilds == 1
        assert sim.client_manager.fraction == pytest.approx(0.25)  # degrade
        assert sup.quorum_control.quorum == 2  # degrade relaxed the quorum
        assert sup.quarantined_ids(1) == [3]

    def test_recovers_then_succeeds(self):
        sim = _FakeSim([_the(), None])
        sup = RecoverySupervisor(sim, RecoveryPolicy())
        assert sup.run(4) == "done"
        assert sim.fits == 2
        assert sup._total_attempts == 1

    def test_quarantine_skipped_without_suspects(self):
        # a cohort-level verdict with no named clients and an empty ring:
        # the quarantine rung has nobody to mask — the ladder skips it
        sim = _FakeSim([_the(clients=()), _the(clients=())])
        sup = RecoverySupervisor(
            sim,
            RecoveryPolicy(rungs=("quarantine", "robustify"),
                           attempts_per_rung=1),
        )
        with pytest.raises(TrainingHealthError):
            sup.run(4)
        assert "quarantine" not in sup._attempts
        assert sup._attempts == {"robustify": 1}

    def test_nonrecoverable_kinds_propagate_untouched(self):
        for exc, raises in ((RuntimeError("boom"), RuntimeError),
                            (SigtermShutdown(), SystemExit)):
            sim = _FakeSim([exc])
            sup = RecoverySupervisor(sim, RecoveryPolicy())
            with pytest.raises(raises):
                sup.run(4)
            assert sup._total_attempts == 0
            assert sim.fits == 1

    def test_max_total_attempts_is_a_hard_ceiling(self):
        sim = _FakeSim([_the()] * 10)
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(attempts_per_rung=10, max_total_attempts=2)
        )
        with pytest.raises(TrainingHealthError):
            sup.run(4)
        assert sup._total_attempts == 2
        assert sim.fits == 3

    def test_quorum_error_is_recoverable(self):
        err = QuorumError("quorum lost", required=3, succeeded=1,
                          failures=[("h:1", "timeout")])
        sim = _FakeSim([err, None], manager=_FakeManager())
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(rungs=("degrade",)),
            quorum_control=QuorumControl(quorum=3),
        )
        assert sup.run(4) == "done"
        assert sup.quorum_control.quorum == 2

    def test_checkpoint_corrupt_clears_ring_and_restarts(self, tmp_path):
        sc = SimulationStateCheckpointer(str(tmp_path))
        bad = tmp_path / "state.g00000001.ckpt"
        bad.write_bytes(b"FL4HCKPT garbage")
        err = CheckpointCorruptError(str(bad), "CRC32 mismatch")
        sim = _FakeSim([err, None], checkpointer=sc)
        sup = RecoverySupervisor(sim, RecoveryPolicy(rungs=("retry",)))
        assert sup.run(4) == "done"
        assert not sc.exists()  # wreckage cleared
        assert sim.resets == 1  # nothing durable left: restart from init


# ---------------------------------------------------------------------------
class TestQuarantineRosterAndProbation:
    def test_keep_mask_and_release_round(self):
        sim = _FakeSim([])
        sup = RecoverySupervisor(sim, RecoveryPolicy(quarantine_rounds=3))
        assert sup.keep_mask(1, 6) is None  # never engaged: pure fast path
        sup._apply_quarantine([1, 4], resume_round=5)
        keep = sup.keep_mask(5, 6)
        np.testing.assert_array_equal(keep, [1, 0, 1, 1, 0, 1])
        assert sup.quarantined_ids(7) == [1, 4]
        # release at resume_round + quarantine_rounds = 8
        assert sup.keep_mask(8, 6) is None
        assert sup.quarantined_ids(8) == []

    def test_quarantine_rounds_zero_is_rest_of_run(self):
        sup = RecoverySupervisor(
            _FakeSim([]), RecoveryPolicy(quarantine_rounds=0)
        )
        sup._apply_quarantine([2], resume_round=1)
        assert sup.quarantined_ids(10_000) == [2]

    def test_probation_resets_ladder_and_marks_healthy(self):
        sim = _FakeSim([])
        obs = sim.observability
        obs.enabled = True  # metrics/healthz surface for this unit
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(probation_rounds=2, attempts_per_rung=3)
        )
        sup._attempts = {"retry": 2}
        sup._rung_idx = 1
        sup._engaged = True
        sup._probation_after = 4  # the failure was at round 4
        obs.mark_unhealthy("recovering")
        sup.note_round(3)  # replayed pre-failure round: no credit
        sup.note_round(4)
        assert sup._healthy_rounds == 0
        sup.note_round(5)
        assert sup._engaged and obs.unhealthy_reason is not None
        sup.note_round(6)  # second healthy round PAST the failure:
        # probation passes
        assert not sup._engaged
        assert sup._attempts == {} and sup._rung_idx == 0
        assert obs.unhealthy_reason is None  # mark_healthy: /healthz 200
        snap = obs.registry.snapshot()
        assert snap["fl_recovery_engaged"] == 0.0
        assert snap["fl_recovery_probations_passed_total"] == 1.0

    def test_ledger_survives_a_new_process(self, tmp_path):
        path = str(tmp_path / "recovery_ledger.json")
        sim = _FakeSim([_the(clients=(2,)), None])
        sup = RecoverySupervisor(
            sim,
            RecoveryPolicy(rungs=("quarantine",), quarantine_rounds=0),
            ledger_path=path,
        )
        assert sup.run(4) == "done"
        assert sup.quarantined_ids(1) == [2]
        with open(path) as f:
            doc = json.load(f)
        assert doc["quarantine"] == {"2": 0}
        # "new process": a fresh supervisor over the same ledger path
        sup2 = RecoverySupervisor(
            _FakeSim([]), RecoveryPolicy(rungs=("quarantine",)),
            ledger_path=path,
        )
        assert sup2.quarantined_ids(1) == [2]
        assert sup2._engaged and sup2._total_attempts == 1

    def test_ledger_rearms_robustify_and_degrade_mitigations(
            self, tmp_path):
        """A SIGKILLed process's factory rebuilds the sim with its
        ORIGINAL strategy/manager/quorum — the ledger must re-apply the
        journaled robustify swap and degrade relaxations, not just
        remember their spent attempt budgets."""
        from fl4health_tpu.server.client_manager import FixedFractionManager

        path = str(tmp_path / "recovery_ledger.json")
        sim = _FakeSim([_the(), _the(), None],
                       manager=FixedFractionManager(8, 0.5))
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(rungs=("robustify", "degrade")),
            ledger_path=path, quorum_control=QuorumControl(quorum=3),
        )
        assert sup.run(4) == "done"
        assert isinstance(sim.strategy, RobustFedAvg)
        assert sim.client_manager.k == 2
        # "new process": plain FedAvg + original manager/quorum again
        sim2 = _FakeSim([], manager=FixedFractionManager(8, 0.5))
        ctl2 = QuorumControl(quorum=3)
        RecoverySupervisor(sim2, RecoveryPolicy(), ledger_path=path,
                           quorum_control=ctl2)
        assert isinstance(sim2.strategy, RobustFedAvg)
        assert sim2.strategy.trim_fraction == pytest.approx(0.2)
        assert sim2.rebuilds == 1  # the swap re-traced the programs
        assert sim2.client_manager.fraction == pytest.approx(0.25)
        assert sim2.client_manager.k == 2
        assert ctl2.quorum == 2

    def test_robustify_rung_skipped_when_nothing_to_tighten(self):
        """An existing RobustFedAvg with no trimming knob (median/Krum)
        leaves the rung inapplicable — no parameter-identical copy, no
        wasted re-trace, no burned attempt."""
        sim = _FakeSim([], strategy=RobustFedAvg(method="median"))
        sup = RecoverySupervisor(sim, RecoveryPolicy())
        assert sup._robustify_target() is None
        assert not sup._rung_applicable("robustify", [1])
        # ledger restore still reaches the handle (trim re-application)
        assert sup._robustify_target(for_restore=True) is sim.strategy

    def test_unreadable_ledger_degrades_to_fresh_ladder(self, tmp_path):
        path = tmp_path / "recovery_ledger.json"
        path.write_text("{torn")
        sup = RecoverySupervisor(_FakeSim([]), RecoveryPolicy(),
                                 ledger_path=str(path))
        assert sup._total_attempts == 0 and not sup._quarantine


# ---------------------------------------------------------------------------
class TestMitigationsOnRealSimulations:
    def test_in_graph_seeding_on_quarantining_strategy(self):
        sim = make_sim(strategy=QuarantiningStrategy(
            FedAvg(), QuarantinePolicy()
        ))
        sup = RecoverySupervisor(sim, RecoveryPolicy(quarantine_rounds=4))
        sup._engaged = True
        sup._pending_seed = [1, 3]
        sup.on_resume(2)
        q = np.asarray(sim.strategy.quarantine_mask(sim.server_state))
        np.testing.assert_array_equal(q, [0, 1, 0, 1, 0, 0])
        release = np.asarray(sim.server_state.quarantine.release_in)
        assert release[1] == 4.0 and release[3] == 4.0

    def test_robustify_swap_keeps_state_and_still_fits(self):
        sim = make_sim()
        sup = RecoverySupervisor(sim, RecoveryPolicy())
        before = jax.tree_util.tree_structure(sim.server_state)
        facts = sup._apply_robustify()
        assert facts == {"robustify": "swap", "method": "trimmed_mean",
                         "trim_fraction": 0.2}
        assert isinstance(sim.strategy, RobustFedAvg)
        # RobustFedAvg's state IS FedAvgState: restored checkpoints fit
        assert jax.tree_util.tree_structure(sim.server_state) == before
        hist = sim.fit(2)  # the rebuilt programs dispatch fine
        assert len(hist) == 2

    def test_robustify_tightens_an_existing_robust_strategy(self):
        sim = make_sim(strategy=RobustFedAvg(method="trimmed_mean",
                                             trim_fraction=0.2))
        sup = RecoverySupervisor(sim, RecoveryPolicy())
        facts = sup._apply_robustify()
        assert facts["robustify"] == "tighten"
        assert sim.strategy.trim_fraction == pytest.approx(0.3)

    def test_degrade_recomputes_fixed_fraction_k(self):
        """FixedFractionManager caches its realized count k at
        construction — the degrade rung must re-derive it or shrinking
        the fraction would be a silent no-op."""
        from fl4health_tpu.server.client_manager import FixedFractionManager

        mgr = FixedFractionManager(8, 0.5)
        assert mgr.k == 4
        sim = _FakeSim([], manager=mgr)
        sup = RecoverySupervisor(sim, RecoveryPolicy(cohort_shrink=0.5))
        facts = sup._apply_degrade()
        assert facts["cohort_fraction"]["to"] == pytest.approx(0.25)
        assert mgr.k == 2

    def test_robustify_not_applicable_to_stateful_strategies(self):
        from fl4health_tpu.strategies.fedopt import fed_adam

        sim = make_sim(strategy=fed_adam(lr=0.01))
        sup = RecoverySupervisor(sim, RecoveryPolicy())
        assert sup._robustify_target() is None


# ---------------------------------------------------------------------------
class TestSuspectScoring:
    def test_chaos_disclosure_and_nonfinite_dominate(self):
        ring = [
            {"round": 2, "mask": np.ones(4),
             "telemetry": {"nonfinite_loss": np.array([0, 0, 2, 0.0])},
             "fault": {"corrupted": [1], "kinds": {"scale": [1]}}},
        ]
        ranked = rank_suspects(ring)
        by_id = {s["client"]: s for s in ranked}
        assert set(by_id) == {1, 2}
        assert by_id[2]["score"] == pytest.approx(10.0)  # non-finite
        assert by_id[1]["score"] == pytest.approx(6.0)   # chaos disclosure
        assert any("chaos layer" in e for e in by_id[1]["evidence"])

    def test_verdict_clients_lead_then_ring_fills(self):
        sim = _FakeSim([])
        sup = RecoverySupervisor(
            sim, RecoveryPolicy(max_suspects=2, suspect_score_threshold=2.0)
        )
        sim.observability.flight_recorder.record_round(
            2, {}, mask=np.ones(4),
            telemetry={"nonfinite_loss": np.array([0, 0, 3, 0.0])},
        )
        suspects, ranked = sup._suspects({"clients": [0]})
        assert suspects == [0, 2]
        assert ranked[0]["client"] == 2


# ---------------------------------------------------------------------------
@pytest.mark.selfheal
class TestArmedNeverEngagedBitIdentity:
    @pytest.mark.parametrize("mode", ["pipelined", "chunked"])
    def test_armed_idle_policy_is_bit_identical(self, mode):
        base = make_sim(mode)
        hb = base.fit(3)
        armed = make_sim(mode, recovery=RecoveryPolicy())
        ha = armed.fit(3)
        assert _params_bytes(base) == _params_bytes(armed)
        assert [r.fit_losses for r in hb] == [r.fit_losses for r in ha]
        sup = armed._recovery_supervisor
        assert sup is not None and sup._total_attempts == 0


# ---------------------------------------------------------------------------
@pytest.mark.selfheal
class TestSelfHealDrill:
    """THE acceptance pin, both execution modes: probability-1 scale
    fault -> unsupervised FedAvg diverges and the watchdog halts it;
    the supervised run self-heals (rollback + quarantine of exactly the
    flight-recorder-named suspects) and converges within pinned tolerance
    of the fault-free trajectory — one self-consistent postmortem bundle
    per recovery attempt, ``/healthz`` back to 200 after probation."""

    N_ROUNDS = 10

    @pytest.fixture(scope="class")
    def fault_free_final(self):
        hist = make_sim("chunked", obs=make_obs()).fit(self.N_ROUNDS)
        return (hist[-1].fit_losses["backward"],
                hist[-1].eval_losses["checkpoint"])

    @pytest.mark.parametrize("mode", ["pipelined", "chunked"])
    def test_supervised_run_self_heals(self, mode, tmp_path,
                                       fault_free_final):
        # -- unsupervised arm: diverges, watchdog halts ------------------
        with pytest.raises(TrainingHealthError) as ei:
            make_sim(mode, obs=make_obs(), fault=SCALE_FAULT).fit(
                self.N_ROUNDS
            )
        assert ei.value.check == "loss_divergence"

        # -- supervised arm: rollback + quarantine + resume --------------
        obs = make_obs(output_dir=tmp_path / "obs")
        sim = make_sim(
            mode, obs=obs, fault=SCALE_FAULT, ckpt_dir=tmp_path / "ck",
            recovery=RecoveryPolicy(probation_rounds=3,
                                    quarantine_rounds=0),
        )
        hist = sim.fit(self.N_ROUNDS)
        assert [r.round for r in hist] == list(range(1, self.N_ROUNDS + 1))
        sup = sim._recovery_supervisor
        # exactly the flight-recorder-named suspects are quarantined
        assert sorted(sup._quarantine) == sorted(POISONED)
        assert sup._attempts == {}  # probation passed: ladder reset
        assert not sup._engaged
        assert obs.unhealthy_reason is None  # /healthz back to 200
        # one self-consistent postmortem bundle per recovery attempt
        bundles = list_bundles(str(tmp_path / "obs"))
        assert len(bundles) == 2
        for b in bundles:
            verdict = load_bundle(b)["verdict"]
            assert verdict["kind"] == "training_health"
        # the recovery JSONL trail: one engage per attempt. Each attempt's
        # shutdown exports-and-clears the event log, so the full trail
        # lives in the per-attempt bundles' events.tail.jsonl plus the
        # final run's metrics.jsonl — exactly the operator's artifacts.
        events = []
        for b in bundles:
            events.extend(load_bundle(b)["events"])
        with open(tmp_path / "obs" / "metrics.jsonl") as f:
            events.extend(json.loads(line) for line in f if line.strip())
        events = [e for e in events if e.get("event") == "recovery"]
        engages = [e for e in events if e.get("phase") == "engage"]
        assert [e["rung"] for e in engages] == ["retry", "quarantine"]
        assert all(sorted(e["suspects"]) == sorted(POISONED)
                   for e in engages)
        assert any(e.get("phase") == "probation_passed" for e in events)
        # fl_recovery_* metrics landed
        snap = obs.registry.snapshot()
        assert snap["fl_recovery_attempts_total"]['{rung="retry"}'] == 1.0
        assert (snap["fl_recovery_attempts_total"]['{rung="quarantine"}']
                == 1.0)
        # -- convergence within pinned tolerance of fault-free -----------
        fit_ref, eval_ref = fault_free_final
        fit_final = hist[-1].fit_losses["backward"]
        eval_final = hist[-1].eval_losses["checkpoint"]
        assert abs(fit_final - fit_ref) < 0.2, (fit_final, fit_ref)
        assert abs(eval_final - eval_ref) < 0.6, (eval_final, eval_ref)

    def test_client_failures_taxonomy_heals_too(self):
        """accept_failures=False + a NaN-poisoned client: the structured
        ClientFailuresError names the client; the supervisor quarantines
        it (restart rollback — no checkpointer) and the run completes."""
        sim = make_sim(
            "pipelined", datasets=_datasets(4, poison_nan=(2,)),
            failure_policy=FailurePolicy(accept_failures=False),
            recovery=RecoveryPolicy(rungs=("quarantine",),
                                    quarantine_rounds=0),
        )
        hist = sim.fit(3)
        assert len(hist) == 3
        assert sim._recovery_supervisor.quarantined_ids(1) == [2]

    def test_unsupervised_client_failures_still_raise(self):
        sim = make_sim(
            "pipelined", datasets=_datasets(4, poison_nan=(2,)),
            failure_policy=FailurePolicy(accept_failures=False),
        )
        with pytest.raises(ClientFailuresError):
            sim.fit(3)

"""Two-poll feature-alignment orchestration: pandas in, federated round out
(reference: servers/tabular_feature_alignment_server.py:27,113,
clients/tabular_data_client.py:22)."""

import numpy as np
import optax
import pandas as pd
import pytest

from fl4health_tpu.clients import engine
from fl4health_tpu.feature_alignment.orchestration import (
    FEATURE_INFO,
    INPUT_DIMENSION,
    OUTPUT_DIMENSION,
    SOURCE_SPECIFIED,
    TabularDataClient,
    TabularFeatureAlignmentServer,
)
from fl4health_tpu.feature_alignment.schema import TabularFeaturesInfoEncoder
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg


def client_frame(n, seed, drop_column=False, extra_column=False):
    """Heterogeneous hospital-style frames: same underlying task, ragged
    schemas (a column missing here, an extra local-only column there)."""
    rng = np.random.default_rng(seed)
    age = rng.uniform(20, 90, n).round(1)
    pressure = rng.uniform(90, 180, n).round(1)
    sex = rng.choice(["F", "M"], n)
    score = (age / 90 + (pressure - 90) / 90 + (sex == "M") * 0.3) / 2.3
    outcome = (score + rng.normal(0, 0.15, n) > 0.55).astype(int).astype(str)
    data = {
        "patient_id": np.arange(n),
        "age": age,
        "pressure": pressure,
        "sex": sex,
        "outcome": outcome,
    }
    if drop_column:
        del data["pressure"]
    if extra_column:
        data["local_only_notes_id"] = rng.integers(0, 5, n)
    return pd.DataFrame(data)


def make_clients():
    return [
        TabularDataClient(client_frame(60, 1), "patient_id", ["outcome"]),
        TabularDataClient(client_frame(60, 2, drop_column=True), "patient_id", ["outcome"]),
        TabularDataClient(client_frame(60, 3, extra_column=True), "patient_id", ["outcome"]),
    ]


def sim_builder(input_dim, output_dim, clients):
    datasets = []
    for c in clients:
        x, y = c.aligned_arrays()
        y = y.astype(np.int32)
        split = int(0.8 * len(x))
        datasets.append(
            ClientDataset(
                x_train=x[:split], y_train=y[:split],
                x_val=x[split:], y_val=y[split:],
            )
        )
    return FederatedSimulation(
        logic=engine.ClientLogic(
            engine.from_flax(Mlp(features=(16,), n_outputs=output_dim)),
            engine.masked_cross_entropy,
        ),
        tx=optax.adam(5e-3),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=5,
        seed=0,
    )


class TestClientProtocol:
    def test_poll1_offers_schema_poll2_aligns_and_reports_dims(self):
        client = make_clients()[0]
        props1 = client.get_properties({SOURCE_SPECIFIED: False})
        assert FEATURE_INFO in props1
        schema = TabularFeaturesInfoEncoder.from_json(props1[FEATURE_INFO])
        assert "age" in schema.get_feature_columns()
        assert schema.get_target_columns() == ["outcome"]

        props2 = client.get_properties(
            {SOURCE_SPECIFIED: True, FEATURE_INFO: props1[FEATURE_INFO]}
        )
        assert props2[INPUT_DIMENSION] > 0
        assert props2[OUTPUT_DIMENSION] == 2  # binary outcome -> 2 classes

    def test_alignment_imputes_missing_and_drops_local_only(self):
        """The client missing 'pressure' and the client with a local-only
        column must both land on the SAME encoded width."""
        clients = make_clients()
        schema_json = clients[0].get_properties({SOURCE_SPECIFIED: False})[FEATURE_INFO]
        widths = set()
        for c in clients:
            x, _ = c.align(schema_json)
            widths.add(x.shape[1])
        assert len(widths) == 1


class TestServerOrchestration:
    def test_two_polls_then_federated_round(self):
        clients = make_clients()
        server = TabularFeatureAlignmentServer(
            config={"n_server_rounds": 3},
            clients=clients,
            sim_builder=sim_builder,
        )
        history = server.fit(3)

        # protocol outcomes
        assert server.initial_polls_complete
        assert server.source_info_gathered
        assert FEATURE_INFO in server.config, "schema redistributed via config"
        assert server.dimension_info[OUTPUT_DIMENSION] == 2
        # all clients aligned (the second poll touches every client)
        assert all(c.aligned is not None for c in clients)

        assert len(history) == 3
        assert history[-1].fit_losses["backward"] < history[0].fit_losses["backward"]
        assert history[-1].eval_metrics["accuracy"] > 0.5

    def test_supplied_source_of_truth_skips_poll1(self):
        clients = make_clients()
        # source of truth from a frame that has every column
        truth = TabularFeaturesInfoEncoder.encoder_from_dataframe(
            client_frame(30, 9), "patient_id", ["outcome"]
        ).to_json()
        calls = {"n": 0}
        orig = clients[0].get_properties

        def counting(request):
            calls["n"] += 1
            assert request.get(SOURCE_SPECIFIED, False), (
                "with a supplied source of truth, only the dimension poll may run"
            )
            return orig(request)

        clients[0].get_properties = counting
        server = TabularFeatureAlignmentServer(
            config={},
            clients=clients,
            sim_builder=sim_builder,
            feature_info_source=truth,
        )
        server.fit(1)
        assert calls["n"] == 1  # dimension poll only

"""Unit pins for the schema-driven column transforms
(feature_alignment/preprocessor.py — reference
tab_features_preprocessor.py:18 + string_columns_transformer.py). The
orchestration e2e test proves the negotiation; these pin the TRANSFORM
semantics the aligned arrays depend on: fit-then-transform scaling,
unknown-category handling, missing-column synthesis, and sklearn-default
TF-IDF math."""

import numpy as np
import pandas as pd
import pytest

from fl4health_tpu.feature_alignment.preprocessor import (
    TabularFeaturesPreprocessor,
    _categorical_transform,
    _NumericTransform,
    _TfidfTransform,
)
from fl4health_tpu.feature_alignment.schema import (
    TabularFeature,
    TabularFeaturesInfoEncoder,
    TabularType,
)


def _num(name="age", fill=0.0):
    return TabularFeature(name, TabularType.NUMERIC, fill_value=fill)


class TestNumericTransform:
    def test_fit_then_transform_scales_consistently(self):
        """Validation data must use the TRAINING min/max (sklearn pipeline
        semantics) — values outside the fitted range land outside [0, 1]."""
        t = _NumericTransform(_num())
        t.fit(np.asarray([0.0, 10.0], dtype=object))
        out = t(np.asarray([0.0, 5.0, 10.0, 20.0], dtype=object))
        np.testing.assert_allclose(out[:, 0], [0.0, 0.5, 1.0, 2.0])

    def test_constant_column_does_not_divide_by_zero(self):
        t = _NumericTransform(_num())
        out = t(np.asarray([3.0, 3.0, 3.0], dtype=object))
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(out[:, 0], 0.0)

    def test_missing_values_imputed_with_fill(self):
        t = _NumericTransform(_num(fill=5.0))
        t.fit(np.asarray([0.0, 10.0], dtype=object))
        out = t(np.asarray([None, float("nan"), 10.0], dtype=object))
        np.testing.assert_allclose(out[:, 0], [0.5, 0.5, 1.0])


class TestCategoricalTransform:
    def _feat(self):
        return TabularFeature("color", TabularType.ORDINAL, fill_value="red",
                              metadata=["blue", "green", "red"])

    def test_one_hot_known_and_unknown(self):
        t = _categorical_transform(self._feat(), one_hot=True)
        out = t(np.asarray(["blue", "red", "PURPLE"], dtype=object))
        np.testing.assert_array_equal(out[0], [1, 0, 0])
        np.testing.assert_array_equal(out[1], [0, 0, 1])
        # unknown category -> all-zero row (handle_unknown='ignore')
        np.testing.assert_array_equal(out[2], [0, 0, 0])

    def test_ordinal_targets_get_dedicated_unknown_code(self):
        t = _categorical_transform(self._feat(), one_hot=False)
        out = t(np.asarray(["green", "PURPLE"], dtype=object))
        assert out[0, 0] == 1.0
        assert out[1, 0] == len(self._feat().metadata) + 1  # unknown_value

    def test_missing_imputed_before_encoding(self):
        t = _categorical_transform(self._feat(), one_hot=True)
        out = t(np.asarray([None], dtype=object))
        np.testing.assert_array_equal(out[0], [0, 0, 1])  # fill 'red'


class TestTfidfTransform:
    def _feat(self):
        return TabularFeature("notes", TabularType.STRING, fill_value="",
                              metadata=["cough", "fever", "mild"])

    def test_matches_sklearn_default_formula(self):
        """smooth-idf + l2 rows: idf = log((1+n)/(1+df)) + 1."""
        t = _TfidfTransform(self._feat())
        corpus = np.asarray(
            ["mild cough", "fever", "mild fever"], dtype=object
        )
        out = t.fit(corpus)(corpus)
        n = 3
        df = np.asarray([1, 2, 2])  # cough, fever, mild
        idf = np.log((1 + n) / (1 + df)) + 1
        row0 = np.asarray([idf[0], 0.0, idf[2]])
        row0 = row0 / np.linalg.norm(row0)
        np.testing.assert_allclose(out[0], row0, rtol=1e-12)
        # every non-empty row is l2-normalized
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0)

    def test_out_of_vocabulary_tokens_ignored(self):
        t = _TfidfTransform(self._feat())
        t.fit(np.asarray(["cough fever mild"], dtype=object))
        out = t(np.asarray(["zebra quantum"], dtype=object))
        np.testing.assert_allclose(out[0], 0.0)


class TestPreprocessorAlignment:
    def _encoder(self):
        return TabularFeaturesInfoEncoder(
            tabular_features=[
                _num("age"),
                TabularFeature("color", TabularType.ORDINAL,
                               fill_value="red",
                               metadata=["blue", "green", "red"]),
            ],
            tabular_targets=[
                TabularFeature("label", TabularType.ORDINAL, fill_value="no",
                               metadata=["no", "yes"]),
            ],
        )

    def test_missing_column_synthesized_from_fill_value(self):
        """A client lacking a negotiated column still produces the aligned
        width — the core cross-client alignment contract."""
        pre = TabularFeaturesPreprocessor(self._encoder())
        df_full = pd.DataFrame({"age": [0.0, 10.0], "color": ["blue", "red"],
                                "label": ["no", "yes"]})
        pre.fit(df_full)
        x_full, y_full = pre.preprocess_features(df_full)
        df_missing = pd.DataFrame({"age": [5.0], "label": ["yes"]})
        x_miss, y_miss = pre.preprocess_features(df_missing)
        assert x_miss.shape[1] == x_full.shape[1]
        # synthesized 'color' column one-hots the fill value 'red'
        np.testing.assert_array_equal(x_miss[0, 1:], [0, 0, 1])
        assert y_miss[0] == 1.0

    def test_column_order_is_sorted_feature_names(self):
        pre = TabularFeaturesPreprocessor(self._encoder())
        df = pd.DataFrame({"color": ["blue"], "age": [1.0], "label": ["no"]})
        x, _ = pre.preprocess_features(df)
        # 'age' (numeric, 1 col) before 'color' (one-hot, 3 cols)
        assert x.shape == (1, 4)
        np.testing.assert_allclose(x[0, 0], 0.0)  # lazily-fit single value

    def test_set_feature_pipeline_hook(self):
        pre = TabularFeaturesPreprocessor(self._encoder())
        pre.set_feature_pipeline("age", lambda col: np.full((len(col), 1), 7.0))
        df = pd.DataFrame({"age": [1.0], "color": ["blue"], "label": ["no"]})
        x, _ = pre.preprocess_features(df)
        assert x[0, 0] == pytest.approx(7.0)

"""Examples corpus smoke tests (the reference's de-facto acceptance surface,
SURVEY Appendix A: examples/*/{server,client}.py). Every run.py executes
end-to-end with a tiny config, in-process (one JAX runtime for the whole
parametrized sweep — the subprocess-per-example pattern would re-pay backend
startup ~20x)."""

import os
import runpy
import sys
from pathlib import Path

import pytest

EXAMPLES_DIR = Path(__file__).resolve().parent.parent.parent / "examples"
ALL_RUN_SCRIPTS = sorted(
    p.relative_to(EXAMPLES_DIR) for p in EXAMPLES_DIR.rglob("run.py")
)

# Heavier examples get their own pared-down env; everything else shares the
# 1-round 2-client override.
TINY_ENV = {
    "FL4HEALTH_EXAMPLE_ROUNDS": "1",
    "FL4HEALTH_EXAMPLE_CLIENTS": "2",
    "FL4HEALTH_EXAMPLE_TINY": "1",
}


# Completeness stays in the fast lane (cheap, pure-Python); the 42 e2e runs
# are the slow lane's biggest line item.
def test_corpus_is_complete():
    """The corpus must keep covering the major reference families."""
    names = {str(p.parent) for p in ALL_RUN_SCRIPTS}
    for required in [
        "basic_example", "fedopt_example", "fedprox_example",
        "scaffold_example", "ditto_example", "mr_mtl_example", "apfl_example",
        "moon_example", "fedbn_example", "fedper_example", "fedpm_example",
        "feddg_ga_example", "flash_example", "federated_eval_example",
        "model_merge_example", "bert_finetuning_example", "nnunet_example",
        "feature_alignment_example", "dp_fed_examples/instance_level_dp",
        "dp_fed_examples/client_level_dp", "fenda_example", "perfcl_example",
        "fedrep_example", "gpfl_example", "ensemble_example",
        "fedsimclr_example", "dynamic_layer_exchange_example",
        "sparse_tensor_partial_exchange_example", "warm_up_example",
        "fedpca_example", "ae_examples/fedprox_vae_example",
        "ae_examples/cvae_example", "ae_examples/cvae_dim_example",
        "mkmmd_example", "cross_silo_example",
        "fl_plus_local_ft_example", "dp_fed_examples/dp_scaffold",
        "fenda_ditto_example", "fedllm_example", "nnunet_pfl_example",
        "long_context_example",
        "docker_basic_example",
    ]:
        assert required in names, f"examples/{required} missing from corpus"


@pytest.mark.slow
@pytest.mark.parametrize("script", ALL_RUN_SCRIPTS, ids=lambda p: str(p.parent))
def test_example_runs(script, monkeypatch, capsys):
    for k, v in TINY_ENV.items():
        monkeypatch.setenv(k, v)
    run_py = EXAMPLES_DIR / script
    # each example inserts its own paths; keep sys.path/modules hermetic
    old_path = list(sys.path)
    old_mods = set(sys.modules)
    old_cwd = os.getcwd()
    try:
        runpy.run_path(str(run_py), run_name="__main__")
    finally:
        sys.path[:] = old_path
        # Drop every module the example imported from under examples/ —
        # example-local helpers (e.g. _lib, docker's fl_nodes) must not leak
        # into the next example's import of a same-named file.
        for mod in set(sys.modules) - old_mods:
            mod_file = getattr(sys.modules.get(mod), "__file__", None) or ""
            if mod_file.startswith(str(EXAMPLES_DIR)):
                del sys.modules[mod]
        os.chdir(old_cwd)
    out = capsys.readouterr().out
    assert "{" in out, f"{script} produced no JSON report lines"
    assert "nan" not in out.lower().replace("final", ""), (
        f"{script} reported non-finite metrics:\n{out}"
    )

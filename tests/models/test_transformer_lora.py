"""Transformer + LoRA/PEFT tests: forward contract, adapter semantics,
freezing, wire filtering, and the federated LoRA + FedOpt config
(reference capability: examples/bert_finetuning_example,
examples/fedllm_example, utils/peft_parameter_extraction.py:7)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_text_classification
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.transformer import LoraDense, TransformerClassifier
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedopt import FedOpt
from fl4health_tpu.utils.peft import (
    lora_exchanger,
    lora_trainable_mask,
    masked_optimizer,
    peft_parameter_paths,
)

VOCAB, SEQ, CLASSES = 128, 16, 4


def small_model(**kw):
    defaults = dict(
        vocab_size=VOCAB, n_classes=CLASSES, d_model=32, n_heads=2,
        n_layers=2, d_ff=64, max_len=SEQ,
    )
    defaults.update(kw)
    return TransformerClassifier(**defaults)


class TestTransformer:
    def test_forward_shapes_and_contract(self):
        m = small_model()
        x, _ = synthetic_text_classification(jax.random.PRNGKey(0), 6, VOCAB, SEQ, CLASSES)
        variables = m.init(jax.random.PRNGKey(1), x, train=False)
        preds, feats = m.apply(variables, x, train=False)
        assert preds["prediction"].shape == (6, CLASSES)
        assert feats["features"].shape == (6, 32)

    def test_pad_positions_are_inert(self):
        """Trailing pads must not influence logits: the same tokens scored at
        full padded length and at their exact length agree (attention mask +
        masked mean-pool both screen the pads)."""
        m = small_model()
        tokens = [5, 6, 7, 8]
        x_padded = jnp.asarray([tokens + [0] * (SEQ - 4)], jnp.int32)
        x_exact = jnp.asarray([tokens], jnp.int32)
        variables = m.init(jax.random.PRNGKey(0), x_padded, train=False)
        out_padded, _ = m.apply(variables, x_padded, train=False)
        out_exact, _ = m.apply(variables, x_exact, train=False)
        np.testing.assert_allclose(
            np.asarray(out_padded["prediction"]),
            np.asarray(out_exact["prediction"]),
            atol=1e-5,
        )

    def test_bf16_compute_path(self):
        m = small_model(dtype=jnp.bfloat16)
        x, _ = synthetic_text_classification(jax.random.PRNGKey(0), 4, VOCAB, SEQ, CLASSES)
        variables = m.init(jax.random.PRNGKey(1), x, train=False)
        preds, _ = m.apply(variables, x, train=False)
        # params stay fp32 (mixed precision), logits come back fp32
        kernels = [
            p for p in jax.tree_util.tree_leaves(variables["params"]) if p.ndim == 2
        ]
        assert all(k.dtype == jnp.float32 for k in kernels)
        assert preds["prediction"].dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(preds["prediction"])))


class TestLora:
    def test_lora_b_zero_init_means_identity_at_start(self):
        """With lora_b = 0, the adapted layer equals the base layer."""
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 8))
        base = LoraDense(6, rank=0)
        lora = LoraDense(6, rank=2)
        vb = base.init(jax.random.PRNGKey(1), x)
        vl = lora.init(jax.random.PRNGKey(1), x)
        # same base kernel init (same rng), plus lora_a/lora_b
        assert set(vl["params"]) == {"kernel", "bias", "lora_a", "lora_b"}
        assert bool(jnp.all(vl["params"]["lora_b"] == 0))
        np.testing.assert_allclose(
            np.asarray(base.apply(vb, x)), np.asarray(lora.apply(vl, x)), atol=1e-6
        )

    def test_peft_paths_and_exchanger_filter(self):
        m = small_model(lora_rank=2)
        x = jnp.zeros((1, SEQ), jnp.int32)
        params = m.init(jax.random.PRNGKey(0), x, train=False)["params"]
        paths = peft_parameter_paths(params)
        assert paths, "must find adapter params"
        assert all(
            any(mk in p.split(".") for mk in ("lora_a", "lora_b", "classifier"))
            for p in paths
        )
        # the exchanger zeroes everything else on push
        ex = lora_exchanger()
        pushed = ex.push(params)
        flat = jax.tree_util.tree_flatten_with_path(pushed)[0]
        for key_path, leaf in flat:
            dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
            is_peft = any(
                mk in dotted.split(".") for mk in ("lora_a", "lora_b", "classifier")
            )
            if not is_peft:
                assert bool(jnp.all(leaf == 0)), f"{dotted} leaked onto the wire"

    @pytest.mark.slow
    def test_masked_optimizer_freezes_base_weights(self):
        m = small_model(lora_rank=2, n_layers=1)
        x, y = synthetic_text_classification(jax.random.PRNGKey(0), 8, VOCAB, SEQ, CLASSES)
        params = m.init(jax.random.PRNGKey(1), x, train=False)["params"]
        mask = lora_trainable_mask(params)
        tx = masked_optimizer(optax.adam(1e-2), mask)
        state = tx.init(params)

        def loss_fn(p):
            preds, _ = m.apply({"params": p}, x, train=False)
            return engine.masked_cross_entropy(
                preds["prediction"], y, jnp.ones((x.shape[0],))
            )

        grads = jax.grad(loss_fn)(params)
        updates, _ = tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)

        flat_old = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_new = jax.tree_util.tree_leaves(new_params)
        moved = frozen_moved = 0
        for (key_path, old), new in zip(flat_old, flat_new):
            dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
            changed = bool(jnp.any(old != new))
            is_trainable = any(
                mk in dotted.split(".") for mk in ("lora_a", "lora_b", "classifier")
            )
            if is_trainable and changed:
                moved += 1
            if not is_trainable and changed:
                frozen_moved += 1
        assert moved > 0, "adapters must train"
        assert frozen_moved == 0, "base weights must stay frozen"


class TestFederatedLora:
    def test_fedopt_lora_round_learns_and_keeps_base_frozen(self):
        """The bert_finetuning/fedllm capability: FedOpt server optimizer +
        LoRA-only exchange, 4 clients, AG-News-shaped synthetic data."""
        m = small_model(lora_rank=4)
        model = engine.from_flax(m)
        datasets = []
        for i in range(4):
            x, y = synthetic_text_classification(
                jax.random.PRNGKey(10 + i), 48, VOCAB, SEQ, CLASSES, class_sep=3.0
            )
            datasets.append(ClientDataset(x[:32], y[:32], x[32:], y[32:]))

        sample_x = datasets[0].x_train[:1]
        init_params = model.init(jax.random.PRNGKey(0), sample_x)[0]
        mask = lora_trainable_mask(init_params)
        logic = engine.ClientLogic(model, engine.masked_cross_entropy)
        sim = FederatedSimulation(
            logic=logic,
            tx=masked_optimizer(optax.adam(1e-2), mask),
            strategy=FedOpt(optax.adam(1e-2)),
            datasets=datasets,
            batch_size=8,
            metrics=MetricManager((efficient.accuracy(),)),
            local_steps=8,
            seed=3,
            exchanger=lora_exchanger(),
        )
        base_before = jax.device_get(
            sim.client_states.params["layer_0"]["attn"]["q_proj"]["kernel"]
        )
        history = sim.fit(5)
        base_after = jax.device_get(
            sim.client_states.params["layer_0"]["attn"]["q_proj"]["kernel"]
        )
        np.testing.assert_allclose(base_before, base_after, atol=1e-7)
        assert history[-1].fit_losses["backward"] < history[0].fit_losses["backward"]
        assert history[-1].eval_metrics["accuracy"] > 0.3  # 0.25 = chance


class TestRemat:
    @pytest.mark.slow
    def test_remat_gradients_match_unremat(self):
        # remat=True must be a pure memory/FLOPs trade: same params tree,
        # same gradients (jax.checkpoint recomputes, never changes math)
        from jax.flatten_util import ravel_pytree

        a, b = small_model(), small_model(remat=True)
        x, _ = synthetic_text_classification(
            jax.random.PRNGKey(2), 4, VOCAB, SEQ, CLASSES
        )
        v = a.init(jax.random.PRNGKey(3), x, train=False)

        def sq(model):
            return jax.grad(lambda p: jnp.sum(jnp.square(
                model.apply(p, x, train=False)[0]["prediction"])))(v)

        fa = ravel_pytree(sq(a))[0]
        fb = ravel_pytree(sq(b))[0]
        np.testing.assert_allclose(np.asarray(fa), np.asarray(fb),
                                   atol=1e-5, rtol=1e-5)

"""Unit tests for split-architecture model bases (reference:
tests/model_bases/)."""

import jax
import jax.numpy as jnp

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.models import bases


def _init_apply(module, x, **kwargs):
    variables = module.init(jax.random.PRNGKey(0), x, **kwargs)
    out = module.apply(variables, x, **kwargs)
    return variables, out


def test_sequentially_split_model_shapes_and_predicate():
    m = bases.SequentiallySplitModel(
        features_module=bases.DenseFeatures((16,)),
        head_module=bases.DenseHead(4),
    )
    x = jnp.ones((2, 8))
    variables, (preds, feats) = _init_apply(m, x)
    assert preds["prediction"].shape == (2, 4)
    assert feats["features"].shape == (2, 16)
    paths = ptu.leaf_paths(variables["params"])
    shared = [p for p in paths if bases.SequentiallySplitModel.exchange_features_only(p)]
    assert shared and all(p.startswith("features_module") for p in shared)
    private = [p for p in paths if not bases.SequentiallySplitModel.exchange_features_only(p)]
    assert private and all(p.startswith("head_module") for p in private)


def test_parallel_split_join_modes():
    for mode, dim in [(bases.JoinMode.CONCATENATE, 32), (bases.JoinMode.SUM, 16)]:
        m = bases.ParallelSplitModel(
            first_feature_extractor=bases.DenseFeatures((16,)),
            second_feature_extractor=bases.DenseFeatures((16,)),
            head_module=bases.HeadModule(head=bases.DenseHead(3), join_mode=mode),
        )
        x = jnp.ones((2, 8))
        variables, (preds, feats) = _init_apply(m, x)
        assert preds["prediction"].shape == (2, 3)
        assert feats["local_features"].shape == (2, 16)
        assert feats["global_features"].shape == (2, 16)
    # FENDA predicate exchanges exactly the second extractor
    paths = ptu.leaf_paths(variables["params"])
    ex = [p for p in paths if bases.ParallelSplitModel.exchange_global_extractor(p)]
    assert ex and all(p.startswith("second_feature_extractor") for p in ex)


def test_apfl_module_alpha_mixing():
    m = bases.ApflModule(
        local_model=bases.DenseHead(3), global_model=bases.DenseHead(3)
    )
    x = jnp.ones((2, 8))
    variables = m.init(jax.random.PRNGKey(0), x, alpha=jnp.asarray(0.5))
    for alpha in (0.0, 1.0):
        preds, _ = m.apply(variables, x, alpha=jnp.asarray(alpha))
        ref = preds["global"] if alpha == 0.0 else preds["local"]
        assert jnp.allclose(preds["personal"], ref)


def test_twin_model_structure():
    m = bases.TwinModel(
        global_model=bases.DenseHead(3), personal_model=bases.DenseHead(3)
    )
    x = jnp.ones((2, 8))
    variables, (preds, _) = _init_apply(m, x)
    assert set(variables["params"].keys()) == {"global_model", "personal_model"}
    assert preds["prediction"].shape == (2, 3)
    assert jnp.allclose(preds["prediction"], preds["personal"])


def test_moon_model_projection():
    m = bases.MoonModel(
        base_module=bases.DenseFeatures((16,)),
        head_module=bases.DenseHead(3),
        projection_module=bases.DenseFeatures((8,)),
    )
    x = jnp.ones((2, 10))
    _, (preds, feats) = _init_apply(m, x)
    assert feats["features"].shape == (2, 8)  # projected
    assert preds["prediction"].shape == (2, 3)


def test_gpfl_model_outputs():
    m = bases.GpflModel(
        base_module=bases.DenseFeatures((16,)), n_classes=5, feature_dim=12
    )
    x = jnp.ones((3, 8))
    variables = m.init(jax.random.PRNGKey(0), x)
    preds, feats = m.apply(
        variables, x, p_cond=jnp.ones((12,)), g_cond=jnp.zeros((12,))
    )
    assert preds["prediction"].shape == (3, 5)
    assert preds["gce_logits"].shape == (3, 5)
    assert feats["gce_embeddings"].shape == (5, 12)
    # cosine logits bounded
    assert float(jnp.max(jnp.abs(preds["gce_logits"]))) <= 1.0 + 1e-5
    paths = ptu.leaf_paths(variables["params"])
    private = [p for p in paths if not bases.GpflModel.exchange_shared(p)]
    assert private and all(p.startswith("head") for p in private)


def test_ensemble_model_average():
    m = bases.EnsembleModel(members=(bases.DenseHead(3), bases.DenseHead(3)))
    x = jnp.ones((2, 8))
    _, (preds, _) = _init_apply(m, x)
    avg = (preds["ensemble-pred-0"] + preds["ensemble-pred-1"]) / 2.0
    assert jnp.allclose(preds["prediction"], avg)


def test_fedsimclr_modes():
    enc = bases.DenseFeatures((16,))
    proj = bases.DenseFeatures((8,))
    head = bases.DenseHead(3)
    pre = bases.FedSimClrModel(encoder=enc, projection_head=proj,
                               prediction_head=head, pretrain=True)
    x = jnp.ones((2, 10))
    _, (preds, _) = _init_apply(pre, x)
    assert preds["prediction"].shape == (2, 8)
    ft = bases.FedSimClrModel(encoder=enc, projection_head=proj,
                              prediction_head=head, pretrain=False)
    _, (preds, _) = _init_apply(ft, x)
    assert preds["prediction"].shape == (2, 3)

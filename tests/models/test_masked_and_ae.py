"""Masked layers, autoencoders, PCA module tests (reference:
tests/model_bases/test_masked_layers.py, test_autoencoders.py, test_pca.py)."""

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fl4health_tpu.models.autoencoders import (
    BasicAe,
    ConditionalVae,
    PcaModule,
    VariationalAe,
    kl_to_standard_normal,
    make_vae_loss,
    unpack_vae_output,
)
from fl4health_tpu.models.masked import (
    MaskedBatchNorm,
    MaskedConv,
    MaskedDense,
    MaskedLayerNorm,
    MaskedMlp,
    bernoulli_ste,
    transplant_dense_weights,
)


# ---------------------------------------------------------------------------
# Masked layers
# ---------------------------------------------------------------------------

def test_bernoulli_ste_straight_through_gradient():
    probs = jnp.asarray([0.2, 0.8, 0.5])
    rng = jax.random.PRNGKey(0)
    g = jax.grad(lambda p: jnp.sum(bernoulli_ste(p, rng) * jnp.asarray([1.0, 2.0, 3.0])))(probs)
    # backward = probs * upstream (utils/functions.py:35-39)
    assert np.allclose(np.asarray(g), np.asarray(probs * jnp.asarray([1.0, 2.0, 3.0])))


def test_masked_dense_samples_masks_and_freezes_weights():
    layer = MaskedDense(4)
    x = jnp.ones((2, 3))
    variables = layer.init({"params": jax.random.PRNGKey(0), "mask": jax.random.PRNGKey(1)}, x)
    assert "kernel_scores" in variables["params"]
    assert "kernel" in variables["frozen"]
    # With the mask rng: stochastic binary masking.
    y1 = layer.apply(variables, x, rngs={"mask": jax.random.PRNGKey(2)})
    y2 = layer.apply(variables, x, rngs={"mask": jax.random.PRNGKey(3)})
    assert y1.shape == (2, 4)
    # Without the rng: deterministic expectation.
    y_det = layer.apply(variables, x)
    y_det2 = layer.apply(variables, x)
    assert np.allclose(np.asarray(y_det), np.asarray(y_det2))
    # Gradients flow to scores only; frozen kernel has no params entry.
    def loss(params):
        return jnp.sum(layer.apply({"params": params, "frozen": variables["frozen"]},
                                   x, rngs={"mask": jax.random.PRNGKey(4)}) ** 2)
    g = jax.grad(loss)(variables["params"])
    assert float(jnp.max(jnp.abs(g["kernel_scores"]))) > 0.0


def test_masked_conv_and_norms_forward():
    x = jnp.ones((2, 8, 8, 3))
    conv = MaskedConv(5, (3, 3))
    v = conv.init({"params": jax.random.PRNGKey(0), "mask": jax.random.PRNGKey(1)}, x)
    y = conv.apply(v, x, rngs={"mask": jax.random.PRNGKey(2)})
    assert y.shape == (2, 8, 8, 5)

    ln = MaskedLayerNorm()
    v = ln.init({"params": jax.random.PRNGKey(0), "mask": jax.random.PRNGKey(1)}, y)
    out = ln.apply(v, y, rngs={"mask": jax.random.PRNGKey(2)})
    assert out.shape == y.shape

    bn = MaskedBatchNorm()
    v = bn.init({"params": jax.random.PRNGKey(0), "mask": jax.random.PRNGKey(1)}, y)
    out, updated = bn.apply(v, y, rngs={"mask": jax.random.PRNGKey(2)},
                            mutable=["batch_stats"])
    assert out.shape == y.shape
    assert "mean" in updated["batch_stats"]


def test_transplant_dense_weights():
    from fl4health_tpu.models.cnn import Mlp
    dense = Mlp(features=(8,), n_outputs=3)
    x = jnp.ones((2, 5))
    dense_params = dense.init(jax.random.PRNGKey(0), x)["params"]
    masked = MaskedMlp(features=(8,), n_outputs=3)
    mv = masked.init({"params": jax.random.PRNGKey(1), "mask": jax.random.PRNGKey(2)}, x)
    frozen = transplant_dense_weights(dense_params, mv["frozen"])
    # Every dense layer's weights actually landed in the masked twin's frozen
    # collection (Dense_i -> MaskedDense_i via class-prefix normalization).
    src = sorted(np.asarray(l).sum() for l in jax.tree_util.tree_leaves(dense_params))
    dst = sorted(np.asarray(l).sum() for l in jax.tree_util.tree_leaves(frozen))
    assert np.allclose(src, dst)
    before = sorted(np.asarray(l).sum() for l in jax.tree_util.tree_leaves(mv["frozen"]))
    assert not np.allclose(src, before)  # init values were really replaced


# ---------------------------------------------------------------------------
# Autoencoders
# ---------------------------------------------------------------------------

class _Enc(nn.Module):
    latent: int = 4

    @nn.compact
    def __call__(self, x, train=True):
        x = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.latent)(h)


class _VEnc(nn.Module):
    latent: int = 4

    @nn.compact
    def __call__(self, x, train=True):
        x = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.latent)(h), nn.Dense(self.latent)(h)


class _CEnc(nn.Module):
    latent: int = 4

    @nn.compact
    def __call__(self, x, cond, train=True):
        x = jnp.concatenate([x.reshape((x.shape[0], -1)), cond], axis=1)
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.latent)(h), nn.Dense(self.latent)(h)


class _Dec(nn.Module):
    out_dim: int = 6

    @nn.compact
    def __call__(self, z, train=True):
        return nn.Dense(self.out_dim)(nn.relu(nn.Dense(16)(z)))


class _CDec(nn.Module):
    out_dim: int = 6

    @nn.compact
    def __call__(self, z, cond, train=True):
        z = jnp.concatenate([z, cond], axis=1)
        return nn.Dense(self.out_dim)(nn.relu(nn.Dense(16)(z)))


def test_basic_ae_roundtrip_shapes():
    model = BasicAe(encoder=_Enc(), decoder=_Dec())
    x = jnp.ones((3, 6))
    v = model.init(jax.random.PRNGKey(0), x)
    (preds, feats), _ = model.apply(v, x), None
    assert preds["prediction"].shape == (3, 6)
    assert feats["latent"].shape == (3, 4)


def test_vae_packed_output_and_loss():
    latent = 4
    model = VariationalAe(encoder=_VEnc(latent), decoder=_Dec(6))
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 6))
    v = model.init({"params": jax.random.PRNGKey(0), "sampling": jax.random.PRNGKey(1)}, x)
    (preds, feats) = model.apply(v, x, rngs={"sampling": jax.random.PRNGKey(2)})
    packed = preds["prediction"]
    assert packed.shape == (5, 2 * latent + 6)  # [logvar | mu | flat recon]
    recon, mu, logvar = unpack_vae_output(packed, latent)
    assert np.allclose(np.asarray(mu), np.asarray(feats["mu"]))
    assert np.allclose(np.asarray(logvar), np.asarray(feats["logvar"]))

    def mse(preds_, targets_, mask_):
        return jnp.sum(((preds_ - targets_) ** 2) * mask_[:, None]) / jnp.maximum(jnp.sum(mask_), 1.0)

    criterion = make_vae_loss(latent, mse)
    loss = criterion(packed, x, jnp.ones(5))
    assert np.isfinite(float(loss))
    # KL of a standard normal estimate is >= 0
    assert float(kl_to_standard_normal(mu, logvar)) >= -1e-5 or True


def test_conditional_vae_uses_condition():
    latent = 4
    from fl4health_tpu.preprocessing.autoencoders import AutoEncoderDatasetConverter

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 6))
    y = jnp.arange(8) % 3
    converter = AutoEncoderDatasetConverter(condition="label", do_one_hot_encoding=True)
    packed_x, target = converter.convert_dataset(x, y)
    assert packed_x.shape == (8, 6 + 3)
    unpack = converter.get_unpacking_function()
    data, cond = unpack(packed_x)
    assert data.shape == (8, 6)
    assert cond.shape == (8, 3)

    model = ConditionalVae(encoder=_CEnc(latent), decoder=_CDec(6),
                           unpack_input_condition=unpack)
    v = model.init({"params": jax.random.PRNGKey(0), "sampling": jax.random.PRNGKey(1)},
                   packed_x)
    (preds, _) = model.apply(v, packed_x, rngs={"sampling": jax.random.PRNGKey(2)})
    assert preds["prediction"].shape == (8, 2 * latent + 6)


def test_converter_fixed_condition_and_none():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 3))
    y = jnp.arange(4)
    from fl4health_tpu.preprocessing.autoencoders import AutoEncoderDatasetConverter
    conv = AutoEncoderDatasetConverter(condition=None)
    px, target = conv.convert_dataset(x, y)
    assert px.shape == x.shape and np.allclose(np.asarray(target), np.asarray(x))
    conv2 = AutoEncoderDatasetConverter(condition=jnp.asarray([1.0, 2.0]))
    px2, _ = conv2.convert_dataset(x, y)
    assert px2.shape == (4, 6 + 2)
    data, cond = conv2.get_unpacking_function()(px2)
    assert data.shape == (4, 2, 3)
    assert np.allclose(np.asarray(cond[0]), [1.0, 2.0])


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

def test_pca_projection_and_variance():
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 10))
    pca = PcaModule()
    state = pca.fit(x, center_data=True)
    ratios = pca.explained_variance_ratios(state)
    assert np.isclose(float(jnp.sum(ratios)), 1.0, atol=1e-5)
    # More components -> lower reconstruction error.
    err2 = float(pca.reconstruction_error(state, x, k=2, center_data=True))
    err8 = float(pca.reconstruction_error(state, x, k=8, center_data=True))
    assert err8 < err2
    # Full-rank reconstruction is exact.
    err_full = float(pca.reconstruction_error(state, x, k=None, center_data=True))
    assert err_full < 1e-6
    low = pca.project_lower_dim(state, x, k=3, center_data=True)
    assert low.shape == (32, 3)
    back = pca.project_back(state, low, add_mean=True)
    assert back.shape == (32, 10)


def test_pca_low_rank_truncation():
    x = jax.random.normal(jax.random.PRNGKey(0), (20, 12))
    pca = PcaModule(low_rank=True, rank_estimation=5)
    state = pca.fit(x)
    assert state.components.shape == (12, 5)
    assert state.singular_values.shape == (5,)

"""MxuConv (im2col + matmul) must be a drop-in for nn.Conv: identical param
trees and initial values, matching outputs and gradients, and agreement
under the per-client-weights vmap that motivates it (the cohort engine's
grouped-conv hazard, BENCH_r03 note)."""

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fl4health_tpu.models.cnn import CifarNet, MxuConv


def _inputs(b=4, hw=16, c=3, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (b, hw, hw, c))


class TestMxuConvParity:
    def test_same_params_same_output(self):
        x = _inputs()
        ref = nn.Conv(8, (5, 5))
        mxu = MxuConv(8, (5, 5))
        params = ref.init(jax.random.PRNGKey(1), x)
        out_ref = ref.apply(params, x)
        out_mxu = mxu.apply(params, x)  # identical param shapes/names
        np.testing.assert_allclose(
            np.asarray(out_mxu), np.asarray(out_ref), rtol=1e-5, atol=1e-5
        )

    def test_gradients_match(self):
        x = _inputs(seed=2)
        ref = nn.Conv(8, (3, 3))
        mxu = MxuConv(8, (3, 3))
        params = ref.init(jax.random.PRNGKey(1), x)

        def loss(m, p):
            return jnp.sum(m.apply(p, x) ** 2)

        g_ref = jax.grad(lambda p: loss(ref, p))(params)
        g_mxu = jax.grad(lambda p: loss(mxu, p))(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_mxu),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_vmapped_per_client_weights_agree(self):
        """The motivating case: a [clients] axis on the WEIGHTS. The im2col
        path must agree with the grouped-conv lowering it replaces."""
        x = _inputs(b=2, hw=8)
        ref = nn.Conv(4, (3, 3))
        mxu = MxuConv(4, (3, 3))
        stack = jax.vmap(lambda k: ref.init(k, x))(
            jax.random.split(jax.random.PRNGKey(0), 3)
        )
        out_ref = jax.vmap(lambda p: ref.apply(p, x))(stack)
        out_mxu = jax.vmap(lambda p: mxu.apply(p, x))(stack)
        np.testing.assert_allclose(
            np.asarray(out_mxu), np.asarray(out_ref), rtol=1e-5, atol=1e-5
        )

    def test_cifarnet_impls_share_init_and_agree(self):
        """conv_impl must not change the param tree, the RNG-keyed init
        values, or (within float tolerance) the forward outputs."""
        x = _inputs(b=2, hw=32, c=3)
        lax_net = CifarNet()
        mxu_net = CifarNet(conv_impl="mxu")
        v_lax = lax_net.init(jax.random.PRNGKey(3), x, train=False)
        v_mxu = mxu_net.init(jax.random.PRNGKey(3), x, train=False)
        assert (jax.tree_util.tree_structure(v_lax)
                == jax.tree_util.tree_structure(v_mxu))
        for a, b in zip(jax.tree_util.tree_leaves(v_lax),
                        jax.tree_util.tree_leaves(v_mxu)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        p_lax, _ = lax_net.apply(v_lax, x, train=False)
        p_mxu, _ = mxu_net.apply(v_lax, x, train=False)
        np.testing.assert_allclose(
            np.asarray(p_mxu["prediction"]), np.asarray(p_lax["prediction"]),
            rtol=1e-4, atol=1e-4,
        )

    def test_valid_padding(self):
        x = _inputs(b=2, hw=10, c=2, seed=5)
        ref = nn.Conv(6, (3, 3), padding="VALID")
        mxu = MxuConv(6, (3, 3), padding="VALID")
        params = ref.init(jax.random.PRNGKey(1), x)
        np.testing.assert_allclose(
            np.asarray(mxu.apply(params, x)), np.asarray(ref.apply(params, x)),
            rtol=1e-5, atol=1e-5,
        )


class TestUnetConvImpl:
    def test_unet_impls_share_tree_and_agree(self):
        """conv_impl="mxu" on the real U-Net: identical param structure AND
        initial values (same paths -> same RNG folds), forward agreement —
        the property that makes the impl switchable per deployment (sharded
        cohorts need mxu; see test_sharded_mesh.py)."""
        from fl4health_tpu.models.unet import PlainConvUNet

        kwargs = dict(
            features_per_stage=(8, 16),
            strides=((1, 1, 1), (2, 2, 2)),
            kernel_sizes=((3, 3, 3), (3, 3, 3)),
            n_classes=2,
            n_conv_per_stage=2,
            deep_supervision=True,
        )
        lax_net = PlainConvUNet(**kwargs)
        mxu_net = PlainConvUNet(conv_impl="mxu", **kwargs)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 8, 1))
        v_lax = lax_net.init(jax.random.PRNGKey(1), x, train=False)
        v_mxu = mxu_net.init(jax.random.PRNGKey(1), x, train=False)
        assert (jax.tree_util.tree_structure(v_lax)
                == jax.tree_util.tree_structure(v_mxu))
        for a, b in zip(jax.tree_util.tree_leaves(v_lax),
                        jax.tree_util.tree_leaves(v_mxu)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        p_lax, _ = lax_net.apply(v_lax, x, train=False)
        p_mxu, _ = mxu_net.apply(v_lax, x, train=False)
        for k in p_lax:
            np.testing.assert_allclose(
                np.asarray(p_mxu[k]), np.asarray(p_lax[k]),
                rtol=5e-4, atol=5e-4,
            )

    def test_strided_mxu_conv_matches_lax(self):
        from flax import linen as nn

        x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8, 2))
        ref = nn.Conv(4, (3, 3), strides=(2, 2))
        mxu = MxuConv(4, (3, 3), strides=(2, 2))
        params = ref.init(jax.random.PRNGKey(3), x)
        np.testing.assert_allclose(
            np.asarray(mxu.apply(params, x)),
            np.asarray(ref.apply(params, x)), rtol=1e-5, atol=1e-5,
        )


class TestPromotionRuleAndAuto:
    def test_bf16_parity_under_engine_cast_rule(self):
        """The engine-side precision cast hands BOTH impls bf16 inputs and
        bf16 params; the shared promotion rule (precision.policy
        .conv_compute_dtype) must then make lax and mxu compute — and
        emit — the same bf16 values."""
        from fl4health_tpu.precision.policy import cast_floats

        x = _inputs(b=2, hw=8).astype(jnp.bfloat16)
        ref = nn.Conv(4, (3, 3))
        mxu = MxuConv(4, (3, 3))
        params = cast_floats(
            ref.init(jax.random.PRNGKey(1), _inputs(b=2, hw=8)), jnp.bfloat16
        )
        out_ref = ref.apply(params, x)
        out_mxu = mxu.apply(params, x)
        assert out_ref.dtype == out_mxu.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out_mxu, np.float32), np.asarray(out_ref, np.float32),
            rtol=2e-2, atol=2e-2,
        )

    def test_dtype_none_promotion_includes_bias(self):
        """dtype=None follows flax's promote_dtype over input AND params
        (bias included) — bf16 input against f32 params promotes to f32 in
        BOTH impls, so they stay interchangeable under partial casts."""
        x = _inputs(b=2, hw=8).astype(jnp.bfloat16)
        ref = nn.Conv(4, (3, 3))
        mxu = MxuConv(4, (3, 3))
        params = ref.init(jax.random.PRNGKey(1), _inputs(b=2, hw=8))  # f32
        out_ref = ref.apply(params, x)
        out_mxu = mxu.apply(params, x)
        assert out_ref.dtype == out_mxu.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(out_mxu), np.asarray(out_ref), rtol=1e-3, atol=1e-3
        )

    def test_resolve_conv_impl_auto(self):
        from fl4health_tpu.models.cnn import make_conv, resolve_conv_impl

        # "mxu" only where the grouped-conv partitioner rejects the
        # vmapped nn.Conv: clients-sharded meshes. "lax" everywhere else
        # (the measured TPU A/B in the MxuConv docstring).
        assert resolve_conv_impl("auto") == "lax"
        assert resolve_conv_impl("auto", sharded_clients=True) == "mxu"
        assert resolve_conv_impl("lax", sharded_clients=True) == "lax"
        assert resolve_conv_impl("mxu") == "mxu"
        try:
            resolve_conv_impl("im2col")
            raise AssertionError("unknown impl must raise")
        except ValueError:
            pass
        # make_conv accepts "auto" (module-level default: unsharded)
        assert isinstance(make_conv("auto", 4, (3, 3)), nn.Conv)

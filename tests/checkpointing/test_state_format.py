"""Versioned checkpoint-frame tests: CRC32 footer, retention ring,
corrupt-generation fallback, config binding, legacy compatibility, and the
DataclassListSnapshotter record-class header."""

import json
import os
import zlib

import numpy as np
import pytest

from fl4health_tpu.checkpointing.state import (
    CheckpointConfigMismatchError,
    CheckpointCorruptError,
    DataclassListSnapshotter,
    StateCheckpointer,
)
from fl4health_tpu.server.simulation import RoundRecord

TREES = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
         "nested": {"b": np.float32(3.5)}}
TEMPLATES = {"w": np.zeros((2, 3), np.float32),
             "nested": {"b": np.float32(0.0)}}


def _save(ck, value=0.0, rnd=1):
    trees = {"w": TREES["w"] + value, "nested": {"b": np.float32(value)}}
    return ck.save(trees, host={"round": rnd}, extra_meta={"round": rnd})


class TestFrameFormat:
    def test_roundtrip_trees_host_and_meta(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), config_hash="abc123")
        stats = _save(ck, 2.0, rnd=7)
        assert stats["generation"] == 1
        assert stats["bytes"] == os.path.getsize(stats["path"])
        trees, host, info = ck.load_with_info(TEMPLATES, {"round": 0})
        np.testing.assert_array_equal(trees["w"], TREES["w"] + 2.0)
        assert host["round"] == 7
        assert info.meta["config_hash"] == "abc123"
        assert info.meta["format_version"] == 1
        assert info.generation == 1
        assert info.fallback_skipped == []

    def test_crc_covers_the_whole_body(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path))
        path = _save(ck)["path"]
        with open(path, "rb") as f:
            data = f.read()
        body, crc = data[:-4], int.from_bytes(data[-4:], "big")
        assert (zlib.crc32(body) & 0xFFFFFFFF) == crc

    def test_legacy_frame_still_loads(self, tmp_path):
        """Pre-ring checkpoints ([8B len][header][blob], no magic/CRC) load
        as format version 0."""
        from flax import serialization

        legacy = tmp_path / "state.ckpt"
        header = json.dumps({"round": 3}).encode()
        blob = serialization.to_bytes(dict(TREES))
        legacy.write_bytes(len(header).to_bytes(8, "big") + header + blob)
        ck = StateCheckpointer(str(tmp_path))
        assert ck.exists()
        trees, host, info = ck.load_with_info(TEMPLATES, {"round": 0})
        assert host["round"] == 3
        assert info.generation == 0
        assert info.meta["format_version"] == 0
        np.testing.assert_array_equal(trees["w"], TREES["w"])

    def test_newer_format_version_is_a_typed_error(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=1)
        path = _save(ck)["path"]
        data = bytearray(open(path, "rb").read())
        data[8:12] = (99).to_bytes(4, "big")  # bump the version field
        body = bytes(data[:-4])
        data[-4:] = (zlib.crc32(body) & 0xFFFFFFFF).to_bytes(4, "big")
        open(path, "wb").write(bytes(data))
        with pytest.raises(CheckpointCorruptError, match="version 99"):
            ck.load(TEMPLATES)


class TestCorruptionDetection:
    def test_truncation_raises_typed_error_naming_the_file(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=1)
        path = _save(ck)["path"]
        data = open(path, "rb").read()
        open(path, "wb").write(data[:len(data) // 2])
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.load(TEMPLATES)
        assert path in str(ei.value)
        assert ei.value.path == path

    def test_bit_flip_caught_by_crc(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=1)
        path = _save(ck)["path"]
        data = bytearray(open(path, "rb").read())
        i = len(data) // 2
        data[i] ^= 0xFF
        open(path, "wb").write(bytes(data))
        with pytest.raises(CheckpointCorruptError, match="CRC32"):
            ck.load(TEMPLATES)

    def test_tiny_torn_file_is_corrupt_not_a_crash(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=1)
        path = _save(ck)["path"]
        open(path, "wb").write(b"FL4HCKPT\x00")
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            ck.load(TEMPLATES)


class TestRetentionRing:
    def test_ring_keeps_last_k_with_monotonic_generations(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=3)
        for r in range(1, 6):
            _save(ck, float(r), rnd=r)
        gens = ck.generations()
        assert [g for g, _ in gens] == [3, 4, 5]
        trees, host = ck.load(TEMPLATES, {"round": 0})
        assert host["round"] == 5
        np.testing.assert_array_equal(trees["w"], TREES["w"] + 5.0)

    def test_corrupt_newest_falls_back_to_previous_good(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=3)
        for r in (1, 2, 3):
            _save(ck, float(r), rnd=r)
        newest = ck.candidate_paths()[0][1]
        data = open(newest, "rb").read()
        open(newest, "wb").write(data[:100])  # torn tail
        trees, host, info = ck.load_with_info(TEMPLATES, {"round": 0})
        assert host["round"] == 2  # the previous generation won
        np.testing.assert_array_equal(trees["w"], TREES["w"] + 2.0)
        assert info.fallback_skipped == [newest]

    def test_all_generations_corrupt_raises_newest_error(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=2)
        _save(ck, 1.0)
        _save(ck, 2.0)
        paths = [p for _g, p in ck.candidate_paths()]
        for p in paths:
            open(p, "wb").write(b"garbage")
        with pytest.raises(CheckpointCorruptError) as ei:
            ck.load(TEMPLATES)
        assert ei.value.path == paths[0]

    def test_keep_one_has_no_fallback_but_still_detects(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=1)
        _save(ck, 1.0)
        _save(ck, 2.0)
        assert len(ck.generations()) == 1
        newest = ck.candidate_paths()[0][1]
        open(newest, "wb").write(b"garbage")
        with pytest.raises(CheckpointCorruptError):
            ck.load(TEMPLATES)

    def test_clear_removes_every_generation(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path), keep=3)
        _save(ck, 1.0)
        _save(ck, 2.0)
        assert ck.exists()
        ck.clear()
        assert not ck.exists()

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError, match="keep"):
            StateCheckpointer(str(tmp_path), keep=0)
        with pytest.raises(ValueError, match="checkpoint_every"):
            StateCheckpointer(str(tmp_path), checkpoint_every=0)


class TestConfigBinding:
    def test_mismatched_config_hash_rejected(self, tmp_path):
        writer = StateCheckpointer(str(tmp_path), config_hash="exp-A")
        _save(writer)
        reader = StateCheckpointer(str(tmp_path), config_hash="exp-B")
        with pytest.raises(CheckpointConfigMismatchError, match="exp-A"):
            reader.load(TEMPLATES, expected_config_hash="exp-B")

    def test_matching_or_absent_hash_accepted(self, tmp_path):
        writer = StateCheckpointer(str(tmp_path), config_hash="exp-A")
        _save(writer)
        reader = StateCheckpointer(str(tmp_path))
        reader.load(TEMPLATES, expected_config_hash="exp-A")  # match
        reader.load(TEMPLATES)  # no expectation: legacy callers
        # legacy frames (no stored hash) never hard-fail the check
        unhashed = StateCheckpointer(str(tmp_path / "u"))
        _save(unhashed)
        unhashed.load(TEMPLATES, expected_config_hash="anything")


class TestOnSaveHook:
    def test_stats_reported_and_hook_failure_swallowed(self, tmp_path):
        seen = []

        def hook(stats):
            seen.append(stats)
            raise RuntimeError("metrics hook bug")  # must not kill the save

        ck = StateCheckpointer(str(tmp_path), on_save=hook)
        stats = _save(ck, rnd=4)
        assert os.path.exists(stats["path"])
        assert seen[0]["generation"] == 1
        assert seen[0]["round"] == 4
        assert seen[0]["bytes"] > 0
        assert seen[0]["write_s"] >= 0


class TestDataclassListSnapshotter:
    RECORDS = [
        RoundRecord(1, {"backward": 0.5}, {}, {"checkpoint": 0.4}, {},
                    1.0, 0.1),
        RoundRecord(2, {"backward": 0.3}, {}, {"checkpoint": 0.2}, {},
                    1.1, 0.1),
    ]

    def test_empty_template_restores_real_records(self, tmp_path):
        """THE satellite fix: a non-empty payload loaded against an empty
        template must come back as RoundRecords (class name rides the
        header), never raw dicts."""
        snap = DataclassListSnapshotter()
        payload = json.loads(json.dumps(snap.save(self.RECORDS)))
        restored = snap.load(payload, [])
        assert all(isinstance(r, RoundRecord) for r in restored)
        assert restored == self.RECORDS

    def test_legacy_bare_list_payload_with_template(self):
        snap = DataclassListSnapshotter()
        legacy_payload = [dataclasses_asdict(r) for r in self.RECORDS]
        restored = snap.load(legacy_payload, [RoundRecord(0, {}, {}, {}, {},
                                                          0.0, 0.0)])
        assert restored == self.RECORDS

    def test_legacy_bare_list_without_template_degrades_to_dicts(self):
        snap = DataclassListSnapshotter()
        legacy_payload = [dataclasses_asdict(r) for r in self.RECORDS]
        restored = snap.load(legacy_payload, [])
        assert isinstance(restored[0], dict)

    def test_unresolvable_class_degrades_to_dicts(self):
        snap = DataclassListSnapshotter()
        payload = {"rows": [{"a": 1}], "record_class": "no.such.module:X"}
        assert snap.load(payload, []) == [{"a": 1}]

    def test_empty_everything(self):
        snap = DataclassListSnapshotter()
        assert snap.load(None, []) == []
        assert snap.load({"rows": []}, []) == []
        assert snap.load([], []) == []

    def test_full_frame_roundtrip_with_empty_template(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path))
        ck.save({"w": np.zeros(2, np.float32)},
                host={"history": self.RECORDS},
                snapshotters={"history": DataclassListSnapshotter()})
        _trees, host = ck.load(
            {"w": np.zeros(2, np.float32)}, {"history": []},
            snapshotters={"history": DataclassListSnapshotter()},
        )
        assert host["history"] == self.RECORDS
        assert all(isinstance(r, RoundRecord) for r in host["history"])


def dataclasses_asdict(r):
    import dataclasses

    return dataclasses.asdict(r)


class TestOrphanTmpCleanup:
    def test_save_sweeps_mid_write_litter(self, tmp_path):
        """A SIGKILL mid-write leaves `<frame>.tmp.<pid>` litter that
        atomic_write cannot unlink; the next successful save prunes it
        (and clear() does too) so a preemptible job's checkpoint dir
        cannot grow without bound."""
        ck = StateCheckpointer(str(tmp_path), keep=2)
        _save(ck, 1.0)
        orphan = tmp_path / "state.g00000099.ckpt.tmp.12345"
        orphan.write_bytes(b"torn")
        legacy_orphan = tmp_path / "state.ckpt.tmp.777"
        legacy_orphan.write_bytes(b"torn")
        unrelated = tmp_path / "notes.txt"
        unrelated.write_text("keep me")
        _save(ck, 2.0)
        assert not orphan.exists()
        assert not legacy_orphan.exists()
        assert unrelated.exists()

    def test_clear_removes_orphans_too(self, tmp_path):
        ck = StateCheckpointer(str(tmp_path))
        _save(ck, 1.0)
        orphan = tmp_path / "state.g00000002.ckpt.tmp.1"
        orphan.write_bytes(b"torn")
        ck.clear()
        assert not ck.exists()
        assert not orphan.exists()

"""AsyncCheckpointWriter: ordered off-thread persists, flush durability,
exception propagation, and the ParamsCheckpointer._persist routing."""

import numpy as np
import pytest

from fl4health_tpu.checkpointing.async_writer import AsyncCheckpointWriter
from fl4health_tpu.checkpointing.checkpointer import (
    BestLossCheckpointer,
    LatestCheckpointer,
    load_params,
)


def _params(v: float):
    return {"w": np.full((3,), v, np.float32)}


def test_submit_save_is_durable_after_flush(tmp_path):
    w = AsyncCheckpointWriter()
    path = str(tmp_path / "p.msgpack")
    w.submit_save(path, _params(1.5))
    w.flush()
    loaded = load_params(path, _params(0.0))
    np.testing.assert_allclose(loaded["w"], 1.5)
    w.close()


def test_writes_stay_ordered_latest_wins(tmp_path):
    # single worker => FIFO: the last submitted round's artifact is on disk
    w = AsyncCheckpointWriter(maxsize=2)
    path = str(tmp_path / "latest.msgpack")
    for v in range(8):
        w.submit_save(path, _params(float(v)))
    w.flush()
    w.close()
    np.testing.assert_allclose(load_params(path, _params(0.0))["w"], 7.0)


def test_exception_propagates_once_and_skips_later_jobs(tmp_path):
    w = AsyncCheckpointWriter()
    ran = []

    def boom():
        raise OSError("disk full")

    w.submit(boom)
    w._queue.join()
    with pytest.raises(OSError, match="disk full"):
        w.submit(lambda: ran.append(1))
    w.flush()  # exception already consumed; flush is clean
    assert ran == []
    w.close()


def test_close_is_idempotent_and_rejects_after(tmp_path):
    w = AsyncCheckpointWriter()
    w.close()
    w.close()
    with pytest.raises(RuntimeError, match="closed"):
        w.submit_save(str(tmp_path / "x"), _params(0.0))


def test_checkpointer_routes_persist_through_attached_writer(tmp_path):
    w = AsyncCheckpointWriter()
    ck = LatestCheckpointer(str(tmp_path / "m.msgpack"))
    ck.async_writer = w
    assert ck.maybe_checkpoint(_params(3.0), 0.5, {})
    w.flush()
    np.testing.assert_allclose(
        load_params(ck.path, _params(0.0))["w"], 3.0
    )
    # detach -> synchronous persist again
    ck.async_writer = None
    ck.maybe_checkpoint(_params(4.0), 0.4, {})
    np.testing.assert_allclose(
        load_params(ck.path, _params(0.0))["w"], 4.0
    )
    w.close()


def test_best_loss_decision_unaffected_by_async_routing(tmp_path):
    w = AsyncCheckpointWriter()
    ck = BestLossCheckpointer(str(tmp_path / "best.msgpack"))
    ck.async_writer = w
    assert ck.maybe_checkpoint(_params(1.0), 1.0, {})
    assert not ck.maybe_checkpoint(_params(2.0), 2.0, {})  # worse: no write
    assert ck.maybe_checkpoint(_params(3.0), 0.5, {})
    w.flush()
    w.close()
    np.testing.assert_allclose(
        load_params(ck.path, _params(0.0))["w"], 3.0
    )

"""Checkpointing tests — policies, state round-trip, kill-and-resume.

Reference analogue: tests/checkpointing/ + the fault-tolerance smoke test
(tests/smoke_tests/run_smoke_test.py:414) which kills a 1-round run and
asserts the resumed run matches golden metrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fl4health_tpu.checkpointing import (
    BestLossCheckpointer,
    BestMetricCheckpointer,
    CheckpointMode,
    LatestCheckpointer,
    SimulationStateCheckpointer,
    load_params,
)
from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_classification
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import MnistNet
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg


def _params(v: float):
    return {"w": jnp.full((3,), v), "nested": {"b": jnp.asarray(v)}}


def test_latest_overwrites(tmp_path):
    p = str(tmp_path / "latest.msgpack")
    ck = LatestCheckpointer(p)
    assert ck.maybe_checkpoint(_params(1.0), 5.0, {})
    assert ck.maybe_checkpoint(_params(2.0), 9.0, {})
    got = load_params(p, _params(0.0))
    np.testing.assert_allclose(np.asarray(got["w"]), 2.0)


def test_best_loss_keeps_minimum(tmp_path):
    p = str(tmp_path / "best.msgpack")
    ck = BestLossCheckpointer(p)
    assert ck.maybe_checkpoint(_params(1.0), 5.0, {})
    assert not ck.maybe_checkpoint(_params(2.0), 7.0, {})
    assert ck.maybe_checkpoint(_params(3.0), 3.0, {})
    got = load_params(p, _params(0.0))
    np.testing.assert_allclose(np.asarray(got["w"]), 3.0)


def test_best_metric_maximizes_and_validates_key(tmp_path):
    p = str(tmp_path / "bm.msgpack")
    ck = BestMetricCheckpointer(p, "accuracy", maximize=True)
    assert ck.maybe_checkpoint(_params(1.0), None, {"accuracy": 0.5})
    assert not ck.maybe_checkpoint(_params(2.0), None, {"accuracy": 0.4})
    with pytest.raises(KeyError):
        ck.maybe_checkpoint(_params(2.0), None, {"other": 1.0})


def _make_sim(tmp_path=None, with_state=False, n_clients=3, seed=7):
    datasets = []
    for i in range(n_clients):
        x, y = synthetic_classification(jax.random.PRNGKey(i), 24, (28, 28, 1), 10)
        datasets.append(ClientDataset(x[:16], y[:16], x[16:], y[16:]))
    kwargs = {}
    if with_state:
        kwargs["state_checkpointer"] = SimulationStateCheckpointer(str(tmp_path))
    return FederatedSimulation(
        logic=engine.ClientLogic(
            engine.from_flax(MnistNet(hidden=16)), engine.masked_cross_entropy
        ),
        tx=optax.sgd(0.05),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=2,
        seed=seed,
        **kwargs,
    )


def _flat(params):
    return np.asarray(jax.flatten_util.ravel_pytree(params)[0])


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    straight = _make_sim()
    straight.fit(4)

    part1 = _make_sim(tmp_path / "state", with_state=True)
    part1.fit(2)
    # "kill": throw the object away, rebuild from scratch, resume from disk
    part2 = _make_sim(tmp_path / "state", with_state=True)
    part2.fit(4)

    np.testing.assert_allclose(
        _flat(part2.global_params), _flat(straight.global_params), atol=1e-6
    )
    assert len(part2.history) == 4
    assert [r.round for r in part2.history] == [1, 2, 3, 4]


def test_resume_rejects_client_count_mismatch(tmp_path):
    sim = _make_sim(tmp_path / "s", with_state=True)
    sim.fit(1)
    other = _make_sim(tmp_path / "s", with_state=True, n_clients=4)
    with pytest.raises(ValueError, match="clients"):
        other.fit(2)


def test_model_checkpointers_fire_in_fit(tmp_path):
    sim = _make_sim()
    post = BestLossCheckpointer(str(tmp_path / "post.msgpack"))
    pre = LatestCheckpointer(str(tmp_path / "pre.msgpack"))
    sim.model_checkpointers = [
        (CheckpointMode.POST_AGGREGATION, post),
        (CheckpointMode.PRE_AGGREGATION, pre),
    ]
    sim.fit(2)
    restored = post.load(sim.global_params)
    assert _flat(restored).shape == _flat(sim.global_params).shape
    # pre-aggregation artifact is the client-stacked tree
    stacked = load_params(str(tmp_path / "pre.msgpack"), sim.client_states.params)
    first = jax.tree_util.tree_leaves(stacked)[0]
    assert first.shape[0] == sim.n_clients

"""Dirichlet label-based dataset partitioning across clients.

Parity target: /root/reference/fl4health/utils/partitioners.py
``DirichletLabelBasedAllocation`` (:16) — per-label Dirichlet allocation
across N partitions with a min-examples retry loop (:168-220) and optional
prior distribution reuse (so a test set can be partitioned like its train
set, :120-135). Numpy-native re-design of the torch index plumbing.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Sequence

import numpy as np

logger = logging.getLogger(__name__)


class DirichletLabelBasedAllocation:
    def __init__(
        self,
        number_of_partitions: int,
        unique_labels: Sequence[Any],
        min_label_examples: int | None = None,
        beta: float | None = None,
        prior_distribution: dict | None = None,
        hash_key: int | None = None,
    ):
        assert (beta is not None) ^ (prior_distribution is not None), (
            "Either beta or a prior distribution must be provided, but not both."
        )
        self.number_of_partitions = number_of_partitions
        self.unique_labels = list(unique_labels)
        self.beta = beta
        self.min_label_examples = min_label_examples or 0
        self.prior_distribution = prior_distribution
        self.rng = np.random.default_rng(hash_key)
        if prior_distribution is not None:
            assert len(prior_distribution) == len(self.unique_labels), (
                "The length of the prior must match the number of labels"
            )

    def partition_label_indices(
        self, label: Any, label_indices: np.ndarray
    ) -> tuple[list[np.ndarray], int, np.ndarray]:
        """Allocate one label's indices over the partitions
        (partitioners.py:102-166). Returns (per-partition indices, min count,
        allocation distribution)."""
        if self.prior_distribution is not None:
            allocation = np.asarray(self.prior_distribution[label], np.float64)
            allocation = allocation / allocation.sum()
        else:
            allocation = self.rng.dirichlet(
                np.repeat(self.beta, self.number_of_partitions)
            )
        total = label_indices.shape[0]
        counts = [math.floor(p * total) for p in allocation]
        min_samples = min(counts)
        shuffled = label_indices[self.rng.permutation(total)]
        # Rounding slack goes to a final "fill" partition that is discarded
        # (partitioners.py:155-165).
        out = []
        start = 0
        for c in counts:
            out.append(shuffled[start : start + c])
            start += c
        return out, min_samples, allocation

    def partition_dataset(
        self,
        x: np.ndarray,
        y: np.ndarray,
        max_retries: int | None = 5,
    ) -> tuple[list[tuple[np.ndarray, np.ndarray]], dict]:
        """-> (list of (x_i, y_i) partitions, per-label allocation dists).

        Retries a label's Dirichlet draw while any partition receives fewer
        than ``min_label_examples`` points of that label, up to ``max_retries``
        (partitioners.py:168-220, raising when exhausted).
        """
        x, y = np.asarray(x), np.asarray(y)
        partitioned_indices: list[list[np.ndarray]] = [
            [] for _ in range(self.number_of_partitions)
        ]
        attempts = 0
        probabilities: dict = {}
        for label in self.unique_labels:
            label_indices = np.nonzero(y == label)[0]
            while True:
                parts, min_selected, allocation = self.partition_label_indices(
                    label, label_indices
                )
                if self.prior_distribution is not None or min_selected >= self.min_label_examples:
                    probabilities[label] = allocation
                    for i, p in enumerate(parts):
                        partitioned_indices[i].append(p)
                    break
                attempts += 1
                logger.info(
                    "Too few datapoints in a partition (%d < %d). Resampling...",
                    min_selected, self.min_label_examples,
                )
                if max_retries is not None and attempts >= max_retries:
                    raise ValueError(
                        f"Exhausted {max_retries} retries without satisfying "
                        f"min_label_examples={self.min_label_examples}"
                    )
        partitions = []
        for chunks in partitioned_indices:
            idx = np.concatenate(chunks) if chunks else np.zeros((0,), np.int64)
            idx = self.rng.permutation(idx)  # mix label blocks within a client
            partitions.append((x[idx], y[idx]))
        return partitions, probabilities

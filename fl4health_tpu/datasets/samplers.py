"""Label-based non-IID subsampling (host-side data prep).

Parity targets: /root/reference/fl4health/utils/sampler.py —
``MinorityLabelBasedSampler`` (:34) and ``DirichletLabelBasedSampler`` (:99).
Re-designed numpy-native: datasets are (x, y) array pairs (the simulation's
host boundary), not torch Datasets; sampling math is identical.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import numpy as np


class LabelBasedSampler:
    """Common surface: ``subsample(x, y) -> (x, y)`` (sampler.py:12)."""

    def __init__(self, unique_labels: Sequence[Any]):
        self.unique_labels = list(unique_labels)
        self.num_classes = len(self.unique_labels)

    def subsample(self, x: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


class MinorityLabelBasedSampler(LabelBasedSampler):
    """Downsample the specified minority labels to ``downsampling_ratio``
    (sampler.py:34): a label with 10 examples and ratio 0.2 keeps 2."""

    def __init__(
        self,
        unique_labels: Sequence[Any],
        downsampling_ratio: float,
        minority_labels: set,
        hash_key: int | None = None,
    ):
        super().__init__(unique_labels)
        self.downsampling_ratio = downsampling_ratio
        self.minority_labels = set(minority_labels)
        self.rng = np.random.default_rng(hash_key)

    def subsample(self, x: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        selected: list[np.ndarray] = []
        for label in self.unique_labels:
            idx = np.nonzero(np.asarray(y) == label)[0]
            if label in self.minority_labels:
                size = int(idx.shape[0] * self.downsampling_ratio)
                perm = self.rng.permutation(idx.shape[0])
                idx = idx[perm[:size]]
            selected.append(idx)
        sel = np.concatenate(selected)
        return np.asarray(x)[sel], np.asarray(y)[sel]


class DirichletLabelBasedSampler(LabelBasedSampler):
    """Subsample so the label marginal follows a Dirichlet(beta) draw
    (sampler.py:99). Large beta -> near-uniform; small beta -> heterogeneous.
    ``sample_percentage`` sets the size of the subsampled dataset. Sampling is
    with replacement per class (torch.multinomial(replacement=True) parity,
    sampler.py:168-175), and the final count is trimmed to exactly
    ``sample_percentage * len(dataset)`` (:180-186).
    """

    def __init__(
        self,
        unique_labels: Sequence[Any],
        hash_key: int | None = None,
        sample_percentage: float = 0.5,
        beta: float = 100,
    ):
        super().__init__(unique_labels)
        self.rng = np.random.default_rng(hash_key)
        self.probabilities = self.rng.dirichlet(np.repeat(beta, self.num_classes))
        self.sample_percentage = sample_percentage

    def subsample(self, x: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        y = np.asarray(y)
        assert self.sample_percentage <= 1.0
        total = int(y.shape[0] * self.sample_percentage)
        per_class = [math.ceil(p * total) for p in self.probabilities]
        chosen: list[np.ndarray] = []
        for label, n_samples in zip(self.unique_labels, per_class):
            idx = np.nonzero(y == label)[0]
            if idx.shape[0] == 0 or n_samples == 0:
                continue
            chosen.append(self.rng.choice(idx, size=n_samples, replace=True))
        sel = np.concatenate(chosen) if chosen else np.zeros((0,), np.int64)
        # ceil() overshoots; uniformly trim to the exact requested count.
        if sel.shape[0] > total:
            sel = sel[self.rng.permutation(sel.shape[0])[:total]]
        return np.asarray(x)[sel], y[sel]

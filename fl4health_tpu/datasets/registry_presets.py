"""Large-N non-IID REGISTRY presets — Dirichlet partitions as index views.

``federated_client_datasets`` + ``DirichletLabelBasedAllocation`` densify
every client's shard (``x[idx]`` copies), which is fine for a handful of
silos and fatal for a registry of 10^5..10^6 simulated clients: N shards
of a shared pool would copy the pool N*shard/pool times over. These
presets build the same label-Dirichlet heterogeneity as an
:class:`~fl4health_tpu.server.registry.IndexedPoolSource` — ONE shared
example pool plus per-client index arrays that are views into a single
owner-sorted permutation — so registry memory is O(pool + N index rows)
and a client's shard only materializes when cohort-slot execution
actually samples it.

Usage (the cohort-slot bench's registry construction)::

    x, y = synthetic_cifar_arrays(4096)
    source = dirichlet_registry_source(x, y, n_clients=100_000, beta=0.5)
    sim = FederatedSimulation(..., datasets=source,
                              cohort=CohortConfig(slots=64),
                              client_manager=FixedFractionManager(...))
"""

from __future__ import annotations

import numpy as np

from fl4health_tpu.server.registry import IndexedPoolSource


def dirichlet_registry_source(
    x: np.ndarray,
    y: np.ndarray,
    n_clients: int,
    *,
    beta: float = 0.5,
    val_fraction: float = 0.2,
    seed: int = 0,
    min_train: int = 1,
    min_val: int = 1,
) -> IndexedPoolSource:
    """Label-Dirichlet allocation of a pooled ``(x, y)`` dataset over
    ``n_clients`` registry clients, WITHOUT densifying the shards.

    Per label, a Dirichlet(``beta``) draw over clients sets that label's
    allocation and each of its rows is assigned to a client by one
    vectorized categorical draw — the
    ``DirichletLabelBasedAllocation`` heterogeneity model, re-expressed
    as an ownership vector instead of N materialized partitions. Clients
    too small to hold ``min_train + min_val`` rows are topped up with
    uniformly-drawn pool rows (shared, view-only duplicates — with
    ``n_clients`` approaching or exceeding the pool size some sharing is
    unavoidable and is disclosed here rather than failing).

    Deterministic in ``seed``. Returns an :class:`IndexedPoolSource`
    whose index arrays are views into one owner-sorted permutation."""
    x, y = np.asarray(x), np.asarray(y)
    n = y.shape[0]
    if n_clients < 1:
        raise ValueError(f"n_clients must be >= 1; got {n_clients}")
    if not 0.0 < val_fraction < 1.0:
        raise ValueError(
            f"val_fraction must be in (0, 1); got {val_fraction}"
        )
    rng = np.random.default_rng(seed)
    owner = np.empty((n,), np.int64)
    for label in np.unique(y):
        rows = np.nonzero(y == label)[0]
        # one Dirichlet draw per label = that label's client allocation;
        # one vectorized categorical draw assigns its rows
        p = rng.dirichlet(np.full((n_clients,), float(beta)))
        owner[rows] = rng.choice(n_clients, size=rows.size, p=p)
    order = np.argsort(owner, kind="stable")
    sorted_owner = owner[order]
    client_range = np.arange(n_clients)
    starts = np.searchsorted(sorted_owner, client_range, side="left")
    ends = np.searchsorted(sorted_owner, client_range, side="right")
    need = min_train + min_val
    train_idx: list[np.ndarray] = []
    val_idx: list[np.ndarray] = []
    for c in range(n_clients):
        seg = order[starts[c]:ends[c]]  # a VIEW into the one permutation
        if seg.size < need:
            seg = np.concatenate(
                [seg, rng.integers(0, n, size=need - seg.size)]
            )
        n_val = min(max(min_val, int(round(seg.size * val_fraction))),
                    seg.size - min_train)
        val_idx.append(seg[:n_val])
        train_idx.append(seg[n_val:])
    return IndexedPoolSource((x, y), (x, y), train_idx, val_idx)


def cifar_dirichlet_registry(
    n_clients: int,
    *,
    beta: float = 0.5,
    pool_size: int = 4096,
    data_dir=None,
    seed: int = 0,
    val_fraction: float = 0.2,
) -> IndexedPoolSource:
    """CIFAR-shaped Dirichlet registry: real CIFAR-10 arrays when
    ``data_dir`` holds them, the deterministic synthetic stand-in
    otherwise (the zero-egress convention of ``datasets/vision.py``)."""
    from fl4health_tpu.datasets import vision

    if data_dir is not None:
        x, y = vision.load_cifar10_arrays(data_dir, train=True)
    else:
        x, y = vision.synthetic_cifar_arrays(pool_size, seed=seed)
    return dirichlet_registry_source(
        x, y, n_clients, beta=beta, seed=seed, val_fraction=val_fraction
    )


def mnist_dirichlet_registry(
    n_clients: int,
    *,
    beta: float = 0.5,
    pool_size: int = 4096,
    data_dir=None,
    seed: int = 0,
    val_fraction: float = 0.2,
) -> IndexedPoolSource:
    """MNIST-shaped Dirichlet registry (see
    :func:`cifar_dirichlet_registry`)."""
    from fl4health_tpu.datasets import vision

    if data_dir is not None:
        x, y = vision.load_mnist_arrays(data_dir, train=True)
    else:
        x, y = vision.synthetic_mnist_arrays(pool_size, seed=seed)
    return dirichlet_registry_source(
        x, y, n_clients, beta=beta, seed=seed, val_fraction=val_fraction
    )

"""MNIST / CIFAR-10 loading and federated client-dataset construction.

Parity targets: /root/reference/fl4health/utils/load_data.py —
``load_mnist_data`` (:75), ``load_cifar10_data`` (:203),
``split_data_and_targets`` (:33). The reference reads torchvision caches and
returns DataLoaders; here loaders read the standard on-disk formats directly
(IDX / keras-style npz for MNIST, python-pickle batches / npz for CIFAR-10)
into numpy, apply the same normalization ((x/255 - 0.5)/0.5), and produce the
simulation's host-side ``ClientDataset`` list. This environment has zero data
egress, so when no real data exists at ``data_dir`` the federated helpers can
fall back to the deterministic MNIST/CIFAR-shaped synthetic generators
(explicitly, never silently).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from pathlib import Path
from typing import Sequence

import jax
import numpy as np

from fl4health_tpu.datasets.samplers import LabelBasedSampler
from fl4health_tpu.datasets.synthetic import synthetic_classification


# ---------------------------------------------------------------------------
# Raw format readers
# ---------------------------------------------------------------------------

def _read_idx(path: Path) -> np.ndarray:
    """Read an IDX-format file (the MNIST distribution format), .gz or raw."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as f:  # type: ignore[operator]
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path} is not an IDX file")
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                  0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}
        data = np.frombuffer(f.read(), dtype=dtypes[dtype_code])
        return data.reshape(shape)


def _find_first(data_dir: Path, names: Sequence[str]) -> Path | None:
    for name in names:
        p = data_dir / name
        if p.exists():
            return p
    return None


def load_mnist_arrays(data_dir: Path | str, train: bool = True
                      ) -> tuple[np.ndarray, np.ndarray]:
    """-> (images [N,28,28,1] float32 normalized to [-1,1], labels [N] int32).

    Accepts the IDX pair (``train-images-idx3-ubyte[.gz]`` /
    ``train-labels-idx1-ubyte[.gz]``, also under an ``MNIST/raw`` subdir as
    torchvision lays it out) or a keras-style ``mnist.npz``.
    """
    data_dir = Path(data_dir)
    prefix = "train" if train else "t10k"
    for base in (data_dir, data_dir / "MNIST" / "raw"):
        images = _find_first(base, [f"{prefix}-images-idx3-ubyte",
                                    f"{prefix}-images-idx3-ubyte.gz"])
        labels = _find_first(base, [f"{prefix}-labels-idx1-ubyte",
                                    f"{prefix}-labels-idx1-ubyte.gz"])
        if images is not None and labels is not None:
            x = _read_idx(images).astype(np.float32)
            y = _read_idx(labels).astype(np.int32)
            x = (x / 255.0 - 0.5) / 0.5  # Normalize((0.5),(0.5)) parity
            return x[..., None], y
    npz = _find_first(data_dir, ["mnist.npz"])
    if npz is not None:
        with np.load(npz) as z:
            x = z["x_train" if train else "x_test"].astype(np.float32)
            y = z["y_train" if train else "y_test"].astype(np.int32)
        return ((x / 255.0 - 0.5) / 0.5)[..., None], y
    raise FileNotFoundError(
        f"No MNIST data found under {data_dir} (looked for IDX files and "
        "mnist.npz). Pass synthetic_fallback=True to the federated helper to "
        "use the deterministic MNIST-shaped synthetic set instead."
    )


def load_cifar10_arrays(data_dir: Path | str, train: bool = True
                        ) -> tuple[np.ndarray, np.ndarray]:
    """-> (images [N,32,32,3] float32 normalized to [-1,1], labels [N] int32).

    Accepts the python-pickle distribution (``cifar-10-batches-py/``) or a
    ``cifar10.npz`` with x_train/y_train/x_test/y_test.
    """
    data_dir = Path(data_dir)
    batch_dir = data_dir / "cifar-10-batches-py"
    if not batch_dir.exists() and (data_dir / "data_batch_1").exists():
        batch_dir = data_dir
    if batch_dir.exists():
        names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        xs, ys = [], []
        for name in names:
            with open(batch_dir / name, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.concatenate(ys)
        x = (x.astype(np.float32) / 255.0 - 0.5) / 0.5
        return x, y
    npz = _find_first(data_dir, ["cifar10.npz"])
    if npz is not None:
        with np.load(npz) as z:
            x = z["x_train" if train else "x_test"].astype(np.float32)
            y = z["y_train" if train else "y_test"].astype(np.int32)
        return (x / 255.0 - 0.5) / 0.5, y
    raise FileNotFoundError(
        f"No CIFAR-10 data found under {data_dir} (looked for "
        "cifar-10-batches-py/ and cifar10.npz)."
    )


# ---------------------------------------------------------------------------
# Splitting + federated construction
# ---------------------------------------------------------------------------

def split_data_and_targets(
    x: np.ndarray,
    y: np.ndarray,
    validation_proportion: float = 0.2,
    hash_key: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Reproducible train/val split (load_data.py:33-57): shuffle with the
    hash key, put the first (1-p) fraction in train."""
    n = x.shape[0]
    perm = np.random.default_rng(hash_key).permutation(n)
    n_train = int(n * (1 - validation_proportion))
    tr, va = perm[:n_train], perm[n_train:]
    return x[tr], y[tr], x[va], y[va]


def synthetic_mnist_arrays(
    n: int = 4096, seed: int = 0, class_sep: float = 2.0
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped stand-in (zero-egress environments)."""
    x, y = synthetic_classification(
        jax.random.PRNGKey(seed), n, (28, 28, 1), 10, class_sep=class_sep
    )
    return np.asarray(x), np.asarray(y)


def synthetic_cifar_arrays(n: int = 4096, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    x, y = synthetic_classification(jax.random.PRNGKey(seed), n, (32, 32, 3), 10)
    return np.asarray(x), np.asarray(y)


def federated_client_datasets(
    x: np.ndarray,
    y: np.ndarray,
    n_clients: int,
    partitioner=None,
    sampler: LabelBasedSampler | None = None,
    validation_proportion: float = 0.2,
    hash_key: int | None = None,
):
    """Partition (or sampler-subsample) pooled data into per-client
    ``ClientDataset``s with reproducible train/val splits.

    - ``partitioner``: a DirichletLabelBasedAllocation — disjoint non-IID
      partitions (utils/partitioners.py:16 usage pattern).
    - ``sampler``: a LabelBasedSampler applied per client to i.i.d. shards
      (the reference's per-client sampler pattern, load_data.py:122-125).
    """
    from fl4health_tpu.server.simulation import ClientDataset

    if partitioner is not None:
        parts = partitioner.partition_dataset(x, y)[0]
    else:
        shards = np.array_split(np.random.default_rng(hash_key).permutation(x.shape[0]),
                                n_clients)
        parts = [(x[s], y[s]) for s in shards]
        if sampler is not None:
            parts = [sampler.subsample(px, py) for px, py in parts]
    out = []
    for i, (px, py) in enumerate(parts):
        xt, yt, xv, yv = split_data_and_targets(
            px, py, validation_proportion,
            None if hash_key is None else hash_key + i,
        )
        out.append(ClientDataset(x_train=xt, y_train=yt, x_val=xv, y_val=yv))
    return out

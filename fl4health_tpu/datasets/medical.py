"""Medical dataset loaders: rxrx1, skin-cancer (ISIC-family), MSD volumes.

Parity targets: /root/reference/fl4health/datasets/rxrx1/load_data.py:121
(``load_rxrx1_data``: metadata.csv-driven per-image loading with site-based
client splits), /root/reference/fl4health/datasets/skin_cancer/* (ISIC /
HAM10000 / PAD-UFES / Derm7pt: preprocessed per-center JSON/CSV manifests +
image folders), /root/reference/fl4health/utils/load_data.py:288
(``load_msd_dataset``: Medical Segmentation Decathlon download + nnU-Net-style
dataset.json with imagesTr/labelsTr pairs).

TPU-native design: manifests (CSV/JSON) drive array loading into the
host-side numpy tensors the stacked engine consumes — no torchvision/MONAI.
Zero egress in this environment: loaders read real data when it exists on
disk (same directory conventions as the reference's download targets) and
raise a clear FileNotFoundError otherwise; tests synthesize fixtures in the
same on-disk formats. Volumes load from .npy/.npz (nibabel is unavailable,
NIfTI support is gated behind its presence).
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any, Sequence

import numpy as np


def _load_image_array(path: Path) -> np.ndarray:
    """Load one image/volume array: .npy/.npz natively; .png/.jpg when a
    decoder (PIL) is available; .nii/.nii.gz when nibabel is available."""
    suffix = "".join(path.suffixes)
    if path.suffix == ".npy":
        return np.load(path)
    if path.suffix == ".npz":
        with np.load(path) as z:
            return z[list(z.keys())[0]]
    if suffix.endswith((".nii", ".nii.gz")):
        try:
            import nibabel as nib  # gated: not in the base image
        except ImportError as e:
            raise ImportError(
                f"{path}: NIfTI volumes need nibabel, which is not installed; "
                "convert to .npy/.npz"
            ) from e
        return np.asanyarray(nib.load(str(path)).dataobj)
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            f"{path}: image decoding needs PIL; convert to .npy/.npz"
        ) from e
    return np.asarray(Image.open(path))


def _normalize_image(arr: np.ndarray) -> np.ndarray:
    """Integer-typed images scale by 255; float images pass through. Decided
    from dtype, never per-image content — a nearly-black uint8 frame must not
    end up 255x hotter than its neighbors."""
    if np.issubdtype(arr.dtype, np.integer):
        return arr.astype(np.float32) / 255.0
    return arr.astype(np.float32)


# ---------------------------------------------------------------------------
# rxrx1 — fluorescence microscopy, site-partitioned (rxrx1/load_data.py:121)
# ---------------------------------------------------------------------------

def load_rxrx1_data(
    data_dir: Path | str,
    client_site: int | None = None,
    train: bool = True,
) -> tuple[np.ndarray, np.ndarray, dict[str, Any]]:
    """-> (images [N,H,W,C] float32 in [0,1], labels [N] int32, info).

    Expects the reference's layout: ``metadata.csv`` with columns
    ``well_id,site,dataset,sirna_id`` (+ optional ``path``) and per-well
    arrays under ``images/<well_id>.npy`` (or the path column). ``site``
    selects the federated client (the reference partitions rxrx1 by
    experiment site); ``dataset`` in {train, test} selects the split.
    """
    data_dir = Path(data_dir)
    meta_path = data_dir / "metadata.csv"
    if not meta_path.exists():
        raise FileNotFoundError(f"rxrx1: no metadata.csv under {data_dir}")
    want_split = "train" if train else "test"
    rows, all_labels = [], set()
    with open(meta_path) as f:
        for row in csv.DictReader(f):
            # the label space comes from the FULL metadata (every site, every
            # split) — federated clients must agree on class indices even
            # when a site is missing some sirnas locally
            all_labels.add(int(row["sirna_id"]))
            if row.get("dataset", "train") != want_split:
                continue
            if client_site is not None and int(row["site"]) != client_site:
                continue
            rows.append(row)
    if not rows:
        raise FileNotFoundError(
            f"rxrx1: no rows for split={want_split} site={client_site}"
        )
    images, labels = [], []
    for row in rows:
        rel = row.get("path") or f"images/{row['well_id']}.npy"
        images.append(_normalize_image(_load_image_array(data_dir / rel)))
        labels.append(int(row["sirna_id"]))
    x = np.stack(images)
    if x.ndim == 3:
        x = x[..., None]
    classes = sorted(all_labels)
    remap = {c: i for i, c in enumerate(classes)}
    y = np.asarray([remap[v] for v in labels], np.int32)
    return x, y, {"n_classes": len(classes), "sirna_ids": classes}


# ---------------------------------------------------------------------------
# Skin cancer — ISIC-family per-center manifests (datasets/skin_cancer/*)
# ---------------------------------------------------------------------------

SKIN_CANCER_CENTERS = ("isic_2019", "ham10000", "pad_ufes_20", "derm7pt")


def _read_manifest(center_dir: Path, split: str) -> list[dict[str, Any]]:
    csv_path = center_dir / f"{split}.csv"
    json_path = center_dir / f"{split}.json"
    if csv_path.exists():
        with open(csv_path) as f:
            return list(csv.DictReader(f))
    if json_path.exists():
        with open(json_path) as f:
            return json.load(f)
    raise FileNotFoundError(
        f"skin-cancer: no {split}.csv/.json manifest under {center_dir}"
    )


def _record_label(rec: dict[str, Any], label_column: str, source: Path) -> str:
    label = rec.get(label_column, rec.get("label"))
    if label is None:
        raise KeyError(
            f"{source}: record {rec.get('image', rec)!r} has neither "
            f"{label_column!r} nor 'label' — refusing to invent a class"
        )
    return str(label)


def load_skin_cancer_data(
    data_dir: Path | str,
    center: str,
    train: bool = True,
    label_column: str = "diagnosis",
    classes: Sequence[str] | None = None,
) -> tuple[np.ndarray, np.ndarray, dict[str, Any]]:
    """-> (images [N,H,W,3] float32 in [0,1], labels [N] int32, info).

    Layout per center (the reference's preprocessed convention): a manifest
    ``<center>/<split>.csv`` (columns ``image``, ``<label_column>``) or
    ``<center>/<split>.json`` (list of {image, label} records), with image
    arrays resolved relative to the center directory.

    ``classes`` fixes the global label order for federated runs; when None
    it is derived from the UNION of every center manifest present under
    ``data_dir`` (both splits), so centers missing a diagnosis locally still
    agree on class indices.
    """
    data_dir = Path(data_dir)
    center_dir = data_dir / center
    split = "train" if train else "test"
    records = _read_manifest(center_dir, split)

    if classes is None:
        seen = set()
        for other in sorted(p for p in data_dir.iterdir() if p.is_dir()):
            for other_split in ("train", "test"):
                try:
                    for rec in _read_manifest(other, other_split):
                        seen.add(_record_label(rec, label_column, other))
                except FileNotFoundError:
                    continue
        classes = sorted(seen)
    else:
        classes = list(classes)

    images, labels = [], []
    for rec in records:
        images.append(_normalize_image(_load_image_array(center_dir / rec["image"])))
        labels.append(_record_label(rec, label_column, center_dir))
    remap = {c: i for i, c in enumerate(classes)}
    missing = sorted(set(labels) - set(classes))
    if missing:
        raise ValueError(
            f"skin-cancer: labels {missing} in {center} not in the class set {classes}"
        )
    return (
        np.stack(images),
        np.asarray([remap[v] for v in labels], np.int32),
        {"n_classes": len(classes), "classes": list(classes), "center": center},
    )


# ---------------------------------------------------------------------------
# MSD — Medical Segmentation Decathlon volumes (utils/load_data.py:288)
# ---------------------------------------------------------------------------

def load_msd_dataset(
    data_dir: Path | str, task: str | None = None
) -> dict[str, Any]:
    """-> {"volumes": [...], "segmentations": [...], "spacings": [...],
    "labels": {...}, "name": str}.

    Reads the nnU-Net-style ``dataset.json`` (keys ``name``, ``labels``,
    ``training``: [{image, label}]) the MSD tarballs ship; image/label paths
    resolve relative to the task directory. Spacings come from ``spacing``
    entries when present (else unit). Output feeds nnunet.extract_fingerprint
    / extract_patch_dataset directly.
    """
    data_dir = Path(data_dir)
    task_dir = data_dir / task if task else data_dir
    ds_json = task_dir / "dataset.json"
    if not ds_json.exists():
        raise FileNotFoundError(f"MSD: no dataset.json under {task_dir}")
    with open(ds_json) as f:
        desc = json.load(f)
    volumes, segs, spacings = [], [], []
    for case in desc.get("training", []):
        vol = _load_image_array(task_dir / case["image"]).astype(np.float32)
        seg = _load_image_array(task_dir / case["label"]).astype(np.int32)
        if vol.ndim == seg.ndim:  # channels-last expected by the planner
            vol = vol[..., None]
        volumes.append(vol)
        segs.append(seg)
        spacings.append(tuple(case.get("spacing", (1.0,) * seg.ndim)))
    return {
        "volumes": volumes,
        "segmentations": segs,
        "spacings": spacings,
        "labels": desc.get("labels", {}),
        "name": desc.get("name", task or data_dir.name),
    }

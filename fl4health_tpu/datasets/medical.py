"""Medical dataset loaders: rxrx1, skin-cancer (ISIC-family), MSD volumes.

Parity targets: /root/reference/fl4health/datasets/rxrx1/load_data.py:121
(``load_rxrx1_data``: metadata.csv-driven per-image loading with site-based
client splits), /root/reference/fl4health/datasets/skin_cancer/* (ISIC /
HAM10000 / PAD-UFES / Derm7pt: preprocessed per-center JSON/CSV manifests +
image folders), /root/reference/fl4health/utils/load_data.py:288
(``load_msd_dataset``: Medical Segmentation Decathlon download + nnU-Net-style
dataset.json with imagesTr/labelsTr pairs).

TPU-native design: manifests (CSV/JSON) drive array loading into the
host-side numpy tensors the stacked engine consumes — no torchvision/MONAI.
Zero egress in this environment: loaders read real data when it exists on
disk (same directory conventions as the reference's download targets) and
raise a clear FileNotFoundError otherwise; tests synthesize fixtures in the
same on-disk formats. Volumes load from .npy/.npz (nibabel is unavailable,
NIfTI support is gated behind its presence).
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any, Sequence

import numpy as np


def _load_image_array(path: Path) -> np.ndarray:
    """Load one image/volume array: .npy/.npz natively; .png/.jpg when a
    decoder (PIL) is available; .nii/.nii.gz when nibabel is available."""
    suffix = "".join(path.suffixes)
    if path.suffix == ".npy":
        return np.load(path)
    if path.suffix == ".npz":
        with np.load(path) as z:
            return z[list(z.keys())[0]]
    if suffix.endswith((".nii", ".nii.gz")):
        try:
            import nibabel as nib  # gated: not in the base image
        except ImportError as e:
            raise ImportError(
                f"{path}: NIfTI volumes need nibabel, which is not installed; "
                "convert to .npy/.npz"
            ) from e
        return np.asanyarray(nib.load(str(path)).dataobj)
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            f"{path}: image decoding needs PIL; convert to .npy/.npz"
        ) from e
    return np.asarray(Image.open(path))


# ---------------------------------------------------------------------------
# rxrx1 — fluorescence microscopy, site-partitioned (rxrx1/load_data.py:121)
# ---------------------------------------------------------------------------

def load_rxrx1_data(
    data_dir: Path | str,
    client_site: int | None = None,
    train: bool = True,
) -> tuple[np.ndarray, np.ndarray, dict[str, Any]]:
    """-> (images [N,H,W,C] float32 in [0,1], labels [N] int32, info).

    Expects the reference's layout: ``metadata.csv`` with columns
    ``well_id,site,dataset,sirna_id`` (+ optional ``path``) and per-well
    arrays under ``images/<well_id>.npy`` (or the path column). ``site``
    selects the federated client (the reference partitions rxrx1 by
    experiment site); ``dataset`` in {train, test} selects the split.
    """
    data_dir = Path(data_dir)
    meta_path = data_dir / "metadata.csv"
    if not meta_path.exists():
        raise FileNotFoundError(f"rxrx1: no metadata.csv under {data_dir}")
    want_split = "train" if train else "test"
    rows = []
    with open(meta_path) as f:
        for row in csv.DictReader(f):
            if row.get("dataset", "train") != want_split:
                continue
            if client_site is not None and int(row["site"]) != client_site:
                continue
            rows.append(row)
    if not rows:
        raise FileNotFoundError(
            f"rxrx1: no rows for split={want_split} site={client_site}"
        )
    images, labels = [], []
    for row in rows:
        rel = row.get("path") or f"images/{row['well_id']}.npy"
        arr = _load_image_array(data_dir / rel).astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        images.append(arr)
        labels.append(int(row["sirna_id"]))
    x = np.stack(images)
    if x.ndim == 3:
        x = x[..., None]
    classes = sorted(set(labels))
    remap = {c: i for i, c in enumerate(classes)}
    y = np.asarray([remap[v] for v in labels], np.int32)
    return x, y, {"n_classes": len(classes), "sirna_ids": classes}


# ---------------------------------------------------------------------------
# Skin cancer — ISIC-family per-center manifests (datasets/skin_cancer/*)
# ---------------------------------------------------------------------------

SKIN_CANCER_CENTERS = ("isic_2019", "ham10000", "pad_ufes_20", "derm7pt")


def load_skin_cancer_data(
    data_dir: Path | str,
    center: str,
    train: bool = True,
    label_column: str = "diagnosis",
) -> tuple[np.ndarray, np.ndarray, dict[str, Any]]:
    """-> (images [N,H,W,3] float32 in [0,1], labels [N] int32, info).

    Layout per center (the reference's preprocessed convention): a manifest
    ``<center>/<split>.csv`` (columns ``image``, ``<label_column>``) or
    ``<center>/<split>.json`` (list of {image, label} records), with image
    arrays resolved relative to the center directory.
    """
    data_dir = Path(data_dir)
    center_dir = data_dir / center
    split = "train" if train else "test"
    records: list[dict[str, Any]] = []
    csv_path = center_dir / f"{split}.csv"
    json_path = center_dir / f"{split}.json"
    if csv_path.exists():
        with open(csv_path) as f:
            records = list(csv.DictReader(f))
    elif json_path.exists():
        with open(json_path) as f:
            records = json.load(f)
    else:
        raise FileNotFoundError(
            f"skin-cancer: no {split}.csv/.json manifest under {center_dir}"
        )
    images, labels = [], []
    for rec in records:
        arr = _load_image_array(center_dir / rec["image"]).astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        images.append(arr)
        labels.append(str(rec.get(label_column, rec.get("label"))))
    classes = sorted(set(labels))
    remap = {c: i for i, c in enumerate(classes)}
    return (
        np.stack(images),
        np.asarray([remap[v] for v in labels], np.int32),
        {"n_classes": len(classes), "classes": classes, "center": center},
    )


# ---------------------------------------------------------------------------
# MSD — Medical Segmentation Decathlon volumes (utils/load_data.py:288)
# ---------------------------------------------------------------------------

def load_msd_dataset(
    data_dir: Path | str, task: str | None = None
) -> dict[str, Any]:
    """-> {"volumes": [...], "segmentations": [...], "spacings": [...],
    "labels": {...}, "name": str}.

    Reads the nnU-Net-style ``dataset.json`` (keys ``name``, ``labels``,
    ``training``: [{image, label}]) the MSD tarballs ship; image/label paths
    resolve relative to the task directory. Spacings come from ``spacing``
    entries when present (else unit). Output feeds nnunet.extract_fingerprint
    / extract_patch_dataset directly.
    """
    data_dir = Path(data_dir)
    task_dir = data_dir / task if task else data_dir
    ds_json = task_dir / "dataset.json"
    if not ds_json.exists():
        raise FileNotFoundError(f"MSD: no dataset.json under {task_dir}")
    with open(ds_json) as f:
        desc = json.load(f)
    volumes, segs, spacings = [], [], []
    for case in desc.get("training", []):
        vol = _load_image_array(task_dir / case["image"]).astype(np.float32)
        seg = _load_image_array(task_dir / case["label"]).astype(np.int32)
        if vol.ndim == seg.ndim:  # channels-last expected by the planner
            vol = vol[..., None]
        volumes.append(vol)
        segs.append(seg)
        spacings.append(tuple(case.get("spacing", (1.0,) * seg.ndim)))
    return {
        "volumes": volumes,
        "segmentations": segs,
        "spacings": spacings,
        "labels": desc.get("labels", {}),
        "name": desc.get("name", task or data_dir.name),
    }

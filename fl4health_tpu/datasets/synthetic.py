"""Deterministic synthetic datasets for tests, smoke runs and benchmarks.

Role of /root/reference/fl4health/utils/dataset.py SyntheticDataset and
utils/data_generation.py (FedProx synthetic generator). With zero data egress
in this environment, the MNIST/CIFAR-shaped generators below also stand in for
the real corpora in smoke tests; loaders in ``fl4health_tpu.datasets.vision``
pick up real data from disk when present.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.core.types import PRNGKey


def synthetic_classification(
    rng: PRNGKey,
    n: int,
    input_shape: tuple[int, ...],
    n_classes: int,
    class_sep: float = 2.0,
) -> tuple[jax.Array, jax.Array]:
    """Gaussian class blobs flattened into ``input_shape`` images.

    Learnable but not trivial; deterministic given rng.
    """
    k_mu, k_x, k_y = jax.random.split(rng, 3)
    dim = 1
    for s in input_shape:
        dim *= s
    mus = jax.random.normal(k_mu, (n_classes, dim)) * class_sep
    y = jax.random.randint(k_y, (n,), 0, n_classes)
    x = mus[y] + jax.random.normal(k_x, (n, dim))
    # standardize: separability is unchanged, conditioning is image-like
    x = (x - jnp.mean(x)) / jnp.maximum(jnp.std(x), 1e-6)
    return x.reshape((n, *input_shape)).astype(jnp.float32), y.astype(jnp.int32)


def fedprox_synthetic(
    rng: PRNGKey,
    n_clients: int,
    samples_per_client: int,
    alpha: float = 0.5,
    beta: float = 0.5,
    dim: int = 60,
    n_classes: int = 10,
) -> list[tuple[jax.Array, jax.Array]]:
    """Heterogeneous synthetic generator of the FedProx paper
    (utils/data_generation.py:12,147): per-client W_k ~ N(u_k, 1),
    u_k ~ N(0, alpha); features x ~ N(v_k, Sigma), v_k ~ N(B_k, 1),
    B_k ~ N(0, beta); labels = argmax(softmax(Wx + b)).
    """
    sigma = jnp.diag(jnp.arange(1, dim + 1, dtype=jnp.float32) ** -1.2)
    out = []
    for k in range(n_clients):
        rk = jax.random.fold_in(rng, k)
        k1, k2, k3, k4, k5 = jax.random.split(rk, 5)
        u_k = jax.random.normal(k1, ()) * jnp.sqrt(alpha)
        b_k = jax.random.normal(k2, ()) * jnp.sqrt(beta)
        w = jax.random.normal(k3, (n_classes, dim)) + u_k
        bias = jax.random.normal(k4, (n_classes,)) + u_k
        v_k = jax.random.normal(k5, (dim,)) + b_k
        x = v_k + jax.random.normal(
            jax.random.fold_in(rk, 99), (samples_per_client, dim)
        ) @ jnp.sqrt(sigma)
        logits = x @ w.T + bias
        y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out.append((x.astype(jnp.float32), y))
    return out


def dirichlet_partition(
    rng: PRNGKey,
    x: jax.Array,
    y: jax.Array,
    n_partitions: int,
    beta: float,
    n_classes: int | None = None,
    min_examples: int = 1,
    max_retries: int = 5,
) -> list[tuple[jax.Array, jax.Array]]:
    """Dirichlet label-skew partitioner
    (utils/partitioners.py:16 DirichletLabelBasedAllocation): for each label,
    draw p ~ Dir(beta * 1_N) and allocate that label's examples across the N
    partitions by p; retry while any partition has < min_examples.
    """
    import numpy as np

    n_classes = int(jnp.max(y)) + 1 if n_classes is None else n_classes
    y_np = np.asarray(y)
    seed = int(jax.random.randint(rng, (), 0, 2**31 - 1))
    gen = np.random.default_rng(seed)
    for attempt in range(max_retries):
        parts: list[list[int]] = [[] for _ in range(n_partitions)]
        for c in range(n_classes):
            idx = np.flatnonzero(y_np == c)
            gen.shuffle(idx)
            p = gen.dirichlet(np.full((n_partitions,), beta))
            splits = (np.cumsum(p) * len(idx)).astype(int)[:-1]
            for part, chunk in zip(parts, np.split(idx, splits)):
                part.extend(chunk.tolist())
        if min(len(p) for p in parts) >= min_examples:
            break
    else:
        raise ValueError(
            f"Dirichlet partition failed to give every partition >= {min_examples} "
            f"examples in {max_retries} tries (beta={beta})"
        )
    out = []
    for part in parts:
        sel = jnp.asarray(np.sort(np.asarray(part, dtype=np.int64)))
        out.append((x[sel], y[sel]))
    return out


def synthetic_text_classification(
    rng: PRNGKey,
    n: int,
    vocab_size: int = 512,
    seq_len: int = 32,
    n_classes: int = 4,
    class_sep: float = 2.0,
) -> tuple[jax.Array, jax.Array]:
    """AG-News-shaped synthetic token sequences for transformer configs
    (role of /root/reference/examples/bert_finetuning_example's AG-News data
    under zero egress; research/ag_news is the cluster-scale counterpart).

    Each class has its own token distribution (a Dirichlet-ish softmax over
    the vocab, temperature ``class_sep``); sequences carry ragged lengths so
    pad-mask handling is exercised. Token id 0 is PAD.
    """
    k_logits, k_y, k_tok, k_len = jax.random.split(rng, 4)
    class_logits = jax.random.normal(k_logits, (n_classes, vocab_size - 1)) * class_sep
    y = jax.random.randint(k_y, (n,), 0, n_classes)
    if n * seq_len * vocab_size <= 1 << 28:
        toks = jax.random.categorical(
            k_tok, class_logits[y], axis=-1, shape=(seq_len, n)
        ).T
    else:
        # categorical broadcasts logits to [seq, n, vocab] — ~12 GB for the
        # long-context bench config (n=176, seq=2048, vocab=8192), which
        # RESOURCE_EXHAUSTs a 16 GB v5e before training even starts. Same
        # distribution via inverse-CDF: O(n*vocab + n*seq) memory. Different
        # draws for the same key, so the small-config branch above keeps the
        # recorded goldens' exact data.
        cdf = jnp.cumsum(jax.nn.softmax(class_logits, axis=-1), axis=-1)
        u = jax.random.uniform(k_tok, (n, seq_len))
        # f32 cumsum can end slightly below 1.0; a u above cdf[-1] would
        # index one past the support — clamp to the last real token
        toks = jnp.minimum(
            jax.vmap(jnp.searchsorted)(cdf[y], u), vocab_size - 2
        )
    toks = toks + 1  # reserve 0 for PAD
    lengths = jax.random.randint(k_len, (n,), seq_len // 2, seq_len + 1)
    mask = jnp.arange(seq_len)[None, :] < lengths[:, None]
    return jnp.where(mask, toks, 0).astype(jnp.int32), y.astype(jnp.int32)

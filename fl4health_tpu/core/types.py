"""Core type vocabulary for the framework.

The reference's wire currency is ``NDArrays`` (lists of NumPy arrays) shipped
over gRPC (/root/reference/fl4health/parameter_exchange/parameter_exchanger_base.py:8).
Here the currency is JAX pytrees: a client's model is a ``Params`` pytree, a
cohort of simulated clients is the same pytree with a leading ``clients`` axis
stacked onto every leaf ("client-stacked" trees), and aggregation is a jit
reduction over that axis.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp

# A pytree of jnp arrays holding model parameters (or any model-shaped state).
Params = Any
# A pytree with a leading clients axis on every leaf.
StackedParams = Any
PyTree = Any
PRNGKey = jax.Array
# Scalar metrics dictionary (values are 0-d arrays or python floats).
Metrics = Mapping[str, Any]
Config = Mapping[str, Any]

# A loss function ``(preds, targets) -> scalar``.
Criterion = Callable[[jax.Array, jax.Array], jax.Array]


class LoggingMode(enum.Enum):
    """Mirror of the reference's logging modes (utils/logging.py:4)."""

    TRAIN = "Training"
    VALIDATION = "Validation"
    TEST = "Testing"
    EARLY_STOP_VALIDATION = "Early Stop Validation"


def num_params(params: Params) -> int:
    return sum(int(jnp.size(x)) for x in jax.tree_util.tree_leaves(params))


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)

"""Single-worker FIFO job queue with cross-thread exception propagation.

Shared machinery for the async round pipeline's background workers
(``server.pipeline.RoundConsumer`` and
``checkpointing.async_writer.AsyncCheckpointWriter``): a bounded FIFO
executed by ONE daemon thread — so jobs run strictly in submission order —
with these contracts:

- ``submit`` blocks once ``maxsize`` jobs are pending (backpressure instead
  of unbounded host memory);
- the FIRST exception a job raises is stored, later jobs are skipped
  (drained, not run), and ``submit``/``flush``/``raise_pending`` re-raise it
  exactly once in the caller's thread;
- ``flush()`` is a completion barrier;
- ``close()`` drains, stops, joins, never raises — safe in ``finally``.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable


class SingleWorkerQueue:
    _STOP = object()

    def __init__(self, maxsize: int = 2, name: str = "fl-worker"):
        # maxsize<=0 would make the queue unbounded — the whole point is a
        # bounded pipeline, so clamp to at least one in-flight job.
        self._queue: queue.Queue = queue.Queue(max(1, int(maxsize)))
        self._exc: BaseException | None = None
        self._raised = False
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    @property
    def maxsize(self) -> int:
        return self._queue.maxsize

    def _loop(self) -> None:
        while True:
            job = self._queue.get()
            try:
                if job is self._STOP:
                    return
                if self._exc is None:  # after a failure, drain without running
                    try:
                        job()
                    except BaseException as e:  # noqa: BLE001 — must cross threads
                        self._exc = e
            finally:
                self._queue.task_done()

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue one job; blocks while ``maxsize`` jobs are pending.
        Re-raises a prior job's stored exception first, so the producer
        stops promptly after a failure."""
        if self._closed:
            raise RuntimeError(f"{type(self).__name__} is closed")
        self.raise_pending()
        self._queue.put(job)

    def flush(self) -> None:
        """Barrier: returns once every submitted job has finished (or been
        skipped after a failure); re-raises the stored exception."""
        self._queue.join()
        self.raise_pending()

    def raise_pending(self) -> None:
        """Re-raise the first worker exception (once)."""
        if self._exc is not None and not self._raised:
            self._raised = True
            raise self._exc

    def close(self) -> None:
        """Stop the worker and join it. Idempotent; never raises — callers
        check ``raise_pending``/``flush`` for errors before/instead."""
        if self._closed:
            return
        self._closed = True
        self._queue.put(self._STOP)
        self._thread.join()

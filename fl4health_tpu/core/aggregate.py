"""Aggregation kernels — the server-side reduction over the clients axis.

Parity target: /root/reference/fl4health/strategies/aggregate_utils.py:8,35
(weighted + unweighted averaging of client NDArrays) and the deterministic
summation-order property of utils/functions.py:84 (decode_and_pseudo_sort).

TPU-first design: client updates arrive as ONE pytree whose leaves carry a
leading ``clients`` axis (possibly sharded over a mesh axis named "clients").
Aggregation is a masked weighted mean along axis 0, compiled by XLA into a
reduce(+collective when sharded) — no per-client Python loop, and the reduction
order is fixed by the stacked layout, giving determinism by construction.

All functions accept an optional boolean ``mask`` (shape [clients]) so a
partially-sampled cohort (Poisson sampling can even be empty,
client_managers/poisson_sampling_manager.py:11) is handled inside jit with
static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.core.types import PyTree, StackedParams


def _expand(w: jax.Array, leaf: jax.Array) -> jax.Array:
    """Reshape [clients] weights to broadcast against [clients, ...] leaf."""
    return w.reshape((-1,) + (1,) * (leaf.ndim - 1))


def effective_weights(
    sample_counts: jax.Array,
    mask: jax.Array | None = None,
    weighted: bool = True,
) -> jax.Array:
    """Normalized aggregation weights over the clients axis.

    weighted=True  -> w_i = n_i / sum(n)   (aggregate_results weighted path)
    weighted=False -> w_i = 1 / |S|        (unweighted average)
    A zero-mask (empty cohort) yields all-zero weights rather than NaN.
    """
    counts = jnp.asarray(sample_counts, dtype=jnp.float32)
    m = jnp.ones_like(counts) if mask is None else jnp.asarray(mask, jnp.float32)
    raw = counts * m if weighted else m
    total = jnp.sum(raw)
    return jnp.where(total > 0, raw / jnp.maximum(total, 1e-12), jnp.zeros_like(raw))


def weighted_mean(stacked: StackedParams, weights: jax.Array) -> PyTree:
    """sum_i w_i * leaf_i along the clients axis; weights already normalized.

    Accumulates in float32 regardless of leaf dtype (bf16 params would lose
    ~1e-3 per round otherwise), and hard-zeroes weight-0 rows so a NaN/Inf in
    an unsampled client's slot cannot poison the aggregate (0 * NaN = NaN).
    """

    def _agg(leaf: jax.Array) -> jax.Array:
        w = _expand(weights.astype(jnp.float32), leaf)
        contrib = jnp.where(w > 0, leaf.astype(jnp.float32), 0.0) * w
        return jnp.sum(contrib, axis=0).astype(leaf.dtype)

    return jax.tree_util.tree_map(_agg, stacked)


def aggregate(
    stacked: StackedParams,
    sample_counts: jax.Array,
    mask: jax.Array | None = None,
    weighted: bool = True,
) -> PyTree:
    """Drop-in equivalent of the reference's aggregate_results."""
    return weighted_mean(stacked, effective_weights(sample_counts, mask, weighted))


def aggregate_losses(
    losses: jax.Array,
    sample_counts: jax.Array,
    mask: jax.Array | None = None,
    weighted: bool = True,
) -> jax.Array:
    """Scalar version (aggregate_utils.py:35)."""
    w = effective_weights(sample_counts, mask, weighted)
    return jnp.sum(jnp.asarray(losses, jnp.float32) * w)

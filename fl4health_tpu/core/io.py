"""Host filesystem helpers — dependency-light (no JAX imports) so the
observability primitives and reporters can use them freely.

One definition of the atomic-publish pattern (write temp file, then
``os.replace``): metrics/trace exports, reporter dumps, and state
checkpoints all publish artifacts that a concurrent reader (smoke-test
scraper, Prometheus scrape, resume-from-checkpoint) may open mid-run — a
crash mid-write must never leave a truncated file at the published path.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "w"):
    """Context manager yielding a file handle to a temp sibling of ``path``;
    on clean exit the temp file is atomically renamed over ``path``
    (parent directories are created), on exception it is removed and the
    previously-published file is left untouched."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            yield f
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

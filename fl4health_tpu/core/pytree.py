"""Pytree manipulation primitives underlying exchange, packing, and DP.

These replace the reference's NumPy list-of-arrays plumbing
(/root/reference/fl4health/parameter_exchange/parameter_packer.py) with
jit-compatible pytree transforms:

- flat-vector round trips (for clipping, drift norms, packing),
- leaf selection by path predicate (layer exchangers),
- client-axis stack/unstack (the SPMD "wire"),
- linear-algebra helpers (global norm, weighted sums) used everywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.flatten_util
import jax.numpy as jnp

from fl4health_tpu.core.types import PyTree, tree_zeros_like  # noqa: F401  (re-export)


# ---------------------------------------------------------------------------
# Path naming
# ---------------------------------------------------------------------------

def leaf_paths(tree: PyTree) -> list[str]:
    """Dotted string path for every leaf, in tree order.

    Plays the role of torch ``state_dict`` keys for layer-wise exchange
    (reference: parameter_exchange/layer_exchanger.py:17 keys on state_dict).
    """
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [_path_str(path) for path, _ in paths_leaves]


def _path_str(path: tuple) -> str:
    parts = []
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            parts.append(str(entry.key))
        elif isinstance(entry, jax.tree_util.SequenceKey):
            parts.append(str(entry.idx))
        elif isinstance(entry, jax.tree_util.GetAttrKey):
            parts.append(str(entry.name))
        else:  # FlattenedIndexKey and friends
            parts.append(str(getattr(entry, "key", entry)))
    return ".".join(parts)


def select_by_path(tree: PyTree, predicate: Callable[[str], bool]) -> PyTree:
    """Return a mask tree: True where the leaf's dotted path satisfies predicate."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    mask = [bool(predicate(_path_str(p))) for p, _ in paths_leaves]
    return jax.tree_util.tree_unflatten(treedef, mask)


def merge_by_mask(mask: PyTree, if_true: PyTree, if_false: PyTree) -> PyTree:
    """Leafwise select between two trees by a boolean mask tree."""
    return jax.tree_util.tree_map(
        lambda m, t, f: t if m else f, mask, if_true, if_false
    )


# ---------------------------------------------------------------------------
# Flat-vector round trips
# ---------------------------------------------------------------------------

def ravel(tree: PyTree) -> tuple[jax.Array, Callable[[jax.Array], PyTree]]:
    """Flatten a pytree to one 1-D vector; returns (vector, unravel_fn)."""
    flat, unravel = jax.flatten_util.ravel_pytree(tree)
    return flat, unravel


def global_norm(tree: PyTree) -> jax.Array:
    """l2 norm over all leaves (reference: losses/weight_drift_loss.py:5 uses
    per-tensor linalg.norm summed; we define the global norm and also expose
    per-leaf norms below)."""
    sq = sum(jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(tree))
    return jnp.sqrt(sq)


def leaf_norms(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(lambda x: jnp.linalg.norm(x.reshape(-1)), tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, c) -> PyTree:
    return jax.tree_util.tree_map(lambda x: x * c, tree)


def tree_axpy(a, x: PyTree, y: PyTree) -> PyTree:
    """a * x + y, leafwise."""
    return jax.tree_util.tree_map(lambda xi, yi: a * xi + yi, x, y)


def tree_dot(a: PyTree, b: PyTree) -> jax.Array:
    return sum(
        jnp.vdot(x, y)
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


# ---------------------------------------------------------------------------
# Client-axis stacking — the SPMD "wire format"
# ---------------------------------------------------------------------------

def stack_clients(trees: Sequence[PyTree]) -> PyTree:
    """Stack per-client pytrees along a new leading clients axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def unstack_clients(stacked: PyTree, n: int) -> list[PyTree]:
    return [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked) for i in range(n)]


def client_slice(stacked: PyTree, i) -> PyTree:
    return jax.tree_util.tree_map(lambda x: x[i], stacked)


def broadcast_clients(tree: PyTree, n: int) -> PyTree:
    """Replicate a tree n times along a new leading clients axis."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), tree
    )


# ---------------------------------------------------------------------------
# Casting helpers
# ---------------------------------------------------------------------------

def tree_astype(tree: PyTree, dtype) -> PyTree:
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree
    )


# ---------------------------------------------------------------------------
# Size accounting
# ---------------------------------------------------------------------------

def tree_nbytes(tree: PyTree) -> int:
    """Total byte footprint of a pytree's array leaves, from shape/dtype
    metadata only (works on concrete arrays AND ``jax.eval_shape`` structs;
    no device transfer). The ONE definition the observability byte
    accounting uses — payload wire-cost (server/simulation.py) and staged
    data stacks (clients/engine.py) must agree on what a byte is."""
    import numpy as np

    return int(sum(
        int(np.prod(leaf.shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    ))

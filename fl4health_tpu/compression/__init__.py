"""Communication-efficient exchange — in-graph compressed update codecs.

The lossy client->server channel of Konečný et al. (arXiv:1610.05492,
"Federated Learning: Strategies for Improving Communication Efficiency"),
built TPU-native: the encode->decode round trip is pure jittable math
compiled INTO the round programs, so chunked mode keeps one dispatch per
N rounds and both execution modes draw identical stochastic codes.

- :mod:`~fl4health_tpu.compression.config` — :class:`CompressionConfig`,
  the static codec recipe (top-k fraction, error feedback, int8/int4
  stochastic quantization, seeded random rotation);
- :mod:`~fl4health_tpu.compression.codecs` — the pure transforms (global
  magnitude top-k, per-leaf stochastic uniform quantization, randomized
  Hadamard rotation, error-feedback residual accounting) plus the shared
  wire-byte arithmetic (:func:`estimate_wire_nbytes`);
- :mod:`~fl4health_tpu.compression.strategy` —
  :class:`CompressingStrategy`, the wrapper that runs the channel inside
  ``Strategy.aggregate`` so any inner strategy (FedAvg, RobustFedAvg,
  QuarantiningStrategy, Scaffold) aggregates exactly what a real wire
  receiver would reconstruct.

The matching BYTE format for the cross-silo path (int8/int4 payloads,
gap-uint16 index sidecars, per-leaf scales, CRC framing) lives in
``transport/codec.py`` (``encode_compressed``/``decode_compressed``).
Enable end-to-end with ``FederatedSimulation(compression=
CompressionConfig(...))``; compression off keeps trajectories
bit-identical to an uncompressed build (pinned by tests/compression).
"""

from fl4health_tpu.compression.codecs import (
    compress_update,
    estimate_wire_nbytes,
    logical_nbytes,
    stochastic_quantize_leaf,
    topk_count,
    topk_mask,
)
from fl4health_tpu.compression.config import QUANT_LEVELS, CompressionConfig
from fl4health_tpu.compression.strategy import (
    CompressedExchangeState,
    CompressingStrategy,
)

__all__ = [
    "CompressionConfig",
    "QUANT_LEVELS",
    "CompressingStrategy",
    "CompressedExchangeState",
    "compress_update",
    "estimate_wire_nbytes",
    "logical_nbytes",
    "stochastic_quantize_leaf",
    "topk_count",
    "topk_mask",
]

"""CompressionConfig — the static recipe for the compressed exchange.

One frozen dataclass describes the whole codec pipeline (Konečný et al.,
arXiv:1610.05492 "structured and sketched updates"): top-k sparsification
with error feedback, stochastic uniform int8/int4 quantization with
per-leaf scales, and an optional seeded random-rotation (randomized
Hadamard) preconditioner. Every field is compile-time config — the
in-graph transforms (compression/codecs.py) trace it into the round
programs, and the wire codec (transport/codec.py encode_compressed) uses
the same recipe for the cross-silo byte format, so the simulated lossy
exchange and the real wire agree on what was kept.
"""

from __future__ import annotations

import dataclasses

#: bits -> max quantization level L of the symmetric signed grid
#: {-L, ..., -1, 0, 1, ..., L}; int8 uses the full signed-byte range less
#: the asymmetric -128, int4 the signed-nibble range less -8.
QUANT_LEVELS = {8: 127, 4: 7}


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """Static codec recipe for client->server update compression.

    - ``topk_fraction``: keep only this fraction of the update's
      coordinates (global magnitude top-k over the flat update, matching
      :class:`~fl4health_tpu.exchange.exchanger.SparseExchanger`
      semantics); ``None`` disables sparsification.
    - ``error_feedback``: carry each client's unsent mass (sparsification
      + quantization error) in a per-client residual that is added to the
      next round's update before encoding (SEC/EF-SGD memory). Only
      meaningful when a lossy stage is enabled.
    - ``quant_bits``: stochastic uniform quantization of the (selected)
      values to a symmetric signed int8/int4 grid with one scale per
      leaf; ``None`` ships f32 values.
    - ``rotation``: precondition each leaf with a seeded randomized
      Hadamard transform before top-k/quantization (spreads outlier
      coordinates so a uniform grid wastes less range); the decode side
      applies the inverse rotation with the same seed.
    - ``seed``: base seed for every stochastic draw (rotation signs,
      quantization rounding); folded with the round index and client index
      so both execution modes draw identically.
    """

    topk_fraction: float | None = None
    error_feedback: bool = True
    quant_bits: int | None = None
    rotation: bool = False
    seed: int = 0
    #: Optional per-round adaptive kept-fraction schedule
    #: ``("linear", f_start, f_end, over_rounds)``: the EFFECTIVE kept
    #: fraction interpolates f_start -> f_end over the first
    #: ``over_rounds`` rounds (then holds f_end), as a TRACED function of
    #: the round index inside the compiled round programs — zero
    #: recompiles across rounds, and the schedule endpoints are hoistable
    #: sweep scalars (fl4health_tpu/sweep/). ``topk_fraction`` stays the
    #: STATIC ceiling: it fixes the selection shape (k = top-k slots, the
    #: wire sidecar size), so both endpoints must be <= it; coordinates
    #: ranked past the effective fraction are zeroed (their mass lands in
    #: the EF residual like any unsent mass). ``None`` = constant
    #: ``topk_fraction``, bit-identical to the pre-schedule codec.
    topk_schedule: tuple | None = None

    def __post_init__(self):
        if self.topk_fraction is not None and not (
            0.0 < self.topk_fraction <= 1.0
        ):
            raise ValueError(
                f"topk_fraction must be in (0, 1]; got {self.topk_fraction}"
            )
        if self.topk_schedule is not None:
            if self.topk_fraction is None:
                raise ValueError(
                    "topk_schedule needs topk_fraction as its static "
                    "ceiling (the selection shape and wire sidecar are "
                    "sized by it)"
                )
            s = self.topk_schedule
            if (len(s) != 4 or s[0] != "linear"):
                raise ValueError(
                    "topk_schedule must be ('linear', f_start, f_end, "
                    f"over_rounds); got {s!r}"
                )
            _, f0, f1, over = s
            for name, f in (("f_start", f0), ("f_end", f1)):
                if not 0.0 < float(f) <= self.topk_fraction:
                    raise ValueError(
                        f"topk_schedule {name}={f} must be in (0, "
                        f"topk_fraction={self.topk_fraction}] — the static "
                        "ceiling fixes the compiled selection shape"
                    )
            if int(over) < 1:
                raise ValueError(
                    f"topk_schedule over_rounds must be >= 1; got {over}"
                )
        if self.quant_bits is not None and self.quant_bits not in QUANT_LEVELS:
            raise ValueError(
                f"quant_bits must be one of {sorted(QUANT_LEVELS)}; "
                f"got {self.quant_bits}"
            )
        if self.rotation and self.quant_bits is None:
            raise ValueError(
                "rotation is a quantization preconditioner; enable "
                "quant_bits with it (rotation alone is lossless and only "
                "spends compute)"
            )

    @property
    def enabled(self) -> bool:
        """True when any lossy stage is configured."""
        return self.topk_fraction is not None or self.quant_bits is not None

    @property
    def uses_error_feedback(self) -> bool:
        return self.error_feedback and self.enabled

    def describe(self) -> dict:
        """JSON-able config facts (run manifest / bench artifacts)."""
        out = {
            "topk_fraction": self.topk_fraction,
            "error_feedback": self.uses_error_feedback,
            "quant_bits": self.quant_bits,
            "rotation": self.rotation,
            "seed": self.seed,
        }
        if self.topk_schedule is not None:
            # absent on constant-fraction configs so legacy manifest
            # config hashes stay stable
            out["topk_schedule"] = list(self.topk_schedule)
        return out

"""In-graph compressed-update codecs — pure jittable encode/decode.

The lossy channel of Konečný et al. (arXiv:1610.05492), expressed as pure
functions over pytrees so the whole encode->decode round trip compiles
INTO the round programs: chunked mode keeps one dispatch per N rounds, and
pipelined/chunked trajectories stay bit-identical because every stochastic
draw is a counter-based ``fold_in`` of (seed, round, client).

Pipeline (per client, on the update ``packet - broadcast_reference``):

1. add the client's error-feedback residual (unsent mass from earlier
   rounds, SEC/EF-SGD memory) when enabled;
2. optional seeded randomized-Hadamard rotation per leaf (sign flip by a
   Rademacher diagonal, then an orthonormal fast Walsh-Hadamard
   transform): spreads outlier coordinates so the uniform quantization
   grid wastes less range;
3. optional global magnitude top-k over the flat update (the
   :class:`~fl4health_tpu.exchange.exchanger.SparseExchanger` selection
   rule: exact top-k, ties broken by lowest index);
4. optional stochastic uniform quantization of the surviving values to a
   symmetric signed int8/int4 grid with one scale per leaf — unbiased
   given the scale (``E[decode(encode(v))] = v``);
5. decode (dequantize, inverse-rotate) immediately — aggregation consumes
   the reconstruction a real wire receiver would see;
6. the new residual is ``(update + old_residual) - decoded``: exactly the
   mass this round failed to transmit.

The matching *byte* format for the cross-silo path lives in
``transport/codec.py`` (``encode_compressed``/``decode_compressed``);
:func:`estimate_wire_nbytes` is the shared arithmetic both the simulation's
``fl_wire_*`` accounting and ``bench.py`` use for it.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from fl4health_tpu.compression.config import QUANT_LEVELS, CompressionConfig
from fl4health_tpu.core.types import PyTree
from fl4health_tpu.observability import stages as stage_attr


# ---------------------------------------------------------------------------
# Randomized Hadamard rotation
# ---------------------------------------------------------------------------

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _fwht(x: jax.Array) -> jax.Array:
    """Orthonormal fast Walsh-Hadamard transform of a length-2^m vector.

    Static Python loop over log2(n) butterfly stages — shapes are
    compile-time constants, so the whole transform fuses under jit. The
    orthonormal scaling (1/sqrt(n)) makes the transform an involution:
    ``_fwht(_fwht(x)) == x`` up to float round-off."""
    n = x.shape[0]
    h = 1
    while h < n:
        x = x.reshape(-1, 2, h)
        a, b = x[:, 0, :], x[:, 1, :]
        x = jnp.stack([a + b, a - b], axis=1).reshape(-1)
        h *= 2
    return x / jnp.sqrt(jnp.float32(n))


def _rotation_signs(seed: int, leaf_idx: int, n_pad: int) -> jax.Array:
    """Rademacher diagonal for one leaf's rotation — a FIXED draw from
    (config.seed, leaf index), shared by encoder and decoder (and, on a
    real wire, by client and server) without any per-round negotiation."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), leaf_idx)
    return jax.random.rademacher(key, (n_pad,), jnp.float32)


def rotate_leaf(flat: jax.Array, signs: jax.Array) -> jax.Array:
    """Flat leaf -> rotated padded vector (length next_pow2(n))."""
    n_pad = signs.shape[0]
    padded = jnp.zeros((n_pad,), jnp.float32).at[: flat.shape[0]].set(
        flat.astype(jnp.float32)
    )
    return _fwht(padded * signs)


def unrotate_leaf(rotated: jax.Array, signs: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`rotate_leaf` (orthonormal H is its own inverse;
    the Rademacher diagonal squares to identity); truncates the padding."""
    return (signs * _fwht(rotated))[:n]


# ---------------------------------------------------------------------------
# Top-k selection
# ---------------------------------------------------------------------------

def topk_count(n_total: int, fraction: float) -> int:
    """Static k for a global top-k over ``n_total`` coordinates."""
    return max(1, min(n_total, int(round(fraction * n_total))))


def topk_mask(flat: jax.Array, k: int,
              k_effective: jax.Array | None = None) -> jax.Array:
    """0/1 mask keeping the ``k`` largest-magnitude coordinates.

    ``jax.lax.top_k`` is deterministic (ties broken by lowest index), so
    the same values always produce the same mask — across calls, backends
    and execution modes (pinned by tests/exchange + tests/compression).

    ``k_effective`` (optional TRACED i32 scalar in ``[1, k]``) keeps only
    the first ``k_effective`` of the ``k`` selected slots — the adaptive
    per-round fraction of ``CompressionConfig.topk_schedule``. The
    selection SHAPE stays ``k`` (static), only rank weights change, so an
    adaptive-fraction run never recompiles. ``None`` is bit-identical to
    the historical constant-``k`` mask."""
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    if k_effective is None:
        return jnp.zeros_like(flat, jnp.float32).at[idx].set(1.0)
    keep = (jnp.arange(k) < k_effective).astype(jnp.float32)
    return jnp.zeros_like(flat, jnp.float32).at[idx].set(keep)


# ---------------------------------------------------------------------------
# Stochastic uniform quantization
# ---------------------------------------------------------------------------

def stochastic_quantize_leaf(
    flat: jax.Array, bits: int, key: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(quantized ints [as f32], scale) for one leaf's flat values.

    Symmetric signed grid {-L..L}, one scale per leaf: ``scale =
    max|v|/L``; stochastic rounding makes the dequantized value unbiased
    given the scale. An all-zero leaf keeps scale 0 and quantizes to 0. A
    NaN/Inf leaf quantizes to NaN — a poisoned submission must stay
    VISIBLY poisoned through the channel (the robust aggregators and the
    quarantine nonfinite signal key off it), never silently launder to
    zeros."""
    if flat.size == 0:
        # zero-size leaf: jnp.max has no identity; ship it as-is
        return flat.astype(jnp.float32), jnp.zeros((), jnp.float32)
    L = QUANT_LEVELS[bits]
    vmax = jnp.max(jnp.abs(flat))
    scale = vmax / L
    safe = jnp.where(scale > 0, scale, 1.0)
    y = flat / safe
    lower = jnp.floor(y)
    frac = y - lower
    q = lower + jax.random.bernoulli(key, jnp.clip(frac, 0.0, 1.0)).astype(
        jnp.float32
    )
    q = jnp.clip(q, -L, L)
    q = jnp.where(scale > 0, q, 0.0)
    return jnp.where(jnp.isfinite(vmax), q, jnp.nan), scale


def dequantize_leaf(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q * scale


# ---------------------------------------------------------------------------
# The full encode->decode transform over an update pytree
# ---------------------------------------------------------------------------

def compress_update(
    update: PyTree,
    residual: PyTree | None,
    key: jax.Array,
    config: CompressionConfig,
    topk_fraction_eff: jax.Array | None = None,
) -> tuple[PyTree, PyTree | None]:
    """Lossy-channel round trip for ONE client's update pytree.

    Returns ``(decoded_update, new_residual)`` where ``decoded_update`` is
    what the server-side decoder reconstructs and ``new_residual`` the
    error-feedback memory (``None`` in == ``None`` out). Pure and
    jit/vmap-compatible; with no lossy stage enabled it is the identity.

    ``topk_fraction_eff`` (optional TRACED f32 scalar) is the round's
    effective kept fraction under ``config.topk_schedule`` — clamped into
    ``[1/n, config.topk_fraction]`` and applied as rank weights over the
    static top-``k`` selection, so the compiled shape never changes.
    ``None`` keeps the constant ``config.topk_fraction`` bit-identically.
    """
    if not config.enabled:
        return update, residual

    leaves, treedef = jax.tree_util.tree_flatten(update)
    res_leaves = (jax.tree_util.tree_leaves(residual)
                  if residual is not None else [None] * len(leaves))
    sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
    n_total = sum(sizes)
    if n_total == 0:
        # an all-empty update tree has nothing to select or scale
        return update, residual

    # 1. flat f32 working vectors (+ error feedback)
    flats = []
    for leaf, res in zip(leaves, res_leaves):
        v = leaf.astype(jnp.float32).reshape(-1)
        if res is not None:
            v = v + res.astype(jnp.float32).reshape(-1)
        flats.append(v)
    carried = flats  # pre-rotation domain, for the residual below

    # 2. rotation (per leaf, fixed seeded Rademacher + orthonormal FWHT)
    signs = None
    if config.rotation:
        with stage_attr.stage("rotation"):
            signs = [
                _rotation_signs(config.seed, i, _next_pow2(sizes[i]))
                for i in range(len(flats))
            ]
            flats = [rotate_leaf(v, s) for v, s in zip(flats, signs)]

    # 3. global magnitude top-k over the concatenated update
    if config.topk_fraction is not None:
        with stage_attr.stage("topk"):
            n_sel = sum(v.shape[0] for v in flats)  # padded under rotation
            k = topk_count(n_total, config.topk_fraction)
            k_eff = None
            if topk_fraction_eff is not None:
                # same arithmetic as the static topk_count, in-graph: round()
                # matches Python round's half-to-even, clamps keep >=1 slot
                k_eff = jnp.clip(
                    jnp.round(topk_fraction_eff * n_total).astype(jnp.int32),
                    1, min(k, n_sel),
                )
            mask = topk_mask(jnp.concatenate(flats), min(k, n_sel), k_eff)
            out, off = [], 0
            for v in flats:
                out.append(v * mask[off: off + v.shape[0]])
                off += v.shape[0]
            flats = out

    # 4. stochastic quantization, one scale per leaf
    if config.quant_bits is not None:
        with stage_attr.stage("quantize"):
            out = []
            for i, v in enumerate(flats):
                q, scale = stochastic_quantize_leaf(
                    v, config.quant_bits, jax.random.fold_in(key, i)
                )
                out.append(dequantize_leaf(q, scale))
            flats = out

    # 5. decode back to the original domain
    if config.rotation:
        with stage_attr.stage("rotation"):
            flats = [
                unrotate_leaf(v, s, n)
                for v, s, n in zip(flats, signs, sizes)
            ]

    # integer leaves round rather than truncate toward zero (parity with
    # the wire decoder's rule in transport/codec.py); `flats` becomes the
    # DELIVERED values so the residual below accounts the rounding too
    flats = [
        jnp.rint(v) if jnp.issubdtype(leaf.dtype, jnp.integer) else v
        for v, leaf in zip(flats, leaves)
    ]
    decoded = [
        v.reshape(leaf.shape).astype(leaf.dtype)
        for v, leaf in zip(flats, leaves)
    ]

    # 6. error feedback: exactly the mass this round failed to transmit.
    # Non-finite residual entries reset to 0 — EF memory must not carry a
    # poisoned (NaN/Inf) submission into every later round.
    new_residual = residual
    if residual is not None:
        res_out = []
        for v_pre, dec, res in zip(carried, flats, res_leaves):
            r = (v_pre - dec).astype(res.dtype).reshape(res.shape)
            res_out.append(jnp.where(jnp.isfinite(r), r, 0.0))
        new_residual = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(residual), res_out
        )

    return jax.tree_util.tree_unflatten(treedef, decoded), new_residual


# ---------------------------------------------------------------------------
# Wire-byte arithmetic (shared with transport/codec.py + bench.py)
# ---------------------------------------------------------------------------

def estimate_wire_nbytes(tree: PyTree, config: CompressionConfig) -> int:
    """Estimated compressed client->server PAYLOAD bytes for one client's
    update under ``config`` — the arithmetic the wire codec's frames
    realize (gap-uint16 index sidecar + int8/int4/f32 values + one f32
    scale per leaf; JSON header excluded). Works from shape/dtype metadata
    only (concrete arrays or ``jax.eval_shape`` structs)."""
    sizes = [
        int(np.prod(l.shape, dtype=np.int64)) if getattr(l, "shape", ()) else 1
        for l in jax.tree_util.tree_leaves(tree)
    ]
    n_total = int(sum(sizes))
    if not config.enabled or n_total == 0:
        return 4 * n_total
    if config.topk_fraction is not None:
        nnz = topk_count(n_total, config.topk_fraction)
        index_bytes = 2 * nnz  # uint16 gap encoding (escapes ~0 at <50% density)
    else:
        nnz = n_total
        index_bytes = 0
    if config.quant_bits is not None:
        value_bytes = math.ceil(nnz * config.quant_bits / 8)
        scale_bytes = 4 * len(sizes)
    else:
        value_bytes = 4 * nnz
        scale_bytes = 0
    return index_bytes + value_bytes + scale_bytes


def logical_nbytes(tree: PyTree) -> int:
    """Dense f32 byte footprint of the same update (the logical payload)."""
    from fl4health_tpu.core.pytree import tree_nbytes

    return tree_nbytes(tree)

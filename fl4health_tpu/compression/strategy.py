"""CompressingStrategy — the lossy exchange channel as a strategy wrapper.

The SPMD "wire" is the stacked packet pytree the round program hands to
``Strategy.aggregate``; compression therefore lives exactly there: a
wrapper that encode->decodes every client's update through the configured
lossy channel (``compression/codecs.py``) BEFORE the inner strategy
aggregates, inside the compiled round programs on both execution modes.
The inner strategy — ``FedAvg``, ``RobustFedAvg``,
``QuarantiningStrategy(...)``, ``Scaffold`` — sees exactly what a real
wire receiver would have reconstructed, so robustness/quarantine claims
under compression are tested against the genuine lossy updates.

Error-feedback residual state is per-client ``[C, ...]`` and rides in the
server-state pytree (:class:`CompressedExchangeState`), so it scans,
donates and checkpoints like every other server state. Residuals update
only for clients in the round's aggregation mask — an unsampled (or
failure-screened) client's garbage packet row never enters its memory.

DP composition (documented check, tests/compression): the instance-level
DP path clips + noises per-example gradients INSIDE local training
(privacy/dpsgd.py), i.e. strictly before the packet exists. Compression
consumes only ``FitResults.packets`` — it is post-processing of the
already-privatized release, so the DP guarantee (and the accountant's
sigma) is unchanged by quantization/sparsification.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.compression.codecs import compress_update
from fl4health_tpu.compression.config import CompressionConfig
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class CompressedExchangeState:
    """Wrapper server state: the inner strategy's state + per-client
    error-feedback residual (``None`` when error feedback is off)."""

    inner: Any
    residual: Any


class CompressingStrategy(Strategy):
    """Wrap any strategy with the in-graph lossy exchange channel.

    The main update (``packets`` itself, or the ``params`` field of a
    structured packet) is compressed relative to what the clients pulled
    this round (``inner.client_payload``), with per-client error-feedback
    residuals when configured. A ``control_variates`` field (SCAFFOLD's
    auxiliary packet, exchange/packer.py) is compressed too — statelessly,
    against a zero reference, since the EF residual tree is shaped by
    ``init`` before the packet layout exists.

    Masked/partial-exchange packet layouts (``LayerMaskPacket`` /
    ``SparseMaskPacket``) are rejected at trace time: their zeroed
    non-selected entries would read as real ``-reference`` deltas and the
    residual would accumulate junk. Compose compression with full-model
    exchange (the reference's sketched-update setting).

    ``n_clients`` is normally learned from ``bind_client_manager`` (the
    simulation calls it before ``init``); pass it explicitly for direct
    use.
    """

    def __init__(
        self,
        inner: Strategy,
        config: CompressionConfig,
        n_clients: int | None = None,
    ):
        if not isinstance(config, CompressionConfig):
            raise TypeError(
                f"config must be a CompressionConfig; got {type(config).__name__}"
            )
        if not config.enabled:
            raise ValueError(
                "CompressionConfig has no lossy stage enabled; drop the "
                "wrapper instead of compiling an identity channel"
            )
        self.inner = inner
        self.config = config
        self._n_clients = n_clients
        # Adaptive top-k schedule endpoints as PLAIN ATTRS (not the frozen
        # config): they are read at trace time inside aggregate(), which
        # makes them hoistable sweep scalars — the sweep engine rebinds
        # them to traced program inputs so a schedule sweep shares one
        # compiled round program (fl4health_tpu/sweep/hoisting.py). The
        # static ceiling config.topk_fraction is NOT hoistable: it sizes
        # the top-k selection shape.
        if config.topk_schedule is not None:
            _, f0, f1, over = config.topk_schedule
            self.topk_f_start = float(f0)
            self.topk_f_end = float(f1)
            self.topk_over_rounds = int(over)
        else:
            self.topk_f_start = self.topk_f_end = None
            self.topk_over_rounds = None
        self.weighted_aggregation = inner.weighted_aggregation
        self.weighted_eval_aggregation = inner.weighted_eval_aggregation
        # chunk-eligibility passthrough (server/simulation.py consults this
        # before the type-level check): only a host-consuming INNER
        # update_after_eval should force the pipelined path
        inner_overrides = getattr(inner, "overrides_update_after_eval", None)
        if inner_overrides is None:
            inner_overrides = (type(inner).update_after_eval
                               is not Strategy.update_after_eval)
        self.overrides_update_after_eval = inner_overrides
        # quarantine visibility passthrough: the simulation snapshots
        # strategy.quarantine_mask per round when present
        inner_qmask = getattr(inner, "quarantine_mask", None)
        if inner_qmask is not None:
            self.quarantine_mask = (
                lambda server_state: inner_qmask(server_state.inner)
            )

    @property
    def evaluate_after_fit(self) -> bool:
        return bool(getattr(self.inner, "evaluate_after_fit", False))

    def bind_client_manager(self, client_manager: Any) -> None:
        self._n_clients = client_manager.n_clients
        bind = getattr(self.inner, "bind_client_manager", None)
        if bind is not None:
            bind(client_manager)

    def init(self, params) -> CompressedExchangeState:
        residual = None
        if self.config.uses_error_feedback:
            if self._n_clients is None:
                raise ValueError(
                    "CompressingStrategy with error feedback needs "
                    "n_clients: pass it to the constructor or let "
                    "FederatedSimulation bind its client manager first"
                )
            n = self._n_clients
            residual = jax.tree_util.tree_map(
                lambda p: jnp.zeros((n, *p.shape), jnp.float32), params
            )
        return CompressedExchangeState(
            inner=self.inner.init(params), residual=residual
        )

    def global_params(self, server_state: CompressedExchangeState):
        return self.inner.global_params(server_state.inner)

    def state_sharding_spec(self, server_state: CompressedExchangeState,
                            clients_axis: str):
        """On a client mesh the per-client ``[C, ...]`` EF residual stack
        shards over the clients axis (it is client-local state — replicating
        it would multiply its footprint by the device count); the inner
        strategy's state follows its own spec (replicated by default)."""
        from jax.sharding import PartitionSpec as P

        from fl4health_tpu.strategies.base import inner_state_sharding_spec

        residual_spec = (P(clients_axis) if server_state.residual is not None
                         else None)
        return CompressedExchangeState(
            inner=inner_state_sharding_spec(
                self.inner, server_state.inner, clients_axis
            ),
            residual=residual_spec,
        )

    def state_rows(self, server_state: CompressedExchangeState):
        """Per-client ``[C, ...]`` EF residual rows (``None`` subtree when
        error feedback is off) plus the inner strategy's rows, for
        cohort-slot gather/scatter (``server/registry.py``): each client's
        residual follows it in and out of the sampled cohort, so error
        feedback stays exact under partial participation."""
        return {
            "residual": server_state.residual,
            "inner": self.inner.state_rows(server_state.inner),
        }

    def scatter_state_rows(self, server_state: CompressedExchangeState, rows):
        return CompressedExchangeState(
            inner=self.inner.scatter_state_rows(
                server_state.inner, rows["inner"]
            ),
            residual=rows["residual"],
        )

    def divergence_reference(self, server_state: CompressedExchangeState):
        return self.inner.divergence_reference(server_state.inner)

    def client_payload(self, server_state: CompressedExchangeState, round_idx):
        return self.inner.client_payload(server_state.inner, round_idx)

    # -- the channel ----------------------------------------------------

    def _round_key(self, round_idx) -> jax.Array:
        return jax.random.fold_in(
            jax.random.PRNGKey(self.config.seed), round_idx
        )

    def effective_topk_fraction(self, round_idx):
        """The round's kept fraction under ``config.topk_schedule`` — a
        traced linear interpolation ``f_start -> f_end`` over the first
        ``over_rounds`` rounds (1-based; holds ``f_end`` after), clamped
        into ``(0, topk_fraction]``. ``None`` without a schedule (the
        constant-fraction codec path, bit-identical to pre-schedule)."""
        if self.topk_f_start is None:
            return None
        if self.topk_over_rounds <= 1:
            # a 1-round ramp IS f_end from round 1 (the generic formula's
            # (r-1)/(T-1) denominator would silently make it a 2-round one)
            t = jnp.ones((), jnp.float32)
        else:
            t = jnp.clip(
                (jnp.asarray(round_idx, jnp.float32) - 1.0)
                / (float(self.topk_over_rounds) - 1.0),
                0.0, 1.0,
            )
        f = self.topk_f_start + (self.topk_f_end - self.topk_f_start) * t
        return jnp.clip(f, 1e-9, float(self.config.topk_fraction))

    def _compress_stacked(
        self, stacked, reference, residuals, round_key, mask,
        topk_fraction_eff=None,
    ):
        """vmap the per-client channel over the ``[C, ...]`` packet stack.

        ``reference`` is what every client pulled (broadcast, unstacked);
        residual rows update only where ``mask`` participates."""
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        keys = jax.vmap(
            lambda i: jax.random.fold_in(round_key, i)
        )(jnp.arange(n))

        def one(packet_c, residual_c, key_c):
            update = jax.tree_util.tree_map(
                lambda p, r: p.astype(jnp.float32) - r.astype(jnp.float32),
                packet_c, reference,
            )
            decoded, new_res = compress_update(
                update, residual_c, key_c, self.config,
                topk_fraction_eff=topk_fraction_eff,
            )
            def cast_back(r, d):
                v = r.astype(jnp.float32) + d
                if jnp.issubdtype(r.dtype, jnp.integer):
                    # round, don't truncate toward zero — same rule as both
                    # decoders (codecs.compress_update, codec.decode_compressed)
                    v = jnp.rint(v)
                return v.astype(r.dtype)

            lossy = jax.tree_util.tree_map(cast_back, reference, decoded)
            return lossy, new_res

        lossy, new_res = jax.vmap(one)(stacked, residuals, keys)
        if residuals is not None:
            keep = jnp.asarray(mask) > 0
            new_res = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    keep.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
                ),
                new_res, residuals,
            )
        return lossy, new_res

    def aggregate(
        self,
        server_state: CompressedExchangeState,
        results: FitResults,
        round_idx,
    ) -> CompressedExchangeState:
        packets = results.packets
        for bad in ("leaf_mask", "element_mask"):
            if hasattr(packets, bad):
                raise ValueError(
                    f"CompressingStrategy cannot compress {type(packets).__name__} "
                    "packets (masked partial exchange): zeroed non-selected "
                    "entries would read as real deltas. Use full-model "
                    "exchange with compression."
                )
        payload = self.inner.client_payload(server_state.inner, round_idx)
        reference = payload.params if hasattr(payload, "params") else payload
        main = packets.params if hasattr(packets, "params") else packets
        ref_def = jax.tree_util.tree_structure(reference)
        if jax.tree_util.tree_structure(main) != ref_def:
            raise ValueError(
                "CompressingStrategy: packet params structure "
                f"{jax.tree_util.tree_structure(main)} does not match the "
                f"broadcast payload structure {ref_def}; compression needs "
                "param-shaped packets (full-model exchange)."
            )
        round_key = self._round_key(round_idx)
        lossy_main, new_residual = self._compress_stacked(
            main, reference, server_state.residual, round_key, results.mask,
            topk_fraction_eff=self.effective_topk_fraction(round_idx),
        )
        if hasattr(packets, "params"):
            new_packets = packets.replace(params=lossy_main)
        else:
            new_packets = lossy_main
        if hasattr(packets, "control_variates"):
            # SCAFFOLD auxiliary packet: same channel, zero reference (the
            # field is already a delta), stateless (no EF memory)
            cv = packets.control_variates
            cv_ref = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape[1:], jnp.float32), cv
            )
            lossy_cv, _ = self._compress_stacked(
                cv, cv_ref, None,
                jax.random.fold_in(round_key, 0x5CAF), results.mask,
                topk_fraction_eff=self.effective_topk_fraction(round_idx),
            )
            new_packets = new_packets.replace(control_variates=lossy_cv)
        new_inner = self.inner.aggregate(
            server_state.inner, results.replace(packets=new_packets),
            round_idx,
        )
        return CompressedExchangeState(inner=new_inner, residual=new_residual)

    def update_after_eval(
        self, server_state: CompressedExchangeState, eval_losses,
        eval_metrics, mask,
    ) -> CompressedExchangeState:
        new_inner = self.inner.update_after_eval(
            server_state.inner, eval_losses, eval_metrics, mask
        )
        return server_state.replace(inner=new_inner)

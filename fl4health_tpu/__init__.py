"""fl4health_tpu — a TPU-native federated-learning framework.

A ground-up JAX/XLA re-design of the capabilities of VectorInstitute/FL4Health
(reference layer map in SURVEY.md §1). Instead of a gRPC client/server process
model (Flower), the core runtime is an in-process SPMD simulator: simulated
clients are entries along a ``clients`` mesh axis, one federated round is a
single jit-compiled program

    broadcast -> shard_map/vmap(local_train_steps) -> weighted psum aggregate

and server "strategies" are pure functions over stacked client updates. A thin
host-level transport (``fl4health_tpu.transport``) retains a wire contract for
genuinely distributed (cross-silo) deployment.
"""

__version__ = "0.1.0"

"""Pallas TPU kernels for hot ops (interpret-mode fallback elsewhere)."""

from fl4health_tpu.kernels.dp_clip import (
    fused_clipped_masked_sum,
    per_example_sq_norms,
    scaled_masked_sum,
)
from fl4health_tpu.kernels.flash_attention import (
    flash_attention,
    flash_attention_lse,
)

__all__ = [
    "fused_clipped_masked_sum",
    "per_example_sq_norms",
    "scaled_masked_sum",
    "flash_attention",
    "flash_attention_lse",
]

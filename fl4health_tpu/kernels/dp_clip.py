"""Pallas kernels for the DP-SGD hot path — fused per-example clip + reduce.

The XLA path (privacy/dpsgd.py) makes three full passes over the [B, D]
per-example gradient tensor: (1) squared-norm reduction, (2) scale-and-write
the clipped tensor, (3) masked sum over B. Passes 2+3 materialize and then
re-read a [B, D] intermediate — pure HBM bandwidth, the dominant cost for
big models (D ~ 10^6-10^8 per batch). These kernels do it in TWO passes and
never materialize the clipped tensor:

    pass 1  sq_norms:   per leaf [B, W] -> [B]  (tiled over W, summed
                                                 across leaves)
    pass 2  scaled sum: per leaf [B, W] -> [W]  (clip scale folded in)

Both kernels tile D into lane-aligned blocks with the whole batch resident
per block (B is small in DP training; the [B, TILE] block fits VMEM). On
non-TPU backends the kernels run in Pallas interpret mode, so the same code
path is exercised by the CPU test suite; `fused_clipped_masked_sum` is the
drop-in used by privacy.dpsgd when enabled.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from fl4health_tpu.core.types import Params
from fl4health_tpu.observability import stages as stage_attr

_LANE = 128


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _interpret_default() -> bool:
    # interpret only where Mosaic cannot compile (XLA:CPU); any non-cpu
    # backend (incl. the axon plugin, whatever platform string it reports)
    # gets the real kernels
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# Pass 1: per-example squared norms
# ---------------------------------------------------------------------------

def _sq_norm_kernel(g_ref, out_ref):
    i = pl.program_id(0)
    partial = jnp.sum(jnp.square(g_ref[:].astype(jnp.float32)), axis=1,
                      keepdims=True)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = partial

    @pl.when(i > 0)
    def _acc():
        out_ref[:] += partial


def _effective_tile(width: int, tile: int) -> int:
    """Clamp the tile to the leaf's lane-rounded width: a [B, 10] bias pads
    to one 128-lane tile, not a full 2048 — small leaves must not reduce
    thousands of zero columns per pass."""
    return min(tile, max(_LANE, -(-width // _LANE) * _LANE))


def per_example_sq_norms(
    flat_grads: jax.Array, tile: int = 2048, interpret: bool | None = None
) -> jax.Array:
    """[B, D] -> [B] squared l2 norms, one pass, D tiled."""
    if interpret is None:
        interpret = _interpret_default()
    b, d = flat_grads.shape
    tile = _effective_tile(d, tile)
    g = _pad_to(flat_grads, 1, tile)
    n_tiles = g.shape[1] // tile
    out = pl.pallas_call(
        _sq_norm_kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((b, tile), lambda i: (0, i))],
        out_specs=pl.BlockSpec((b, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.float32),
        interpret=interpret,
    )(g)
    return out[:, 0]


# ---------------------------------------------------------------------------
# Pass 2: scaled masked sum (the clipped tensor never exists)
# ---------------------------------------------------------------------------

def _scaled_sum_kernel(scale_ref, g_ref, out_ref):
    out_ref[:] = jnp.sum(
        g_ref[:].astype(jnp.float32) * scale_ref[:].astype(jnp.float32),
        axis=0, keepdims=True,
    )


def scaled_masked_sum(
    flat_grads: jax.Array, scale: jax.Array, tile: int = 2048,
    interpret: bool | None = None,
) -> jax.Array:
    """sum_i scale[i] * g[i]  ([B, D], [B] -> [D]), one pass, D tiled."""
    if interpret is None:
        interpret = _interpret_default()
    b, d = flat_grads.shape
    tile = _effective_tile(d, tile)
    g = _pad_to(flat_grads, 1, tile)
    n_tiles = g.shape[1] // tile
    out = pl.pallas_call(
        _scaled_sum_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((b, 1), lambda i: (0, 0)),
            pl.BlockSpec((b, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, g.shape[1]), jnp.float32),
        interpret=interpret,
    )(scale[:, None], g)
    return out[0, :d]


# ---------------------------------------------------------------------------
# The fused DP reduction over a gradient pytree
# ---------------------------------------------------------------------------

def fused_clipped_masked_sum(
    per_example_grads: Params,
    example_mask: jax.Array,
    clipping_bound: float,
    tile: int = 2048,
    interpret: bool | None = None,
    return_norms: bool = False,
) -> Params:
    """sum_i mask[i] * min(1, C/||g_i||) * g_i over a [B,...]-leaved pytree,
    without materializing the clipped per-example tensor (the fused
    replacement for dpsgd.clip_per_example + masked sum).

    ``return_norms=True`` additionally returns the pre-clip per-example
    norms [B] — pass 1 already computes them, so exporting costs nothing
    extra; the DP telemetry derives its clip fraction
    (``mean(mask * [norm > C])``) from this without a third pass.

    Kernels run PER LEAF on [B, leaf_width] views (reshape of a contiguous
    leaf is metadata, not a copy) with the squared-norm partials accumulated
    across leaves — concatenating the tree into one [B, D] matrix first
    would itself write+read the full tensor and forfeit the bandwidth win.
    Leaf sums come back f32 regardless of input dtype (the XLA path promotes
    via the f32 mask multiply, and DP noise must be added at full precision).
    """
    with stage_attr.stage("dp_clip"):
        leaves, treedef = jax.tree_util.tree_flatten(per_example_grads)
        mats = [leaf.reshape(leaf.shape[0], -1) for leaf in leaves]

        sq = sum(
            per_example_sq_norms(m, tile=tile, interpret=interpret)
            for m in mats
        )
        norms = jnp.sqrt(jnp.maximum(sq, 0.0))
        factor = jnp.minimum(1.0, clipping_bound / jnp.maximum(norms, 1e-12))
        scale = factor * example_mask.astype(jnp.float32)

        sums = [
            scaled_masked_sum(m, scale, tile=tile, interpret=interpret)
            .reshape(leaf.shape[1:])
            for leaf, m in zip(leaves, mats)
        ]
        out = jax.tree_util.tree_unflatten(treedef, sums)
    if return_norms:
        return out, norms
    return out

"""Pallas flash-attention kernel — fused softmax attention for the
transformer hot path.

The XLA path (parallel/ring_attention.py ``_dense_attention``) materializes
the [B, H, T, T] score tensor in HBM twice (softmax in, probabilities out) —
O(T^2) HBM traffic that dominates attention cost once T outgrows VMEM. This
kernel is the standard flash recipe on the MXU: stream K/V blocks through
VMEM against a resident Q block, maintain the online-softmax state (running
max, normalizer, weighted accumulator) in registers, and write only the
[T, D] output plus a [T] logsumexp. The backward pass recomputes
probabilities blockwise from the saved logsumexp (two kernels: dQ over query
blocks, dK/dV over key blocks) — nothing quadratic ever touches HBM.

Scope: per-device exact attention with key-padding masks (the shape the
transformer and the ring-attention local block need). The sequence axis
beyond one device is ring attention's job; this kernel is the fast local
block. K/V for one (batch, head) must fit VMEM — T up to ~8k at D=128 —
which the ring sharding guarantees by construction.

On non-TPU backends the kernels run in Pallas interpret mode so the CPU
suite exercises the same code path (house rule from kernels/dp_clip.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANE = 128
NEG_INF = -1e30


def _interpret_default() -> bool:
    # interpret only where Mosaic cannot compile (XLA:CPU); any non-cpu
    # backend (incl. the axon plugin, whatever platform string it reports)
    # gets the real kernels
    return jax.default_backend() == "cpu"


def _dot_precision(dtype) -> jax.lax.Precision:
    """f32 inputs get faithful f32 dots; anything narrower keeps the MXU's
    native fast path.

    Measured on TPU v5e (KERNELS r5): with the default precision Mosaic
    lowers an f32 dot to a single bf16 MXU pass, costing ~1.4e-3 abs error
    against the dense f32 attention the kernel must be a drop-in for.
    HIGHEST selects the multi-pass f32 algorithm for f32 operands only —
    the bf16 training path (the perf headline) is unaffected.
    """
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _pad_axis(x: jax.Array, axis: int, multiple: int, value=0.0) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, block_k,
                scale, precision):
    # Mosaic layout contract (learned on real silicon, KERNELS r5): every
    # block's trailing two dims must be (8k, 128k) or equal the array dims.
    # Row-per-(batch,head) vectors therefore travel as mask [BH, 1, Tp] and
    # lse/delta [BH, Tp, 1], and all in-kernel state stays 2-D.
    q = q_ref[0].astype(jnp.float32) * scale  # [Bq, Dp]
    bq = q.shape[0]
    n_kblocks = k_ref.shape[1] // block_k

    def body(j, carry):
        m, l, acc = carry  # m,l: [Bq, 1]
        kb = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        mk = mask_ref[0, :, pl.dslice(j * block_k, block_k)]  # [1, Bk]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )  # [Bq, Bk]
        s = jnp.where(mk > 0, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mk > 0, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )
        return m_new, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, a0))
    denom = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / denom).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(denom)  # [Bq, 1]


def _fwd_call(q, k, v, mask, block_q, block_k, scale, interpret):
    bh, tp, dp = q.shape
    grid = (bh, tp // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, scale=scale,
                               precision=_dot_precision(q.dtype))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tp, dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tp, dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, tp), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, dp), q.dtype),
            jax.ShapeDtypeStruct((bh, tp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, block_k, scale, precision):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # [Bq, 1]
    delta = delta_ref[0]  # [Bq, 1] = rowsum(dO * O)
    n_kblocks = k_ref.shape[1] // block_k

    def body(j, dq):
        kb = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        mk = mask_ref[0, :, pl.dslice(j * block_k, block_k)]  # [1, Bk]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        ) * scale
        p = jnp.exp(s - lse)
        p = jnp.where(mk > 0, p, 0.0)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )

    dq = jax.lax.fori_loop(
        0, n_kblocks, body, jnp.zeros_like(q)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q, scale, precision):
    kb = k_ref[0].astype(jnp.float32)  # [Bk, Dp]
    vb = v_ref[0].astype(jnp.float32)
    mk = mask_ref[0]  # [1, Bk]
    n_qblocks = q_ref.shape[1] // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.dslice(i * block_q, block_q), :]  # [Bq, 1]
        delta = delta_ref[0, pl.dslice(i * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        ) * scale
        p = jnp.exp(s - lse)  # [Bq, Bk]
        p = jnp.where(mk > 0, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )
        ds = p * (dp - delta) * scale  # [Bq, Bk]
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=precision
        )
        return dk, dv

    dk0 = jnp.zeros_like(kb)
    dv0 = jnp.zeros_like(vb)
    dk, dv = jax.lax.fori_loop(0, n_qblocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_call(q, k, v, mask, o, lse, do, block_q, block_k, scale, interpret,
              dlse):
    bh, tp, dp = q.shape
    # lse is a differentiable OUTPUT (ring-flash merge): its cotangent
    # enters the score gradient as dS = p*(dP - delta + dlse), i.e. the
    # delta slot carries (delta - dlse) — kernels unchanged. Plain
    # flash_attention reaches here with dlse = zeros (custom_vjp
    # instantiates the dropped output's cotangent).
    delta = (jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                     keepdims=True)
             - dlse.astype(jnp.float32))  # [BH, Tp, 1]

    prec = _dot_precision(q.dtype)
    dq_kernel = functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale,
                                  precision=prec)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, tp // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tp, dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tp, dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, tp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tp, dp), q.dtype),
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, block_q=block_q,
                                   scale=scale, precision=prec)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, tp // block_k),
        in_specs=[
            pl.BlockSpec((1, tp, dp), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j: (b, 0, j)),
            pl.BlockSpec((1, tp, dp), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, tp, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, tp, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, dp), k.dtype),
            jax.ShapeDtypeStruct((bh, tp, dp), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp over padded [BH, Tp, Dp] internals
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_padded_lse(q, k, v, mask, block_q, block_k, scale, interpret):
    """(out, lse) pair with lse a first-class differentiable output so
    partial-attention results can be merged exactly (ring-flash). The
    plain-``out`` path (flash_attention) wraps this and drops lse — its
    zero cotangent makes _bwd_call's dlse term vanish, so ONE custom_vjp
    serves both APIs."""
    return _fwd_call(q, k, v, mask, block_q, block_k, scale, interpret)


def _flash_padded_lse_fwd(q, k, v, mask, block_q, block_k, scale, interpret):
    out, lse = _fwd_call(q, k, v, mask, block_q, block_k, scale, interpret)
    return (out, lse), (q, k, v, mask, out, lse)


def _flash_padded_lse_bwd(block_q, block_k, scale, interpret, res, cts):
    do, dlse = cts
    q, k, v, mask, out, lse = res
    dq, dk, dv = _bwd_call(q, k, v, mask, out, lse, do, block_q, block_k,
                           scale, interpret, dlse=dlse)
    return dq, dk, dv, None


_flash_padded_lse.defvjp(_flash_padded_lse_fwd, _flash_padded_lse_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    pad_mask: jax.Array | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Exact softmax attention, flash-style. q,k,v: [B, T, H, D];
    pad_mask: [B, T] with 1 = real token (key positions); returns
    [B, T, H, D]. Drop-in for ring_attention._dense_attention.

    pad_mask is NON-differentiable: it is a binary padding indicator, and the
    custom VJP returns a zero cotangent for it (a soft/learned mask would get
    silent zero grads here — use the dense path for that; stop_gradient in
    the shared prep makes the contract explicit)."""
    out, _ = flash_attention_lse(q, k, v, pad_mask, block_q, block_k,
                                 interpret)
    return out


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    pad_mask: jax.Array | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """flash_attention returning (out [B,T,H,D], lse [B,H,T]) with lse a
    DIFFERENTIABLE output — the partial-softmax statistic that lets two
    attention results over disjoint key sets merge exactly:
    ``L = logsumexp_j(lse_j); out = sum_j exp(lse_j - L) * out_j``. This is
    the local block of ring-flash attention (parallel/ring_attention.py
    ``ring_flash_attention``). Query rows with no valid key anywhere get
    lse ~ NEG_INF + log(1e-20) — a large FINITE negative, deliberately not
    -inf: the ring merge computes exp(lse - M) and a true -inf would turn
    all-padded rows into inf-inf = NaN. Their merge weight underflows to 0
    either way; fully-padded rows' out is garbage, exactly like
    flash_attention."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    if pad_mask is None:
        pad_mask = jnp.ones((b, t), jnp.float32)
    scale = 1.0 / (d ** 0.5)
    # [B,T,H,D] -> [B*H, T, D]; pad T to the block grid, D per d_multiple
    # below (64 for head_dim<=64, else the 128 lane width — dp is NOT
    # guaranteed to be a multiple of 128).
    # T must divide by BOTH block sizes (the q grid tiles by block_q while
    # each kernel loops T/block_k key blocks) — lcm, not max: padding only to
    # max(block_q, block_k) would silently drop trailing key blocks for
    # non-dividing pairs like 48/32.
    t_multiple = math.lcm(block_q, block_k)

    # D padding: blocks always span the full head dim, and a block dim equal
    # to the array dim is legal on Mosaic whatever its size — so pad only to
    # the sublane-packable 64 for the ubiquitous head_dim<=64 case instead
    # of burning 2x FLOPs/VMEM traffic on 128-lane zero padding (the r5
    # long-context config is exactly head_dim=64).
    d_multiple = 64 if d <= 64 else _LANE

    def to_bh(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)
        return _pad_axis(_pad_axis(x, 2, d_multiple), 1, t_multiple)

    qp, kp, vp = to_bh(q), to_bh(k), to_bh(v)
    pad_mask = jax.lax.stop_gradient(pad_mask)
    maskp = _pad_axis(pad_mask.astype(jnp.float32), 1, t_multiple)
    # [BH, 1, Tp]: keys-per-row as the trailing (lane) dim — see _fwd_kernel's
    # Mosaic layout note
    maskp = jnp.repeat(maskp, h, axis=0)[:, None, :]

    out, lse = _flash_padded_lse(qp, kp, vp, maskp, block_q, block_k, scale,
                                 interpret)
    out = out[:, :t, :d].reshape(b, h, t, d)
    lse = lse[:, :t, 0].reshape(b, h, t)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype), lse

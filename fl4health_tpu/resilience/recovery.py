"""Crash-drill harness — prove preemption is a detour, not a restart.

The recovery subsystem's claims (versioned CRC-footed checkpoint frames,
retention-ring fallback, chunk-boundary and async snapshots — see
``checkpointing/state.py`` and ``docs/module_guides/recovery.md``) are only
worth shipping if a killed-and-resumed run PROVABLY reproduces the
uninterrupted trajectory. This module is the proof machinery, the same
pinned-claim discipline the resilience subsystem set for Byzantine faults
(``tests/resilience/test_faults.py::TestRobustnessClaim``):

1. ``run_child`` launches ``fit()`` in a REAL subprocess (its own JAX
   runtime, its own file handles — nothing shared with the test process);
2. a :class:`KillPoint` arms a deterministic SIGKILL inside the child —
   after round ``r``'s checkpoint publishes (``phase="post_save"``), or
   ``byte_offset`` bytes into the checkpoint write itself
   (``phase="mid_write"``, the torn-write drill). ``os.kill(getpid(),
   SIGKILL)`` is a true SIGKILL: no atexit, no flushing, no __del__ — the
   fidelity a preemptible-pool eviction has;
3. a second child resumes from the surviving checkpoint directory and
   writes its final params (serialized bytes) + per-round loss trajectory;
4. the drill compares those artifacts BYTE-identically against an
   uninterrupted run's.

``corrupt_newest_generation`` damages the newest ring generation between
kill and resume (truncation or byte-flip), driving the CRC-detect →
fallback-to-previous-generation path end-to-end.

Child protocol: ``python -m fl4health_tpu.resilience.recovery spec.json``
where the spec names a factory ``factory_file``/``factory_name`` —
``factory(ckpt_dir: str | None) -> FederatedSimulation`` — so the drill
composes with any configuration (execution modes, async_config, fault
plans) a test can express as a factory function.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
import signal
import subprocess
import sys
from typing import Any

_DONE = "done.json"
_PARAMS = "final_params.msgpack"
_HISTORY = "history.json"


@dataclasses.dataclass(frozen=True)
class KillPoint:
    """Where (and how) the child kills itself.

    ``round``: the checkpoint save (by its ``round``/event meta) that arms
    the kill. ``phase="post_save"`` kills right after that save's atomic
    publish returns — the canonical "preempted between rounds" drill.
    ``phase="mid_write"`` kills ``byte_offset`` bytes into that save's
    file write — the torn-write drill: the temp file dies mid-body and the
    previously published generation must survive untouched.

    ``phase="registry_scatter"`` kills at the moment round ``round``'s
    cohort-slot rows would scatter back into the host registry
    (``ClientRegistry.scatter`` on the RoundConsumer thread) — the
    read-after-write edge of the gather/scatter cycle: the round's
    checkpoint (which runs AFTER the scatter in the epilogue) never
    publishes, the scatter gate never releases, and a resume must restore
    the previous generation's registry rows bit-identically.

    ``signal_name`` selects the delivery: ``"SIGKILL"`` (default — no
    atexit, no flushing, eviction fidelity) or ``"SIGTERM"`` — the
    graceful-preemption drill: ``fit()``'s trap converts it into a
    :class:`~fl4health_tpu.observability.flightrec.SigtermShutdown`, the
    flight recorder publishes a postmortem bundle naming the kill round,
    and the child exits 143 (``mid_write``/``registry_scatter`` stay
    SIGKILL-only: a handler running mid-torn-write or mid-scatter would
    let graceful teardown finish the very work the drill interrupts)."""

    round: int
    phase: str = "post_save"
    byte_offset: int = 64
    signal_name: str = "SIGKILL"

    def __post_init__(self):
        if self.phase not in ("post_save", "mid_write", "registry_scatter"):
            raise ValueError(
                "phase must be 'post_save', 'mid_write' or "
                f"'registry_scatter'; got {self.phase!r}"
            )
        if self.round < 1:
            raise ValueError(f"round must be >= 1; got {self.round}")
        if self.byte_offset < 1:
            raise ValueError(
                f"byte_offset must be >= 1; got {self.byte_offset}"
            )
        if self.signal_name not in ("SIGKILL", "SIGTERM"):
            raise ValueError(
                f"signal_name must be 'SIGKILL' or 'SIGTERM'; "
                f"got {self.signal_name!r}"
            )
        if (self.phase in ("mid_write", "registry_scatter")
                and self.signal_name != "SIGKILL"):
            raise ValueError(f"{self.phase} drills are SIGKILL-only")

    @property
    def signum(self) -> int:
        return getattr(signal, self.signal_name)


@dataclasses.dataclass
class DrillResult:
    """One child run's artifacts (present only when it exited cleanly)."""

    returncode: int
    params_bytes: bytes | None
    history: list[dict] | None
    stdout: str
    stderr: str

    @property
    def sigkilled(self) -> bool:
        return self.returncode == -signal.SIGKILL


# -- child side --------------------------------------------------------------

class _KillingFile:
    """File proxy that SIGKILLs the process after ``byte_offset`` bytes —
    flushed first, so the torn prefix really is on disk when we die."""

    def __init__(self, f, byte_offset: int):
        self._f = f
        self._remaining = byte_offset

    def write(self, data):
        if len(data) >= self._remaining:
            self._f.write(data[:self._remaining])
            self._f.flush()
            os.fsync(self._f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        self._remaining -= len(data)
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


def install_kill_hook(checkpointer, kill: KillPoint) -> None:
    """Wrap ``checkpointer.save`` so the configured save dies at the
    configured point. Works wherever the save runs (the async writer
    thread included — SIGKILL takes the whole process)."""
    import contextlib

    from fl4health_tpu.checkpointing import state as state_mod

    orig_save = checkpointer.save
    _orig_atomic_write = state_mod.atomic_write

    @contextlib.contextmanager
    def killing_atomic_write(path, mode="w"):
        with _orig_atomic_write(path, mode) as f:
            yield _KillingFile(f, kill.byte_offset)

    def save(trees, host=None, snapshotters=None, extra_meta=None):
        rnd = (extra_meta or {}).get("round")
        if rnd != kill.round:
            return orig_save(trees, host=host, snapshotters=snapshotters,
                             extra_meta=extra_meta)
        if kill.phase == "mid_write":
            state_mod.atomic_write = killing_atomic_write
            try:
                return orig_save(trees, host=host, snapshotters=snapshotters,
                                 extra_meta=extra_meta)
            finally:  # unreachable when the kill fires; kept for tiny frames
                state_mod.atomic_write = _orig_atomic_write
        out = orig_save(trees, host=host, snapshotters=snapshotters,
                        extra_meta=extra_meta)
        # SIGKILL dies here; SIGTERM raises SigtermShutdown in the MAIN
        # thread (this save may run on the async-writer thread) — the
        # fit() loop then dumps its postmortem bundle and exits 143
        os.kill(os.getpid(), kill.signum)
        return out

    checkpointer.save = save


def install_scatter_kill_hook(sim, kill: KillPoint) -> None:
    """Arm a ``phase="registry_scatter"`` kill: wrap the cohort-slot
    registry's ``scatter`` so the ``kill.round``-th scatter of the run
    SIGKILLs the process at entry — mid-epilogue, BEFORE that round's rows
    persist, before its checkpoint publishes, and before the producer's
    scatter gate releases. The drill then proves the resume restores the
    PREVIOUS generation's registry rows bit-identically (the PR 13
    gather-gated read-after-write edge)."""
    if kill.phase != "registry_scatter":
        raise ValueError(
            f"install_scatter_kill_hook needs phase='registry_scatter'; "
            f"got {kill.phase!r}"
        )
    registry = getattr(sim, "registry", None)
    if registry is None:
        raise RuntimeError(
            "a registry_scatter KillPoint needs cohort-slot execution "
            "(FederatedSimulation(cohort=CohortConfig(...)))"
        )
    orig_scatter = registry.scatter
    calls = {"n": 0}

    def scatter(idx, valid, client_rows, strategy_rows=None):
        calls["n"] += 1
        if calls["n"] == kill.round:
            os.kill(os.getpid(), signal.SIGKILL)
        return orig_scatter(idx, valid, client_rows, strategy_rows)

    registry.scatter = scatter


def _load_factory(factory_file: str, factory_name: str):
    spec = importlib.util.spec_from_file_location("_fl4h_drill_factory",
                                                  factory_file)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return getattr(mod, factory_name)


def child_main(spec_path: str) -> int:
    """Entry point of the drill subprocess: build the sim from the spec's
    factory, arm the kill point, fit, dump artifacts."""
    with open(spec_path) as f:
        spec = json.load(f)
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # match the test environment's 8-device virtual CPU platform so
        # parent-process and drill-child trajectories share one layout
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if spec.get("jax_cache_dir"):
        jax.config.update("jax_compilation_cache_dir", spec["jax_cache_dir"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    factory = _load_factory(spec["factory_file"], spec["factory_name"])
    sim = factory(spec.get("ckpt_dir"))
    kill = spec.get("kill")
    if kill:
        kp = KillPoint(**kill)
        if kp.phase == "registry_scatter":
            install_scatter_kill_hook(sim, kp)
        else:
            if sim.state_checkpointer is None:
                raise RuntimeError("a KillPoint needs a state_checkpointer")
            install_kill_hook(sim.state_checkpointer, kp)
    history = sim.fit(int(spec["n_rounds"]))

    from flax import serialization

    from fl4health_tpu.core.io import atomic_write

    out_dir = spec["out_dir"]
    os.makedirs(out_dir, exist_ok=True)
    params = jax.device_get(sim.global_params)
    with atomic_write(os.path.join(out_dir, _PARAMS), "wb") as f:
        f.write(serialization.to_bytes(params))
    rows = [
        {
            "round": rec.round,
            "fit_loss": rec.fit_losses.get("backward"),
            "eval_loss": rec.eval_losses.get("checkpoint"),
        }
        for rec in history
    ]
    with atomic_write(os.path.join(out_dir, _HISTORY)) as f:
        json.dump(rows, f)
    with atomic_write(os.path.join(out_dir, _DONE)) as f:
        json.dump({"rounds": len(history)}, f)
    return 0


# -- parent side -------------------------------------------------------------

def run_child(spec: dict[str, Any], spec_path: str,
              timeout_s: float = 600.0) -> DrillResult:
    """Write the spec and run one drill child; returns its artifacts (None
    where the child died before writing them — the killed arm)."""
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fl4health_tpu.resilience.recovery",
         spec_path],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
    )
    out_dir = spec["out_dir"]
    params = history = None
    if os.path.exists(os.path.join(out_dir, _DONE)):
        with open(os.path.join(out_dir, _PARAMS), "rb") as f:
            params = f.read()
        with open(os.path.join(out_dir, _HISTORY)) as f:
            history = json.load(f)
    return DrillResult(
        returncode=proc.returncode, params_bytes=params, history=history,
        stdout=proc.stdout, stderr=proc.stderr,
    )


def corrupt_newest_generation(ckpt_dir: str, name: str = "state", *,
                              mode: str = "truncate",
                              keep_bytes: int = 128) -> str:
    """Damage the newest ring generation on disk — the between-kill-and-
    resume corruption drill. ``mode="truncate"`` keeps only the first
    ``keep_bytes`` (a torn tail); ``mode="flip"`` XOR-flips one payload
    byte (at-rest corruption the CRC must catch). Returns the damaged
    path."""
    from fl4health_tpu.checkpointing.state import StateCheckpointer

    cands = StateCheckpointer(ckpt_dir, name).candidate_paths()
    if not cands:
        raise FileNotFoundError(f"no checkpoint generations in {ckpt_dir!r}")
    _gen, path = cands[0]
    with open(path, "rb") as f:
        data = f.read()
    if mode == "truncate":
        damaged = data[:keep_bytes]
    elif mode == "flip":
        i = len(data) // 2
        damaged = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
    else:
        raise ValueError(f"mode must be 'truncate' or 'flip'; got {mode!r}")
    with open(path, "wb") as f:
        f.write(damaged)
    return path


if __name__ == "__main__":
    sys.exit(child_main(sys.argv[1]))

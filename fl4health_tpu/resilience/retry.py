"""Retry, backoff and circuit-breaking for the cross-silo wire path.

The serial ``broadcast_round`` of PRs 1-4 had the failure semantics of a
chain: one slow silo stalled the round, one dead silo killed it. This
module holds the host-side resilience primitives the reworked coordinator
(``transport/coordinator.py``) composes:

- :func:`classify_failure` — map an exception to the ``reason`` label of
  ``transport_rpc_failures_total`` (``timeout`` / ``connection`` /
  ``decode`` / ``other``), so dead-silo triage reads off the metrics page
  instead of the logs;
- :class:`RetryPolicy` — bounded attempts with jittered exponential
  backoff plus an optional overall per-silo ``deadline_s`` budget
  (injectable rng/sleep/clock so tests run in microseconds);
- :class:`CircuitBreaker` — per-silo closed/open/half-open gate: after
  ``failure_threshold`` consecutive failures the silo is skipped outright
  (no connect timeout paid) until ``reset_after_s`` elapses, then a single
  probe decides re-close vs re-open;
- :func:`call_with_retry` — the attempt loop tying the three together.

Everything here is transport-agnostic host code (no JAX): the simulation's
in-graph resilience lives in ``resilience/aggregators.py`` /
``quarantine.py``.
"""

from __future__ import annotations

import dataclasses
import random as _pyrandom
import threading
import time
from typing import Any, Callable

REASON_TIMEOUT = "timeout"
REASON_CONNECTION = "connection"
REASON_DECODE = "decode"
REASON_CIRCUIT_OPEN = "circuit_open"
REASON_DEADLINE = "deadline"
REASON_OTHER = "other"


class CircuitOpenError(ConnectionError):
    """Raised instead of dialing when a silo's circuit breaker is open."""


class RetryDeadlineError(TimeoutError):
    """The per-silo retry budget (``RetryPolicy.deadline_s``) ran out
    before the attempts did — further backoff would push the silo past
    the round deadline. Carries the last attempt's failure as
    ``__cause__``; classified as its own ``"deadline"`` reason so a
    metrics page separates "silo kept failing until the budget died"
    from a single hung RPC's ``"timeout"``."""


def classify_failure(exc: BaseException) -> str:
    """Failure-reason label for ``transport_rpc_failures_total``.

    Order matters: ``RetryDeadlineError`` IS a ``TimeoutError`` (and
    ``socket.timeout`` IS ``TimeoutError``/``OSError`` since 3.10), and
    the codec's ``FrameError`` is a ``ValueError`` (checked by family here
    — importing it would cycle resilience <-> transport) — the most
    specific family wins."""
    if isinstance(exc, CircuitOpenError):
        return REASON_CIRCUIT_OPEN
    if isinstance(exc, RetryDeadlineError):
        return REASON_DEADLINE
    if isinstance(exc, TimeoutError):
        return REASON_TIMEOUT
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        # unframe/CRC FrameErrors and template-mismatch decode errors
        return REASON_DECODE
    if isinstance(exc, (ConnectionError, OSError)):
        return REASON_CONNECTION
    return REASON_OTHER


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff over a bounded attempt budget.

    ``timeout_s`` is the per-attempt RPC timeout the coordinator passes to
    the transport ``call`` (a retry policy without a per-attempt timeout
    would let one hung silo eat the whole budget on attempt 1).

    ``deadline_s`` (optional) is the OVERALL per-silo budget across every
    attempt AND backoff sleep: jittered-exponential retries must not push
    a silo past the round deadline, so once the budget is spent — or the
    next backoff would overshoot it — the attempt loop stops and raises
    :class:`RetryDeadlineError` (reason label ``"deadline"``) chaining the
    last real failure. ``None`` (the default) keeps the unbounded legacy
    behavior."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    backoff_factor: float = 2.0
    jitter: float = 0.5
    timeout_s: float = 10.0
    deadline_s: float | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError("need 0 <= base_delay_s <= max_delay_s")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")

    def backoff_s(self, attempt: int, rng: Any = _pyrandom) -> float:
        """Delay before retry ``attempt+1`` (attempt is 0-based). Jitter
        subtracts up to ``jitter`` of the raw delay so a cohort of silos
        failing together doesn't retry in lockstep."""
        raw = min(
            self.base_delay_s * self.backoff_factor ** attempt,
            self.max_delay_s,
        )
        if self.jitter <= 0:
            return raw
        return raw * (1.0 - self.jitter * rng.random())


class CircuitBreaker:
    """Per-silo closed/open/half-open breaker (thread-safe).

    ``failure_threshold`` consecutive failures open the circuit;
    ``allow()`` then refuses until ``reset_after_s`` has elapsed, after
    which ONE caller is admitted as a half-open probe — its success
    re-closes the circuit, its failure re-opens it for another cooldown.
    ``clock`` is injectable so tests never sleep."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_after_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_out = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.reset_after_s:
                    return False
                self._state = self.HALF_OPEN
                self._probe_out = True
                return True
            # HALF_OPEN: exactly one probe in flight at a time
            if self._probe_out:
                return False
            self._probe_out = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probe_out = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (self._state == self.HALF_OPEN
                    or self._failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probe_out = False


def call_with_retry(
    do_call: Callable[[], Any],
    policy: RetryPolicy | None = None,
    breaker: CircuitBreaker | None = None,
    on_failure: Callable[[BaseException, int, bool], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Any = _pyrandom,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Run ``do_call`` under the retry policy and breaker.

    ``on_failure(exc, attempt, will_retry)`` fires per failed attempt —
    the coordinator uses it to bump the reason-labeled failure counter and
    the retry counter. ``policy=None`` means exactly one attempt (the
    legacy coordinator behavior). A breaker that refuses admission raises
    :class:`CircuitOpenError` without consuming an attempt's wire time.

    With ``policy.deadline_s`` set, the overall budget is enforced across
    attempts and backoff sleeps: when the next backoff would overshoot it
    (or it is already spent), the loop stops and raises
    :class:`RetryDeadlineError` chaining the last real failure —
    ``on_failure`` sees ``will_retry=False`` for that attempt, never a
    retry promise the deadline then breaks. ``clock`` is injectable so
    tests never sleep."""
    attempts = policy.max_attempts if policy is not None else 1
    deadline = policy.deadline_s if policy is not None else None
    t0 = clock() if deadline is not None else 0.0
    last: BaseException | None = None
    for attempt in range(attempts):
        if breaker is not None and not breaker.allow():
            exc: BaseException = CircuitOpenError(
                "circuit breaker open: silo skipped"
            )
            if on_failure is not None:
                on_failure(exc, attempt, False)
            raise exc
        try:
            out = do_call()
        except Exception as e:  # noqa: BLE001 — every wire failure retries
            last = e
            if breaker is not None:
                breaker.record_failure()
            will_retry = attempt + 1 < attempts
            delay = (policy.backoff_s(attempt, rng)
                     if will_retry and policy is not None else 0.0)
            over_deadline = (
                deadline is not None and will_retry
                and clock() - t0 + delay > deadline
            )
            if over_deadline:
                will_retry = False
            if on_failure is not None:
                on_failure(e, attempt, will_retry)
            if over_deadline:
                raise RetryDeadlineError(
                    f"retry deadline_s={deadline} exhausted after "
                    f"{attempt + 1} attempt(s) "
                    f"(last failure: {type(e).__name__}: {e})"
                ) from e
            if will_retry and delay > 0:
                sleep(delay)
            continue
        if breaker is not None:
            breaker.record_success()
        return out
    assert last is not None
    raise last

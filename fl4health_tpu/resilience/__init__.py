"""Resilience — tolerate and route around client/silo failures without
leaving the compiled fast path.

After the observability PRs the framework can *see* every failure (in-graph
telemetry, HealthWatchdog, program introspection) but could only warn or
halt. This subsystem is the next step, four pillars:

- :mod:`~fl4health_tpu.resilience.aggregators` — jit-compatible,
  statically-shaped Byzantine-robust aggregation (coordinate median,
  trimmed mean, norm-bounded mean, Krum/multi-Krum) packaged as the
  drop-in :class:`RobustFedAvg` strategy; runs INSIDE the compiled round
  programs on both execution modes;
- :mod:`~fl4health_tpu.resilience.quarantine` — an in-graph quarantine
  mask carried in server state with strike/probation/recovery semantics
  (:class:`QuarantiningStrategy` wraps any inner strategy); offenders are
  masked, never dropped, so shapes — and compiled programs — never change;
- :mod:`~fl4health_tpu.resilience.faults` — the deterministic, seeded
  :class:`FaultPlan` chaos layer (client dropout, update corruption,
  straggler/drop/corrupt wire faults) robustness claims are tested
  against, not asserted;
- :mod:`~fl4health_tpu.resilience.retry` — retry/backoff, failure-reason
  classification and per-silo circuit breakers for the concurrent
  quorum-based ``broadcast_round`` in ``transport/coordinator.py``;
- :mod:`~fl4health_tpu.resilience.recovery` — the crash-drill harness
  proving preemption survival: a subprocess ``fit()`` SIGKILLed at a
  seeded point (including mid-checkpoint-write), resumed from the
  retention ring, and pinned bit-identical to the uninterrupted run;
- :mod:`~fl4health_tpu.resilience.supervisor` — the self-healing loop:
  a :class:`RecoverySupervisor` driving a declarative
  :class:`RecoveryPolicy` escalation ladder (retry -> quarantine ->
  robustify -> degrade -> halt) over the structured abnormal-end
  taxonomy, with flight-recorder suspect attribution
  (:mod:`~fl4health_tpu.resilience.suspects`), checkpoint-ring rollback
  and ``/healthz``-restoring probation.
"""

from fl4health_tpu.resilience.aggregators import (
    ROBUST_METHODS,
    RobustFedAvg,
    coordinate_median,
    krum_weights,
    norm_bounded_mean,
    trimmed_mean,
)
from fl4health_tpu.resilience.faults import (
    ClientFault,
    FaultPlan,
    TransportFaultPolicy,
    chaos_handler,
)
from fl4health_tpu.resilience.quarantine import (
    QuarantinePolicy,
    QuarantineServerState,
    QuarantineState,
    QuarantiningStrategy,
    init_quarantine,
    quarantine_step,
)
from fl4health_tpu.resilience.recovery import (
    DrillResult,
    KillPoint,
    corrupt_newest_generation,
    install_kill_hook,
    run_child,
)
from fl4health_tpu.resilience.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryDeadlineError,
    RetryPolicy,
    call_with_retry,
    classify_failure,
)
from fl4health_tpu.resilience.supervisor import (
    QuorumControl,
    RecoveryPolicy,
    RecoverySupervisor,
)
from fl4health_tpu.resilience.suspects import (
    detect_divergence_onset,
    rank_suspects,
)

__all__ = [
    "DrillResult",
    "KillPoint",
    "corrupt_newest_generation",
    "install_kill_hook",
    "run_child",
    "ROBUST_METHODS",
    "RobustFedAvg",
    "coordinate_median",
    "trimmed_mean",
    "norm_bounded_mean",
    "krum_weights",
    "QuarantinePolicy",
    "QuarantineState",
    "QuarantineServerState",
    "QuarantiningStrategy",
    "init_quarantine",
    "quarantine_step",
    "ClientFault",
    "FaultPlan",
    "TransportFaultPolicy",
    "chaos_handler",
    "RetryPolicy",
    "RetryDeadlineError",
    "CircuitBreaker",
    "CircuitOpenError",
    "call_with_retry",
    "classify_failure",
    "QuorumControl",
    "RecoveryPolicy",
    "RecoverySupervisor",
    "rank_suspects",
    "detect_divergence_onset",
]

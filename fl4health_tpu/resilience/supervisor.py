"""Self-healing federation — a declarative recovery supervisor.

PRs 5/12/14 gave the framework detection (``HealthWatchdog``, the flight
recorder), isolation (quarantine, ``RobustFedAvg``) and durability
(crash-consistent generation-ring checkpoints, postmortem bundles) — but
every abnormal end still waited for an operator, even though the evidence
(suspect ranking, newest good generation) was already in the bundle. The
:class:`RecoverySupervisor` closes that loop: the machine reads its own
postmortem and acts on it.

Mechanics, per abnormal end of a supervised ``fit()``:

1. **Classify.** The exception is run through the SAME
   ``observability.bundle.verdict_from_exception`` that labels postmortem
   bundles — the structured taxonomy PR 14 established
   (``TrainingHealthError`` / ``ClientFailuresError`` / ``QuorumError`` /
   ``CheckpointCorruptError``); anything outside it (SIGTERM, generic
   exceptions) propagates untouched.
2. **Attribute.** Suspects come from the verdict's named clients plus the
   flight-recorder ring scored by :mod:`~fl4health_tpu.resilience.suspects`
   — the exact nonfinite/norm-outlier/strike scoring
   ``tools/postmortem.py`` renders, with slot→registry-id translation
   already applied under cohort execution.
3. **Roll back.** Checkpoint-ring generations at or past the verdict round
   are pruned (``StateCheckpointer.prune_generations_from_round``) so the
   next ``fit()`` entry restores the newest generation that predates the
   failure — sync, async mid-plan and cohort-kind frames all resume
   through the PR 12 machinery. With no ring (or an all-corrupt one) the
   run restarts from its seed-derived init (``sim._reset_to_initial``).
4. **Mitigate** per the :class:`RecoveryPolicy` escalation ladder
   (``retry`` → ``quarantine`` → ``robustify`` → ``degrade`` → halt), with
   bounded attempts per rung:

   - ``retry``: rollback + resume only (transients, corrupt frames);
   - ``quarantine``: the named suspects are masked out of sampling on
     every execution path (registry-id space under cohorts) until their
     release round — zero recompiles, pure mask math — and, when the
     strategy is a :class:`~fl4health_tpu.resilience.quarantine.
     QuarantiningStrategy`, its in-graph ``QuarantineState`` is seeded
     with the same suspects so strikes/probation agree;
   - ``robustify``: a plain ``FedAvg`` innermost strategy is swapped for
     :class:`~fl4health_tpu.resilience.aggregators.RobustFedAvg` (their
     server states are the SAME pytree, so restored checkpoints still
     load); an existing ``RobustFedAvg`` gets its trimming tightened. The
     aggregation program re-traces once (a persistent-cache disk hit on
     warm caches);
   - ``degrade``: participation pressure comes off — a bound
     :class:`QuorumControl` is relaxed (the cross-silo coordinator path),
     a fraction-sampling client manager's cohort is shrunk, and where the
     innermost strategy supports the PR 11 ``server_lr`` state binding
     the server learning rate is cooled via
     ``sweep.hoisting.apply_state_scalars`` — a state-leaf write through
     the traced-scalar machinery, zero recompiles.

5. **Resume + observe.** The supervised ``fit()`` re-enters (every attempt
   that dies still publishes its own postmortem bundle first); one
   ``recovery`` JSONL event and ``fl_recovery_*`` metrics land per
   attempt. After ``probation_rounds`` consecutive healthy rounds the
   ladder resets to its first rung and ``/healthz`` flips back to 200
   (``Observability.mark_healthy``). When the ladder is exhausted the
   original exception propagates — halt is the last rung.

Crash consistency: the supervisor journals its ladder position and
quarantine roster to an fsync-free atomic JSON ledger next to the
checkpoint ring, so a SIGKILL of the supervised process resumes with the
same mitigations armed (drilled by ``tests/resilience/test_recovery.py``).

``recovery=None`` (the default) and an armed-but-never-engaged policy are
both pinned bit-identical to an unsupervised run on BOTH execution modes
(``tests/resilience/test_supervisor.py``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Mapping, Sequence

import numpy as np

logger = logging.getLogger(__name__)

RUNG_RETRY = "retry"
RUNG_QUARANTINE = "quarantine"
RUNG_ROBUSTIFY = "robustify"
RUNG_DEGRADE = "degrade"
KNOWN_RUNGS = (RUNG_RETRY, RUNG_QUARANTINE, RUNG_ROBUSTIFY, RUNG_DEGRADE)

#: the structured abnormal-end taxonomy the supervisor may act on — the
#: verdict kinds ``observability.bundle.verdict_from_exception`` assigns
#: to the typed failures ("sigterm"/"exception" stay operator territory)
RECOVERABLE_KINDS = (
    "training_health", "client_failures", "quorum", "checkpoint_corrupt",
)

LEDGER_NAME = "recovery_ledger.json"
_LEDGER_VERSION = 1

#: loss-over-ring-best factor used to spot the divergence ONSET for
#: rollback targeting — tighter than the postmortem report's display
#: factor (2.0): a compounding poison trips the watchdog rounds after it
#: started contaminating checkpoints, and the worst case of a
#: false-positive here is re-running one extra healthy round
ONSET_FACTOR = 1.3


@dataclasses.dataclass
class QuorumControl:
    """Mutable quorum handle for supervised cross-silo loops: the driver
    passes ``quorum=ctl.quorum`` to every ``broadcast_round`` and binds
    ``ctl`` to the supervisor (``RecoverySupervisor(quorum_control=...)``)
    — the ``degrade`` rung then relaxes it in place (an int quorum
    decrements toward 1, a fractional one multiplies by
    ``RecoveryPolicy.quorum_relax``, both floored at ``minimum``)."""

    quorum: Any  # int count or float fraction (broadcast_round semantics)
    minimum: Any = 1

    def relax(self, factor: float) -> bool:
        """One degrade step; returns whether anything changed."""
        if isinstance(self.quorum, float):
            new = max(float(self.quorum) * factor, float(self.minimum))
        else:
            new = max(int(self.quorum) - 1, int(self.minimum))
        changed = new != self.quorum
        self.quorum = new
        return changed


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Declarative escalation ladder for :class:`RecoverySupervisor`.

    ``rungs`` orders the mitigations tried on repeated failures; each rung
    gets ``attempts_per_rung`` engagements before the supervisor
    escalates, and a rung that cannot apply to the run (no suspects to
    quarantine, nothing to robustify or degrade) is skipped. When every
    rung is exhausted — or ``max_total_attempts`` trips first — the
    original exception propagates: halt is the ladder's implicit last
    rung. A probation window of ``probation_rounds`` consecutive healthy
    rounds resets the ladder to its first rung (and flips ``/healthz``
    back to 200), so an incident next week starts from ``retry`` again,
    not from where last week's left off.

    Quarantine knobs: suspects are the verdict's named clients plus ring
    suspects scoring at least ``suspect_score_threshold``
    (:func:`~fl4health_tpu.resilience.suspects.rank_suspects`), capped at
    ``max_suspects`` per engagement; they are masked out of sampling for
    ``quarantine_rounds`` rounds after the resume point (``0`` = the rest
    of the run). ``robust_method``/``trim_fraction`` configure the
    ``robustify`` swap; ``quorum_relax``/``cohort_shrink``/
    ``server_lr_factor`` the ``degrade`` step (``server_lr_factor=None``
    disables the lr cool-down)."""

    rungs: tuple[str, ...] = KNOWN_RUNGS
    attempts_per_rung: int = 1
    max_total_attempts: int = 8
    probation_rounds: int = 3
    quarantine_rounds: int = 0
    suspect_score_threshold: float = 2.0
    max_suspects: int = 3
    robust_method: str = "trimmed_mean"
    trim_fraction: float = 0.2
    quorum_relax: float = 0.5
    cohort_shrink: float = 0.5
    server_lr_factor: float | None = None
    recover_kinds: tuple[str, ...] = RECOVERABLE_KINDS

    def __post_init__(self):
        object.__setattr__(self, "rungs", tuple(self.rungs))
        object.__setattr__(self, "recover_kinds", tuple(self.recover_kinds))
        if not self.rungs:
            raise ValueError("RecoveryPolicy needs at least one rung")
        for r in self.rungs:
            if r not in KNOWN_RUNGS:
                raise ValueError(
                    f"unknown rung {r!r}; rungs must be drawn from "
                    f"{KNOWN_RUNGS}"
                )
        if len(set(self.rungs)) != len(self.rungs):
            raise ValueError("rungs must be unique")
        for k in self.recover_kinds:
            if k not in RECOVERABLE_KINDS:
                raise ValueError(
                    f"unknown recoverable kind {k!r}; must be drawn from "
                    f"{RECOVERABLE_KINDS}"
                )
        if self.attempts_per_rung < 1:
            raise ValueError("attempts_per_rung must be >= 1")
        if self.max_total_attempts < 1:
            raise ValueError("max_total_attempts must be >= 1")
        if self.probation_rounds < 1:
            raise ValueError("probation_rounds must be >= 1")
        if self.quarantine_rounds < 0:
            raise ValueError("quarantine_rounds must be >= 0 (0 = rest of "
                             "the run)")
        if self.max_suspects < 1:
            raise ValueError("max_suspects must be >= 1")
        if not 0.0 < self.quorum_relax <= 1.0:
            raise ValueError("quorum_relax must be in (0, 1]")
        if not 0.0 < self.cohort_shrink <= 1.0:
            raise ValueError("cohort_shrink must be in (0, 1]")
        if (self.server_lr_factor is not None
                and not 0.0 < self.server_lr_factor <= 1.0):
            raise ValueError("server_lr_factor must be in (0, 1] or None")
        from fl4health_tpu.resilience.aggregators import ROBUST_METHODS

        if self.robust_method not in ROBUST_METHODS:
            raise ValueError(
                f"robust_method must be one of {ROBUST_METHODS}; got "
                f"{self.robust_method!r}"
            )


class RecoverySupervisor:
    """Drives a :class:`RecoveryPolicy` over a supervised simulation.

    Normally constructed by ``FederatedSimulation`` when
    ``recovery=RecoveryPolicy(...)`` is passed — ``sim.fit`` then routes
    through :meth:`run`. The simulation consults the supervisor on three
    hooks (all no-ops while nothing is engaged, so an armed-but-idle
    policy never perturbs the run): :meth:`keep_mask` /
    :meth:`quarantined_ids` multiply the per-round sampling mask,
    :meth:`note_round` counts healthy rounds for probation, and
    :meth:`on_resume` re-applies pending state mitigations after every
    checkpoint restore.

    Thread-safety: ``note_round`` runs on the RoundConsumer thread while
    ``keep_mask`` runs on the producer — one lock covers the ladder and
    the quarantine roster.
    """

    def __init__(self, sim: Any, policy: RecoveryPolicy,
                 ledger_path: str | None = None,
                 quorum_control: QuorumControl | None = None):
        if not isinstance(policy, RecoveryPolicy):
            raise TypeError(
                f"policy must be a RecoveryPolicy; got "
                f"{type(policy).__name__}"
            )
        self.sim = sim
        self.policy = policy
        self.quorum_control = quorum_control
        sc = getattr(sim, "state_checkpointer", None)
        if ledger_path is None and sc is not None:
            directory = getattr(sc, "directory", None)
            if directory:
                ledger_path = os.path.join(str(directory), LEDGER_NAME)
        self.ledger_path = ledger_path
        self._lock = threading.Lock()
        # ladder state
        self._engaged = False
        self._rung_idx = 0
        self._attempts: dict[str, int] = {}
        self._total_attempts = 0
        # quarantine roster: registry/client id -> release round (0 = the
        # rest of the run); consulted by keep_mask on every path
        self._quarantine: dict[int, int] = {}
        self._last_active: list[int] = []
        # probation bookkeeping: healthy rounds only count once the run is
        # PAST the round that failed — after a rollback, re-running rounds
        # the run had already survived is not new health evidence (a
        # deterministic round-N failure would otherwise pass probation on
        # the replayed prefix every attempt and retry forever)
        self._healthy_rounds = 0
        self._probation_after = 0
        self._resume_round = 1
        # one-shot mitigations applied at the next on_resume (post-restore)
        self._pending_seed: list[int] = []
        self._pending_scalars: dict[str, float] = {}
        # durable mitigation state (robustify swap, degrade quorum/
        # fraction) journaled so a SIGKILLed process re-arms them
        self._mitigations: dict[str, Any] = {}
        self._last_verdict: dict | None = None
        self._load_ledger()

    # -- observability helpers ------------------------------------------
    @property
    def _obs(self):
        return getattr(self.sim, "observability", None)

    def _metric(self, kind: str, name: str, help: str, **kw):
        obs = self._obs
        if obs is None or not getattr(obs, "enabled", False):
            return None
        return getattr(obs, kind)(name, help=help, **kw)

    def _log_event(self, **fields) -> None:
        obs = self._obs
        if obs is not None and getattr(obs, "enabled", False):
            obs.log_event("recovery", **fields)

    # -- ledger (SIGKILL survival) --------------------------------------
    def _ledger_doc(self) -> dict:
        return {
            "version": _LEDGER_VERSION,
            "engaged": self._engaged,
            "rung_idx": self._rung_idx,
            "attempts": dict(self._attempts),
            "total_attempts": self._total_attempts,
            "quarantine": {str(k): int(v)
                           for k, v in self._quarantine.items()},
            "probation_after": self._probation_after,
            "pending_seed": [int(c) for c in self._pending_seed],
            "pending_scalars": dict(self._pending_scalars),
            "mitigations": dict(self._mitigations),
            "last_verdict": self._last_verdict,
        }

    def _persist_ledger(self) -> None:
        if self.ledger_path is None:
            return
        from fl4health_tpu.core.io import atomic_write

        try:
            with atomic_write(self.ledger_path) as f:
                json.dump(self._ledger_doc(), f, indent=2, default=str)
        except OSError:
            logger.warning("recovery ledger write failed (%s) — a SIGKILL "
                           "before the next write loses ladder state",
                           self.ledger_path, exc_info=True)

    def _load_ledger(self) -> None:
        if self.ledger_path is None or not os.path.exists(self.ledger_path):
            return
        try:
            with open(self.ledger_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            logger.warning("recovery ledger %s unreadable — starting with "
                           "a fresh ladder", self.ledger_path)
            return
        self._engaged = bool(doc.get("engaged"))
        self._rung_idx = int(doc.get("rung_idx", 0))
        self._attempts = {str(k): int(v)
                          for k, v in (doc.get("attempts") or {}).items()}
        self._total_attempts = int(doc.get("total_attempts", 0))
        self._quarantine = {int(k): int(v)
                            for k, v in (doc.get("quarantine") or {}).items()}
        self._probation_after = int(doc.get("probation_after", 0))
        self._pending_seed = [int(c) for c in (doc.get("pending_seed")
                                               or [])]
        self._pending_scalars = {
            str(k): float(v)
            for k, v in (doc.get("pending_scalars") or {}).items()
        }
        self._mitigations = dict(doc.get("mitigations") or {})
        self._last_verdict = doc.get("last_verdict")
        if self._engaged or self._quarantine:
            logger.info(
                "recovery ledger restored from %s: rung %d, %d total "
                "attempt(s), %d quarantined client(s)", self.ledger_path,
                self._rung_idx, self._total_attempts, len(self._quarantine),
            )
        # a SIGKILLed process's durable mitigations re-arm HERE, at
        # construction — the factory rebuilt the sim with its original
        # strategy/manager/quorum, so the "resumes with the same
        # mitigations armed" contract needs them re-applied, not just the
        # attempt budgets remembered
        self._reapply_mitigations()

    def _reapply_mitigations(self) -> None:
        m = self._mitigations
        if not m:
            return
        rob = m.get("robustify")
        if rob:
            try:
                self._restore_robustify(rob)
            except Exception:
                logger.warning("recovery: could not re-apply the journaled "
                               "robustify swap", exc_info=True)
        frac = m.get("cohort_fraction")
        manager = getattr(self.sim, "client_manager", None)
        if frac is not None and manager is not None and hasattr(
                manager, "fraction"):
            self._set_manager_fraction(manager, float(frac))
        q = m.get("quorum")
        if q is not None and self.quorum_control is not None:
            self.quorum_control.quorum = (float(q) if isinstance(
                self.quorum_control.quorum, float) else int(q))

    def _restore_robustify(self, rob: Mapping[str, Any]) -> None:
        """Re-arm a journaled robustify mitigation on the freshly rebuilt
        strategy chain: swap a plain innermost FedAvg for the recorded
        RobustFedAvg, or restore the tightened trim fraction."""
        from fl4health_tpu.resilience.aggregators import RobustFedAvg

        target = self._robustify_target(for_restore=True)
        if target is None:
            return
        if isinstance(target, RobustFedAvg):
            trim = rob.get("trim_fraction")
            if trim is None or target.trim_fraction == trim:
                return
            self._swap_innermost(lambda t: self._copy_with_trim(t, trim))
        else:
            self._swap_innermost(lambda t: RobustFedAvg(
                method=str(rob.get("method", self.policy.robust_method)),
                trim_fraction=float(rob.get(
                    "trim_fraction", self.policy.trim_fraction
                )),
                weighted_aggregation=getattr(
                    t, "weighted_aggregation", True
                ),
            ))
        self.sim._build_compiled()

    # -- hooks the simulation calls -------------------------------------
    def keep_mask(self, round_idx: int, n_clients: int) -> np.ndarray | None:
        """[n_clients] keep-mask (0.0 = quarantined at this round), or
        None while nothing is quarantined — the never-engaged fast path
        multiplies nothing, preserving bit-identical trajectories."""
        with self._lock:
            if not self._quarantine:
                return None
            keep = np.ones((n_clients,), np.float32)
            hit = False
            for cid, release in self._quarantine.items():
                if release and round_idx >= release:
                    continue  # probation served — participates again
                if 0 <= cid < n_clients:
                    keep[cid] = 0.0
                    hit = True
            return keep if hit else None

    def quarantined_ids(self, round_idx: int) -> list[int]:
        """Registry/client ids quarantined at ``round_idx`` (sorted) —
        the cohort-slot path masks staged slots whose sampled id is
        listed here."""
        with self._lock:
            return self._quarantined_ids_locked(round_idx)

    def note_round(self, round_idx: int) -> None:
        """One completed healthy round (called from the round epilogues on
        every execution path, AFTER the watchdog passed). Drives probation
        and quarantine-release accounting."""
        with self._lock:
            if not self._engaged and not self._quarantine:
                return  # never-engaged fast path: zero work per round
            active = [
                cid for cid, release in self._quarantine.items()
                if not release or round_idx + 1 < release
            ]
            released = sorted(set(self._last_active) - set(active))
            self._last_active = sorted(active)
            passed = False
            if self._engaged and round_idx > self._probation_after:
                self._healthy_rounds += 1
                if self._healthy_rounds >= self.policy.probation_rounds:
                    passed = True
                    self._engaged = False
                    self._rung_idx = 0
                    self._attempts = {}
                    self._pending_scalars = {}
        if released:
            logger.info(
                "recovery: clients %s released from supervisor quarantine "
                "at round %d (probation served)", released, round_idx + 1,
            )
            g = self._metric(
                "gauge", "fl_recovery_quarantined_clients",
                "clients currently masked out of sampling by the recovery "
                "supervisor",
            )
            if g is not None:
                g.set(float(len(self._last_active)))
        if passed:
            self._on_probation_passed(round_idx)

    def _on_probation_passed(self, round_idx: int) -> None:
        obs = self._obs
        logger.info(
            "recovery: probation passed at round %d (%d healthy rounds) — "
            "ladder reset, run healthy", round_idx,
            self.policy.probation_rounds,
        )
        if obs is not None and getattr(obs, "enabled", False):
            mark = getattr(obs, "mark_healthy", None)
            if mark is not None:
                mark()  # /healthz back to 200: the run self-healed
            obs.gauge(
                "fl_recovery_engaged",
                help="1 while the recovery supervisor is between an "
                     "engagement and a passed probation window",
            ).set(0.0)
            obs.counter(
                "fl_recovery_probations_passed_total",
                help="probation windows completed (ladder resets)",
            ).inc()
        self._log_event(phase="probation_passed", round=int(round_idx),
                        healthy_rounds=self.policy.probation_rounds)
        self._persist_ledger()

    def on_resume(self, start_round: int) -> None:
        """Called by ``fit()`` right after its checkpoint restore: record
        the resume point, keep ``/healthz`` at 503 while recovery is in
        flight (``Observability.start()`` cleared the verdict), and apply
        the pending post-restore mitigations (in-graph quarantine seeding,
        hoisted-scalar overrides) onto the freshly restored state."""
        with self._lock:
            self._resume_round = int(start_round)
            self._healthy_rounds = 0
            seed = list(self._pending_seed)
            self._pending_seed = []
            scalars = dict(self._pending_scalars)
            engaged = self._engaged
            self._last_active = self._quarantined_ids_locked(start_round)
        obs = self._obs
        if engaged and obs is not None and getattr(obs, "enabled", False):
            mark = getattr(obs, "mark_unhealthy", None)
            if mark is not None:
                # start() reset the verdict; a recovering run must not
                # scrape 200 until probation passes
                mark(f"recovering (rung {self._current_rung_name()}, "
                     f"attempt {self._total_attempts})")
        if seed:
            self._seed_in_graph_quarantine(seed)
        if scalars:
            self._apply_scalars(scalars)

    def _quarantined_ids_locked(self, round_idx: int) -> list[int]:
        # caller holds self._lock (private: the lock contract must not
        # leak into the public API)
        return sorted(
            cid for cid, release in self._quarantine.items()
            if not release or round_idx < release
        )

    def _current_rung_name(self) -> str:
        if self._rung_idx < len(self.policy.rungs):
            return self.policy.rungs[self._rung_idx]
        return "halt"

    # -- the supervised run loop ----------------------------------------
    def run(self, n_rounds: int):
        """Run ``sim.fit(n_rounds)`` under the recovery policy: every
        recoverable abnormal end is classified, rolled back, mitigated per
        the ladder and resumed; anything else (or an exhausted ladder)
        propagates after its postmortem bundle published."""
        while True:
            try:
                return self.sim._fit_unsupervised(n_rounds)
            except BaseException as exc:
                if not self._engage(exc):
                    raise

    def _classify(self, exc: BaseException) -> dict:
        from fl4health_tpu.observability.bundle import verdict_from_exception

        obs = self._obs
        recorder = (getattr(obs, "flight_recorder", None)
                    if obs is not None else None)
        try:
            return verdict_from_exception(exc, recorder=recorder)
        except Exception:  # classification must never mask the failure
            logger.warning("recovery: verdict classification failed",
                           exc_info=True)
            return {"kind": "exception", "exception": type(exc).__name__,
                    "message": str(exc)}

    def _ring_entries(self) -> list[dict]:
        obs = self._obs
        recorder = (getattr(obs, "flight_recorder", None)
                    if obs is not None else None)
        if recorder is None:
            return []
        try:
            return recorder.entries
        except Exception:
            return []

    def _suspects(self, verdict: dict) -> tuple[list[int], list[dict]]:
        """(suspect ids, ranking evidence): the verdict's named clients
        first, then ring suspects at or above the score threshold, capped
        at ``max_suspects``. Ids are REGISTRY ids under cohort execution
        (both sources already translate)."""
        from fl4health_tpu.resilience.suspects import rank_suspects

        # fleet-ledger priors (observability/fleet.py): repeat offenders
        # on the lifetime record outrank first-time suspects with equal
        # window evidence — quarantine lands on the chronic client first
        ledger = (getattr(self._obs, "fleet_ledger", None)
                  if self._obs is not None else None)
        ranked = rank_suspects(self._ring_entries(),
                               top=max(self.policy.max_suspects * 2, 8),
                               ledger=ledger)
        out: list[int] = []
        for c in verdict.get("clients") or []:
            c = int(c)
            if c not in out:
                out.append(c)
        for s in ranked:
            if len(out) >= self.policy.max_suspects:
                break
            if (s["score"] >= self.policy.suspect_score_threshold
                    and int(s["client"]) not in out):
                out.append(int(s["client"]))
        return out[:self.policy.max_suspects], ranked

    # -- rollback --------------------------------------------------------
    def _rollback(self, verdict: dict) -> dict:
        """Bring training state back behind the failure: prune checkpoint
        generations at/past the verdict round so the next ``fit()``
        restores the newest PRE-failure generation; with nothing durable
        left, reset to the seed-derived init. Returns the rollback facts
        for the ``recovery`` event (incl. the expected resume round)."""
        sim = self.sim
        sc = getattr(sim, "state_checkpointer", None)
        # NOTE on buffered-async runs: every "round" here is an EVENT —
        # async round records, ring entries, watchdog verdicts and the
        # frames' meta["round"] (save_async_snapshot stamps the event
        # cursor) are all numbered by the same buffer-fill event index,
        # so pruning frames by the verdict round stays a like-for-like
        # comparison on every execution mode.
        bad_round = verdict.get("round")
        onset = self._divergence_onset()
        if onset is not None and (bad_round is None or onset < bad_round):
            # the ring saw the loss leave its envelope EARLIER than the
            # verdict round (a compounding poison trips the watchdog late)
            # — checkpoints from the onset on are contaminated too
            bad_round = onset
        facts: dict[str, Any] = {"mode": "restart"}
        if verdict.get("kind") == "checkpoint_corrupt" and sc is not None:
            # the ring fallback already failed (this error only surfaces
            # when EVERY candidate is corrupt): clear the wreckage
            try:
                sc.clear()
            except Exception:
                logger.warning("recovery: could not clear corrupt "
                               "checkpoint ring", exc_info=True)
        if sc is not None and hasattr(sc, "candidate_paths"):
            pruned: list[str] = []
            if bad_round is not None and hasattr(
                    sc, "prune_generations_from_round"):
                pruned = sc.prune_generations_from_round(int(bad_round))
            if sc.exists():
                newest_round, generation = self._newest_frame_round(sc)
                facts = {
                    "mode": "checkpoint",
                    "pruned_generations": len(pruned),
                    "resume_generation": generation,
                    "resume_round": ((newest_round + 1)
                                     if newest_round is not None else None),
                }
                c = self._metric(
                    "counter", "fl_recovery_rollbacks_total",
                    "checkpoint-ring rollbacks performed by the recovery "
                    "supervisor",
                )
                if c is not None:
                    c.inc()
                return facts
            facts["pruned_generations"] = len(pruned)
        # nothing durable predates the failure: restart from init —
        # rollback to "generation zero"
        sim._reset_to_initial()
        facts["resume_round"] = 1
        return facts

    def _divergence_onset(self) -> int | None:
        """Earliest checkpoint round contaminated by the failure, per the
        ring's loss trajectory. Round ``r``'s recorded training loss is
        measured on the model pulled from round ``r-1``'s aggregate, so
        the first out-of-envelope loss at ``r`` convicts the ``r-1``
        checkpoint — prune from ``r-1`` and the newest survivor predates
        the poison."""
        from fl4health_tpu.resilience.suspects import detect_divergence_onset

        onset = detect_divergence_onset(self._ring_entries(),
                                        factor=ONSET_FACTOR)
        if onset is None:
            return None
        return max(int(onset["round"]) - 1, 1)

    @staticmethod
    def _newest_frame_round(sc) -> tuple[int | None, int | None]:
        """(round, generation) of the newest readable ring frame."""
        from fl4health_tpu.checkpointing.state import (
            CheckpointCorruptError,
            read_frame,
        )

        for gen, path in sc.candidate_paths():
            try:
                _host, meta, _blob = read_frame(path)
            except CheckpointCorruptError:
                continue
            r = meta.get("round")
            return (int(r) if r is not None else None), int(gen)
        return None, None

    # -- mitigations -----------------------------------------------------
    def _rung_applicable(self, rung: str, suspects: Sequence[int]) -> bool:
        if rung == RUNG_RETRY:
            return True
        if rung == RUNG_QUARANTINE:
            return bool(suspects) and not getattr(
                self.sim, "_async_active", False
            )
        if rung == RUNG_ROBUSTIFY:
            return self._robustify_target() is not None
        if rung == RUNG_DEGRADE:
            return bool(self._degrade_targets())
        return False

    def _robustify_target(self, for_restore: bool = False):
        from fl4health_tpu.resilience.aggregators import RobustFedAvg
        from fl4health_tpu.strategies.fedavg import FedAvg
        from fl4health_tpu.sweep.hoisting import wrapper_chain

        inner = wrapper_chain(self.sim.strategy)[-1]
        if isinstance(inner, RobustFedAvg):
            # as a fresh MITIGATION there is only something to do when the
            # trimming can tighten — a median/Krum RobustFedAvg has no
            # knob here, so the rung is inapplicable (skipped) rather than
            # a parameter-identical copy that wastes a re-trace and an
            # attempt; ledger RESTORE still needs the handle either way
            if not for_restore and inner.method != "trimmed_mean":
                return None
            return inner
        # strict type check: only the plain FedAvg shares RobustFedAvg's
        # exact server-state pytree (FedOpt/SCAFFOLD carry more state, so
        # a swap would orphan the restored checkpoint's structure)
        if type(inner) is FedAvg:
            return inner
        return None

    def _swap_innermost(self, make_new) -> None:
        """Replace the innermost strategy with ``make_new(innermost)``,
        rebuilding the wrapper chain around shallow copies (the
        ``_wire_zero1_server_optimizer`` pattern: never mutate a strategy
        a caller may share with another simulation)."""
        import copy

        from fl4health_tpu.sweep.hoisting import wrapper_chain

        chain = wrapper_chain(self.sim.strategy)
        rebuilt = make_new(chain[-1])
        for wrapper in reversed(chain[:-1]):
            wrapper = copy.copy(wrapper)
            wrapper.inner = rebuilt
            rebuilt = wrapper
        self.sim.strategy = rebuilt

    @staticmethod
    def _copy_with_trim(target, trim: float):
        import copy

        new = copy.copy(target)
        new.trim_fraction = float(trim)
        return new

    @staticmethod
    def _set_manager_fraction(manager, fraction: float) -> None:
        manager.fraction = float(fraction)
        if hasattr(manager, "k"):
            # FixedFraction/FixedSampling cache the realized count at
            # construction — re-derive it with the manager's own
            # epsilon-safe floor or the shrink is a no-op
            from fl4health_tpu.server.client_manager import _fraction_floor

            manager.k = min(
                manager.n_clients,
                max(getattr(manager, "min_clients", 1),
                    _fraction_floor(manager.fraction, manager.n_clients)),
            )

    def _degrade_targets(self) -> list[str]:
        out = []
        if self.quorum_control is not None:
            out.append("quorum")
        manager = getattr(self.sim, "client_manager", None)
        if manager is not None and hasattr(manager, "fraction"):
            out.append("cohort")
        if self.policy.server_lr_factor is not None:
            try:
                from fl4health_tpu.sweep.hoisting import applicable_scalars

                if "server_lr" in applicable_scalars(self.sim.strategy):
                    out.append("server_lr")
            except Exception:
                pass
        return out

    def _apply_quarantine(self, suspects: Sequence[int],
                          resume_round: int | None) -> dict:
        resume_round = int(resume_round or 1)
        release = (0 if self.policy.quarantine_rounds == 0
                   else resume_round + self.policy.quarantine_rounds)
        with self._lock:
            for cid in suspects:
                self._quarantine[int(cid)] = release
            self._pending_seed = [int(c) for c in suspects]
            active = self._quarantined_ids_locked(resume_round)
        g = self._metric(
            "gauge", "fl_recovery_quarantined_clients",
            "clients currently masked out of sampling by the recovery "
            "supervisor",
        )
        if g is not None:
            g.set(float(len(active)))
        obs = self._obs
        if obs is not None and getattr(obs, "enabled", False):
            obs.log_event(
                "quarantine", round=resume_round, source="recovery",
                active=active, entered=sorted(int(c) for c in suspects),
                released=[],
            )
        return {"quarantined": sorted(int(c) for c in suspects),
                "release_round": release}

    def _seed_in_graph_quarantine(self, suspects: Sequence[int]) -> None:
        """When the strategy is a ``QuarantiningStrategy`` (dense modes:
        cohort persistence lives in registry rows keyed by id, outside the
        live slot state), seed its in-graph ``QuarantineState`` so the
        strategy's own strike/probation bookkeeping names the same
        offenders the supervisor masked."""
        sim = self.sim
        strategy = sim.strategy
        if getattr(sim, "_cohort_active", False):
            return
        if not hasattr(strategy, "quarantine_mask"):
            return
        try:
            import jax.numpy as jnp

            state = sim.server_state
            q = state.quarantine
            idx = jnp.asarray([int(c) for c in suspects], jnp.int32)
            rounds = float(self.policy.quarantine_rounds
                           or sim._fit_n_rounds or 10_000)
            new_q = q.replace(
                quarantined=q.quarantined.at[idx].set(1.0),
                release_in=q.release_in.at[idx].set(rounds),
                strikes=q.strikes.at[idx].set(0.0),
            )
            sim.server_state = state.replace(quarantine=new_q)
        except Exception:
            logger.warning("recovery: in-graph quarantine seeding failed "
                           "(host-side sampling quarantine still applies)",
                           exc_info=True)

    def _apply_robustify(self) -> dict | None:
        from fl4health_tpu.resilience.aggregators import RobustFedAvg

        sim = self.sim
        target = self._robustify_target()
        if target is None:
            return None
        if isinstance(target, RobustFedAvg):
            trim = min(0.45, float(target.trim_fraction) + 0.1)
            self._swap_innermost(lambda t: self._copy_with_trim(t, trim))
            facts = {"robustify": "tighten", "method": target.method,
                     "trim_fraction": trim}
        else:
            facts = {"robustify": "swap",
                     "method": self.policy.robust_method,
                     "trim_fraction": self.policy.trim_fraction}
            self._swap_innermost(lambda t: RobustFedAvg(
                method=self.policy.robust_method,
                trim_fraction=self.policy.trim_fraction,
                weighted_aggregation=getattr(
                    t, "weighted_aggregation", True
                ),
            ))
        # the aggregation program changed: re-trace (RobustFedAvg's state
        # IS FedAvgState, so the restored checkpoint structure still fits;
        # warm persistent caches make the recompile a disk hit)
        sim._build_compiled()
        # journal the swap so a SIGKILLed process re-arms it at ledger load
        self._mitigations["robustify"] = {
            "method": facts["method"],
            "trim_fraction": facts["trim_fraction"],
        }
        return facts

    def _apply_degrade(self) -> dict | None:
        targets = self._degrade_targets()
        if not targets:
            return None
        facts: dict[str, Any] = {}
        if "quorum" in targets:
            before = self.quorum_control.quorum
            if self.quorum_control.relax(self.policy.quorum_relax):
                facts["quorum"] = {"from": before,
                                   "to": self.quorum_control.quorum}
                self._mitigations["quorum"] = self.quorum_control.quorum
        if "cohort" in targets:
            manager = self.sim.client_manager
            before = float(manager.fraction)
            self._set_manager_fraction(manager, max(
                before * self.policy.cohort_shrink,
                1.0 / max(getattr(manager, "n_clients", 1), 1),
            ))
            facts["cohort_fraction"] = {"from": before,
                                        "to": float(manager.fraction)}
            self._mitigations["cohort_fraction"] = float(manager.fraction)
        if "server_lr" in targets:
            from fl4health_tpu.sweep.hoisting import binding

            b = binding("server_lr")
            try:
                current = self._pending_scalars.get(
                    "server_lr", b.default(self.sim.strategy)
                )
                new = float(current) * float(self.policy.server_lr_factor)
                # applied to the restored state at on_resume via
                # apply_state_scalars — a state-leaf write through the
                # PR 11 traced-scalar machinery, zero recompiles
                self._pending_scalars["server_lr"] = new
                facts["server_lr"] = {"from": float(current), "to": new}
            except Exception:
                logger.warning("recovery: server_lr cool-down failed",
                               exc_info=True)
        return facts or None

    def _apply_scalars(self, scalars: dict[str, float]) -> None:
        try:
            from fl4health_tpu.sweep.hoisting import apply_state_scalars

            self.sim.server_state = apply_state_scalars(
                self.sim.strategy, self.sim.server_state, scalars
            )
        except Exception:
            logger.warning("recovery: hoisted-scalar override failed "
                           "(%s)", scalars, exc_info=True)

    # -- engagement ------------------------------------------------------
    def _engage(self, exc: BaseException) -> bool:
        """Classify -> select rung -> rollback -> mitigate. Returns False
        (caller re-raises) when the failure is outside the policy's
        taxonomy or the ladder is exhausted."""
        verdict = self._classify(exc)
        kind = verdict.get("kind")
        if kind not in self.policy.recover_kinds:
            return False
        with self._lock:
            if self._total_attempts >= self.policy.max_total_attempts:
                logger.error(
                    "recovery: max_total_attempts=%d exhausted — halting "
                    "with the original %s", self.policy.max_total_attempts,
                    type(exc).__name__,
                )
                self._log_event(phase="halt", reason="max_total_attempts",
                                kind=kind, round=verdict.get("round"))
                return False
        suspects, ranked = self._suspects(verdict)
        rung = self._select_rung(suspects)
        if rung is None:
            self._log_event(phase="halt", reason="ladder_exhausted",
                            kind=kind, round=verdict.get("round"))
            logger.error(
                "recovery: escalation ladder exhausted — halting with the "
                "original %s", type(exc).__name__,
            )
            return False
        rollback = self._rollback(verdict)
        resume_round = rollback.get("resume_round") or 1
        mitigation: dict[str, Any] | None = None
        if rung == RUNG_QUARANTINE:
            mitigation = self._apply_quarantine(suspects, resume_round)
        elif rung == RUNG_ROBUSTIFY:
            mitigation = self._apply_robustify()
        elif rung == RUNG_DEGRADE:
            mitigation = self._apply_degrade()
        with self._lock:
            self._attempts[rung] = self._attempts.get(rung, 0) + 1
            self._total_attempts += 1
            self._engaged = True
            self._healthy_rounds = 0
            if verdict.get("round") is not None:
                # probation bar: only rounds BEYOND the failure count
                self._probation_after = int(verdict["round"])
            self._last_verdict = {
                "kind": kind, "round": verdict.get("round"),
                "ts": time.time(),
            }
            total = self._total_attempts
        obs = self._obs
        if obs is not None and getattr(obs, "enabled", False):
            obs.counter(
                "fl_recovery_attempts_total",
                help="recovery-supervisor engagements, by ladder rung",
                labels={"rung": rung},
            ).inc()
            obs.gauge(
                "fl_recovery_engaged",
                help="1 while the recovery supervisor is between an "
                     "engagement and a passed probation window",
            ).set(1.0)
            obs.gauge(
                "fl_recovery_rung",
                help="current escalation-ladder position (0-based rung "
                     "index)",
            ).set(float(self._rung_idx))
        self._log_event(
            phase="engage", attempt=total, rung=rung, kind=kind,
            round=verdict.get("round"), suspects=suspects,
            suspect_scores=[
                {"client": s["client"], "score": s["score"]}
                for s in ranked[:self.policy.max_suspects]
            ],
            rollback=rollback, mitigation=mitigation,
            resume_round=resume_round,
        )
        self._persist_ledger()
        logger.warning(
            "recovery attempt %d: %s at round %s -> rung %r "
            "(suspects=%s, rollback=%s, mitigation=%s); resuming at "
            "round %s", total, kind, verdict.get("round"), rung, suspects,
            rollback.get("mode"), mitigation, resume_round,
        )
        return True

    def _select_rung(self, suspects: Sequence[int]) -> str | None:
        """The first rung, from the current ladder position, with budget
        left AND applicable to this run; advances the ladder position past
        exhausted/inapplicable rungs. None = ladder exhausted (halt)."""
        with self._lock:
            idx = self._rung_idx
            while idx < len(self.policy.rungs):
                rung = self.policy.rungs[idx]
                if (self._attempts.get(rung, 0)
                        < self.policy.attempts_per_rung
                        and self._rung_applicable(rung, suspects)):
                    self._rung_idx = idx
                    return rung
                idx += 1
            self._rung_idx = idx
            return None

"""Suspect-client ranking over flight-recorder evidence.

The flight recorder (``observability/flightrec.py``) keeps the last
``window`` rounds' per-client telemetry; when a run ends abnormally, the
question an operator (or the :class:`~fl4health_tpu.resilience.supervisor.
RecoverySupervisor`) asks first is *which clients did this*. This module is
THE scoring shared by the offline incident report (``tools/postmortem.py``)
and the in-process recovery supervisor, so the machine quarantines exactly
the clients the postmortem would have named:

- non-finite state (NaN/Inf in losses/params/eval) is the dominant signal;
- grad-norm / update-norm outliers beyond 2 sigma of the participating
  cohort accumulate their z-scores (the scaled/sign-flipped-update proxy);
- in-graph quarantine standing and watchdog strikes corroborate;
- consumed-update staleness above the round mean (buffered-async runs);
- chaos-layer disclosure: when a ``FaultPlan`` was active, each ring
  entry carries the round's injected-fault summary — a client the plan
  corrupted on record IS a suspect (packet-level corruption is invisible
  to the local-training telemetry by design: clients train honestly and
  lie upstream, so the evidence bag's own disclosure carries the signal
  chaos drills need; absent on real runs, where the other signals carry).

All entries are host dicts — either the live recorder's
(:attr:`FlightRecorder.entries`) or a loaded bundle's ring (the two share
one schema; cohort entries carry ``registry_ids`` so scores attribute to
REAL clients, not slot positions). Pure numpy — safe on any thread, no JAX.
"""

from __future__ import annotations

import math

import numpy as np

#: training-loss factor over the ring best treated as divergence onset
DIVERGENCE_FACTOR = 2.0


def client_ids_for_entry(entry: dict) -> np.ndarray:
    """Registry ids for the entry's per-client vectors (cohort runs store
    them; dense runs fall back to positional ids)."""
    ids = entry.get("registry_ids")
    tele = entry.get("telemetry") or {}
    n = 0
    for v in tele.values():
        v = np.asarray(v)
        if v.ndim >= 1:
            n = max(n, v.shape[0])
    mask = entry.get("mask")
    if mask is not None:
        n = max(n, np.asarray(mask).shape[0])
    if ids is not None:
        return np.asarray(ids)[:n] if n else np.asarray(ids)
    return np.arange(n)


def detect_divergence_onset(ring: list[dict],
                            factor: float = DIVERGENCE_FACTOR) -> dict | None:
    """First recorded round whose training loss exceeded ``factor`` x the
    best loss seen earlier IN THE RING (the black box only holds the tail,
    so onset may predate the window — the report says so)."""
    best = math.inf
    for entry in sorted(ring, key=lambda e: e.get("round", 0)):
        loss = entry.get("fit_loss")
        if loss is None or not math.isfinite(float(loss)):
            # a non-finite aggregate IS the onset
            if loss is not None:
                return {"round": int(entry["round"]), "loss": float(loss),
                        "best": (None if best is math.inf else best),
                        "reason": "non-finite aggregate training loss"}
            continue
        loss = float(loss)
        if best is not math.inf and loss > factor * best:
            return {"round": int(entry["round"]), "loss": loss, "best": best,
                    "reason": f"loss > {factor}x ring best"}
        best = min(best, loss)
    return None


def _ledger_records(ledger) -> "dict[int, dict]":
    """Per-client lifetime docs from a live
    :class:`~fl4health_tpu.observability.fleet.FleetLedger` or its
    ``snapshot()`` dict (what a postmortem bundle's ``fleet.json``
    holds). Tolerant: anything unrecognizable yields no priors."""
    if ledger is None:
        return {}
    snap = ledger.snapshot() if hasattr(ledger, "snapshot") else ledger
    if not isinstance(snap, dict):
        return {}
    out: dict[int, dict] = {}
    for doc in snap.get("clients") or []:
        try:
            out[int(doc["client_id"])] = doc
        except (KeyError, TypeError, ValueError):
            continue
    return out


def rank_suspects(ring: list[dict], top: int = 5,
                  ledger=None) -> list[dict]:
    """Score every client the ring saw, by REGISTRY id. Signals (each
    normalized across the participating cohort per round, then summed over
    the ring): non-finite counts (dominant), grad-norm and update-norm
    outlier z-scores, quarantine strikes, consumed-update staleness above
    the round mean. Higher = more suspect. Returns
    ``[{client, score, evidence}, ...]`` most-suspect first.

    ``ledger`` (a live fleet ledger or its snapshot dict) adds a bounded
    repeat-offender prior: a client the WINDOW already implicated whose
    lifetime record shows prior non-finite rounds / quarantine strikes /
    injected faults gets up to +5.0, so between two equally-suspicious
    clients in the ring the one with history ranks first. Lifetime
    history alone never creates a suspect — the flight window carries the
    incident evidence, the ledger only breaks ties."""
    scores: dict[int, float] = {}
    evidence: dict[int, list[str]] = {}

    def bump(cid: int, amount: float, why: str | None = None):
        cid = int(cid)
        scores[cid] = scores.get(cid, 0.0) + float(amount)
        if why:
            evidence.setdefault(cid, []).append(why)

    for entry in sorted(ring, key=lambda e: e.get("round", 0)):
        rnd = int(entry.get("round", 0))
        ids = client_ids_for_entry(entry)
        if ids.size == 0:
            continue
        mask = entry.get("mask")
        part = (np.asarray(mask)[:ids.size] > 0 if mask is not None
                else np.ones(ids.size, bool))
        tele = entry.get("telemetry") or {}

        nonfinite = np.zeros(ids.size)
        for key in ("nonfinite_loss", "nonfinite_params",
                    "nonfinite_eval_loss"):
            v = tele.get(key)
            if v is not None:
                nonfinite[:len(v)] += np.nan_to_num(
                    np.asarray(v, np.float64)[:ids.size], nan=1.0
                )
        for i in np.nonzero((nonfinite > 0) & part)[0]:
            bump(ids[i], 10.0, f"non-finite state in round {rnd}")

        for key, label in (("grad_norm_mean", "grad norm"),
                           ("update_norm", "update norm")):
            v = tele.get(key)
            if v is None:
                continue
            v = np.asarray(v, np.float64)[:ids.size]
            live = part & np.isfinite(v)
            if live.sum() >= 3:
                mu, sd = float(v[live].mean()), float(v[live].std())
                if sd > 0:
                    z = (v - mu) / sd
                    for i in np.nonzero(live & (z > 2.0))[0]:
                        bump(ids[i], float(z[i]),
                             f"{label} {v[i]:.3g} is {z[i]:.1f} sigma above "
                             f"the round-{rnd} cohort mean")

        fault = entry.get("fault") or {}
        for cid in fault.get("corrupted") or []:
            cid = int(cid)
            if 0 <= cid < ids.size:
                cid = int(ids[cid])  # slot position -> registry id
            bump(cid, 6.0,
                 f"chaos layer corrupted this client's update in round "
                 f"{rnd} ({','.join(sorted((fault.get('kinds') or {})))})")

        q = entry.get("quarantine")
        if q is not None:
            q = np.asarray(q, np.float64)[:ids.size]
            for i in np.nonzero(q > 0)[0]:
                bump(ids[i], 3.0, f"quarantined in round {rnd}")
        for cid in entry.get("quarantine_active") or []:
            bump(cid, 1.0)

        stale = tele.get("staleness")
        if stale is not None:
            v = np.asarray(stale, np.float64)[:ids.size]
            live = part & np.isfinite(v)
            if live.any():
                mu = float(v[live].mean())
                for i in np.nonzero(live & (v > mu + 2))[0]:
                    bump(ids[i], 1.0,
                         f"staleness {v[i]:.0f} in round {rnd} "
                         f"(round mean {mu:.1f})")

    records = _ledger_records(ledger)
    if records:
        for cid in list(scores):
            if scores[cid] <= 0:
                continue
            doc = records.get(cid)
            if not doc:
                continue
            # lifetime suspect weight on the ledger's own scale
            # (observability/fleet.py ClientRecord.suspect_score), clamped
            # so history amplifies window evidence but cannot outvote it
            lifetime = (4.0 * float(doc.get("nonfinite_rounds") or 0)
                        + 3.0 * float(doc.get("quarantine_strikes") or 0)
                        + 2.0 * float(doc.get("fault_rounds") or 0)
                        + 1.0 * float(doc.get("failed_rounds") or 0))
            if lifetime > 0:
                prior = min(5.0, 0.5 * lifetime)
                bump(cid, prior,
                     f"repeat offender on the fleet ledger "
                     f"(lifetime suspect weight {lifetime:.0f} over "
                     f"{int(doc.get('rounds_participated') or 0)} rounds)")

    ranked = sorted(scores.items(), key=lambda kv: -kv[1])
    return [
        {"client": cid, "score": round(s, 3),
         "evidence": evidence.get(cid, [])[:4]}
        for cid, s in ranked[:top] if s > 0
    ]

"""Deterministic, seeded fault injection — the chaos layer robustness
claims are tested against.

Two fault surfaces, one plan:

- **Simulation faults** (in-graph): client dropout and update corruption
  (NaN-poison, scaling, sign-flip) compiled into the round programs. All
  draws come from ``jax.random`` keys folded from ``(seed, fault_index,
  round)``, so the SAME :class:`FaultPlan` produces the SAME faults on the
  pipelined and chunked execution paths, under resume, and across
  processes — a robustness experiment is exactly reproducible. Dropout is
  a mask multiply and corruption a packet transform: shapes never change,
  so a fault-ridden run costs zero recompiles.
- **Transport faults** (host-side): frame drop, frame corruption and
  straggler delay injected by wrapping a silo's handler
  (:func:`chaos_handler`) — deterministic per ``(seed, silo, request
  counter)`` via ``random.Random``. This is what the retry/quorum path
  (``transport/retry.py``, ``broadcast_round``) is exercised against.
  The sleep is injectable (mirroring ``retry.py``'s ``rng``/``sleep``)
  so delay-path tests never wall-clock sleep.
- **Compute-time faults** (virtual clock): ``kind="slow"`` specs model
  stragglers as a per-(client, round) compute-time MULTIPLIER instead of
  a wire delay. They never enter the round programs — the buffered-async
  scheduler (``server/async_schedule.py``) reads them host-side via
  :meth:`FaultPlan.compute_time_factors` to build its deterministic
  arrival plan, and the bench derives sync-round virtual wall times from
  the same draws. A plan with only ``slow`` faults leaves the compiled
  programs (and thus any synchronous trajectory) bit-identical.

Corruption semantics: a corrupted packet is ``payload + s * (packet -
payload)`` relative to the round's broadcast payload — ``s = -1`` is the
classical sign-flip attack on the update, ``s = k`` the scaling attack,
``s = NaN`` the poison. When the packet pytree is not param-shaped the
factor applies multiplicatively to each float leaf instead (checked
statically at trace time).
"""

from __future__ import annotations

import dataclasses
import random as _pyrandom
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

CLIENT_FAULT_KINDS = ("dropout", "nan", "scale", "sign_flip", "slow")

# kinds that transform the wire packet (everything except mask math and
# the host-side virtual-clock straggler model)
_CORRUPTION_KINDS = ("nan", "scale", "sign_flip")


@dataclasses.dataclass(frozen=True)
class ClientFault:
    """One fault spec over a static set of clients.

    ``probability`` is per (client, round); 1.0 means every round in the
    active window. The window is ``[start_round, end_round]`` inclusive
    (``end_round=None`` = forever)."""

    clients: tuple[int, ...]
    kind: str
    scale: float = 10.0
    probability: float = 1.0
    start_round: int = 1
    end_round: int | None = None

    def __post_init__(self):
        if self.kind not in CLIENT_FAULT_KINDS:
            raise ValueError(
                f"ClientFault.kind must be one of {CLIENT_FAULT_KINDS}; "
                f"got {self.kind!r}"
            )
        if self.kind == "slow" and not self.scale > 0:
            raise ValueError(
                "ClientFault(kind='slow') uses scale as a compute-time "
                f"multiplier; it must be > 0 (got {self.scale})"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        if not self.clients:
            raise ValueError("ClientFault.clients must name at least one client")
        # tuple-ify defensively: specs are hashable static config
        object.__setattr__(self, "clients", tuple(int(c) for c in self.clients))


@dataclasses.dataclass(frozen=True)
class TransportFaultPolicy:
    """Host-side wire chaos for one silo handler (all probabilities are per
    request, drawn deterministically from the plan seed)."""

    drop_probability: float = 0.0      # handler raises -> peer sees a reset
    corrupt_probability: float = 0.0   # reply frame byte-flipped (CRC fails)
    delay_s: float = 0.0               # straggler: sleep before replying
    delay_probability: float = 0.0


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seeded, declarative chaos schedule.

    Pass to ``FederatedSimulation(fault_plan=...)`` for the in-graph client
    faults; wrap silo handlers with :func:`chaos_handler` for the wire
    faults. An empty plan is exactly a no-op: the round programs compile
    byte-identically to ``fault_plan=None`` (pinned by
    ``tests/resilience/test_faults.py``)."""

    seed: int = 0
    client_faults: tuple[ClientFault, ...] = ()
    transport: TransportFaultPolicy | None = None

    def __post_init__(self):
        object.__setattr__(self, "client_faults", tuple(self.client_faults))

    # -- static views ---------------------------------------------------
    @property
    def dropout_faults(self) -> tuple[ClientFault, ...]:
        return tuple(f for f in self.client_faults if f.kind == "dropout")

    @property
    def corruption_faults(self) -> tuple[ClientFault, ...]:
        return tuple(
            f for f in self.client_faults if f.kind in _CORRUPTION_KINDS
        )

    @property
    def slow_faults(self) -> tuple[ClientFault, ...]:
        return tuple(f for f in self.client_faults if f.kind == "slow")

    @property
    def has_client_faults(self) -> bool:
        return bool(self.client_faults)

    def _check_clients(self, n_clients: int) -> None:
        """Every spec'd client must exist: JAX drops out-of-bounds scatter
        indices silently, so a typo'd id would inject NO fault anywhere and
        the robustness experiment would pass vacuously."""
        for f in self.client_faults:
            bad = [c for c in f.clients if not 0 <= c < n_clients]
            if bad:
                raise ValueError(
                    f"FaultPlan: ClientFault({f.kind!r}) names clients "
                    f"{bad} but the cohort has {n_clients} clients "
                    f"(valid ids: 0..{n_clients - 1})"
                )

    # -- in-graph draws (jit-traceable; round_idx may be traced) ---------
    def _fired(self, fault: ClientFault, fault_idx: int, round_idx,
               n_clients: int) -> jax.Array:
        """[C] float 1.0 where this fault fires this round."""
        member = jnp.zeros((n_clients,), jnp.float32).at[
            jnp.asarray(fault.clients, jnp.int32)
        ].set(1.0)
        r = jnp.asarray(round_idx, jnp.int32)
        active = (r >= fault.start_round)
        if fault.end_round is not None:
            active &= r <= fault.end_round
        fired = member * active.astype(jnp.float32)
        if fault.probability < 1.0:
            # distinct stream per (seed, fault index, round): deterministic
            # across execution modes and resumes
            key = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.PRNGKey(self.seed), 7919 * fault_idx + 13
                ),
                r,
            )
            u = jax.random.uniform(key, (n_clients,))
            fired = fired * (u < fault.probability).astype(jnp.float32)
        return fired

    def participation_factor(self, round_idx, n_clients: int) -> jax.Array:
        """[C] keep-mask (1.0 = client reachable) from the dropout specs —
        multiplied into the round's sampled participation mask in-graph."""
        self._check_clients(n_clients)
        keep = jnp.ones((n_clients,), jnp.float32)
        for i, f in enumerate(self.client_faults):
            if f.kind != "dropout":
                continue
            keep = keep * (1.0 - self._fired(f, i, round_idx, n_clients))
        return keep

    def corruption_factors(self, round_idx, n_clients: int) -> jax.Array:
        """[C] per-client update multiplier ``s`` (1.0 = honest, -1 =
        sign-flip, k = scale, NaN = poison). Later specs win on overlap."""
        self._check_clients(n_clients)
        factors = jnp.ones((n_clients,), jnp.float32)
        for i, f in enumerate(self.client_faults):
            if f.kind not in _CORRUPTION_KINDS:
                continue
            value = {
                "nan": jnp.nan,
                "sign_flip": -1.0,
                "scale": float(f.scale),
            }[f.kind]
            fired = self._fired(f, i, round_idx, n_clients)
            factors = jnp.where(fired > 0, value, factors)
        return factors

    def corrupt_packets(self, packets: Any, payload_params: Any,
                        round_idx, n_clients: int) -> Any:
        """Apply this round's corruption to the client-stacked packets
        (jit-traceable; identity when no corruption specs exist)."""
        if not self.corruption_faults:
            return packets
        factors = self.corruption_factors(round_idx, n_clients)

        def expand(leaf):
            return factors.reshape((-1,) + (1,) * (leaf.ndim - 1))

        if (jax.tree_util.tree_structure(packets)
                == jax.tree_util.tree_structure(payload_params)):
            # attack the UPDATE relative to the broadcast payload
            return jax.tree_util.tree_map(
                lambda leaf, ref: (
                    ref.astype(leaf.dtype)[None]
                    + expand(leaf) * (leaf - ref.astype(leaf.dtype)[None])
                ).astype(leaf.dtype)
                if jnp.issubdtype(leaf.dtype, jnp.inexact) else leaf,
                packets, payload_params,
            )
        # exotic packet layout: multiplicative on float leaves
        return jax.tree_util.tree_map(
            lambda leaf: (expand(leaf) * leaf).astype(leaf.dtype)
            if jnp.issubdtype(leaf.dtype, jnp.inexact) else leaf,
            packets,
        )

    # -- virtual-clock straggler model (host-side) ----------------------
    def compute_time_factors(self, round_idx: int, n_clients: int) -> np.ndarray:
        """[C] per-client compute-time MULTIPLIER for the training attempt
        whose data plan index is ``round_idx`` (1.0 = nominal speed) — the
        ``kind="slow"`` specs' contribution to the virtual clock.

        Host-side numpy (the async scheduler builds its static event plan
        before dispatch), but the draws come from the SAME seeded
        ``_fired`` streams as the in-graph faults, so a plan mixing slow +
        corruption faults stays one reproducible experiment. Overlapping
        slow specs compound multiplicatively (a client named by two 2x
        specs runs 4x slower)."""
        self._check_clients(n_clients)
        factors = np.ones((n_clients,), np.float64)
        for i, f in enumerate(self.client_faults):
            if f.kind != "slow":
                continue
            fired = np.asarray(self._fired(f, i, round_idx, n_clients))
            factors = np.where(fired > 0, factors * float(f.scale), factors)
        return factors

    # -- host mirror (observability) ------------------------------------
    def summarize_round(self, round_idx: int, n_clients: int) -> dict | None:
        """Host-side mirror of the round's draws for the ``fault`` JSONL
        event — same seeded computation evaluated eagerly, so the log
        reports exactly what the compiled program injected."""
        if not self.client_faults:
            return None
        keep = np.asarray(self.participation_factor(round_idx, n_clients))
        factors = np.asarray(self.corruption_factors(round_idx, n_clients))
        dropped = [int(c) for c in np.nonzero(keep < 1.0)[0]]
        kinds: dict[str, list[int]] = {}
        for c in range(n_clients):
            f = factors[c]
            if np.isnan(f):
                kinds.setdefault("nan", []).append(c)
            elif f == -1.0:
                kinds.setdefault("sign_flip", []).append(c)
            elif f != 1.0:
                kinds.setdefault("scale", []).append(c)
        corrupted = sorted({c for cs in kinds.values() for c in cs})
        slow: list[int] = []
        if self.slow_faults:
            # virtual-clock stragglers are facts about the round too — the
            # log should name them even though no packet was touched
            ct = self.compute_time_factors(round_idx, n_clients)
            slow = [int(c) for c in np.nonzero(ct != 1.0)[0]]
            if slow:
                kinds["slow"] = slow
        if not dropped and not corrupted and not slow:
            return None
        return {
            "round": int(round_idx),
            "dropped": dropped,
            "corrupted": corrupted,
            "kinds": kinds,
        }


class _InjectedDrop(RuntimeError):
    """Raised inside a chaos-wrapped handler to kill the reply — the
    loopback server logs it and closes the connection, which the caller
    observes as a connection failure (exactly a crashed silo)."""


def chaos_handler(
    handler: Callable[[bytes], bytes],
    policy: TransportFaultPolicy,
    seed: int = 0,
    silo_idx: int = 0,
    sleep: Callable[[float], None] = time.sleep,
) -> Callable[[bytes], bytes]:
    """Wrap a silo request handler with deterministic wire chaos.

    Draws come from ``random.Random(f"{seed}:{silo_idx}")`` in a fixed order
    per request (delay, drop, corrupt), so a given plan produces the same
    fault sequence every run — tests assert against it. Thread-safe enough
    for the one-connection-at-a-time loopback server.

    ``sleep`` is injectable (mirroring ``retry.py``'s ``call_with_retry``)
    so straggler-delay tests record the delays instead of paying them —
    the draw ORDER is identical either way, keeping recorded and
    real-sleep runs the same fault sequence."""
    rng = _pyrandom.Random(f"{seed}:{silo_idx}")

    def wrapped(frame: bytes) -> bytes:
        r_delay, r_drop, r_corrupt = rng.random(), rng.random(), rng.random()
        if policy.delay_s > 0 and r_delay < policy.delay_probability:
            sleep(policy.delay_s)
        if r_drop < policy.drop_probability:
            raise _InjectedDrop(
                f"chaos: dropped request at silo {silo_idx}"
            )
        reply = handler(frame)
        if r_corrupt < policy.corrupt_probability and reply:
            # flip one mid-frame byte: framing CRC catches it and the
            # caller sees a decode failure, not silent corruption
            buf = bytearray(reply)
            buf[len(buf) // 2] ^= 0xFF
            reply = bytes(buf)
        return reply

    return wrapped

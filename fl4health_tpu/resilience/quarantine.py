"""In-graph client quarantine — strike/probation state carried in server
state, updated inside the compiled round programs.

The :class:`~fl4health_tpu.observability.health.HealthWatchdog` (PR 3) can
*see* a misbehaving client from host telemetry, but on the chunked-scan
execution mode the whole run is one dispatch — by the time the host sees
round *r*'s telemetry, round *r+1* has already aggregated the offender.
Quarantine therefore has to live where aggregation lives: inside the
graph, as a ``[clients]``-shaped mask in server state, so masking an
offender out of round *r+1* costs zero recompiles and works identically on
both execution modes.

Mechanics (all jit-traceable, static shapes):

- :class:`QuarantineState` rides in the strategy's server-state pytree:
  ``quarantined`` mask, per-client ``strikes``, probation countdown
  (``release_in``), and a dead-update streak;
- :func:`quarantine_step` folds one round's signals — per-client non-finite
  counts, update norms — into that state under a static
  :class:`QuarantinePolicy` (offense -> strike; enough strikes ->
  quarantine; ``quarantine_rounds`` of probation -> release/recovery; a
  re-offender simply re-enters);
- :class:`QuarantiningStrategy` wraps ANY inner strategy: it zeroes
  quarantined clients out of the aggregation mask (the inner strategy never
  sees them), derives the signals from the round's own packets/losses, and
  steps the state — all inside ``Strategy.aggregate``, which both the
  pipelined and chunked round programs already compile.

Host-side visibility (``fl_quarantine_*`` gauges + ``quarantine`` JSONL
events) is emitted by ``FederatedSimulation``, which snapshots the mask per
round on both execution paths. The complementary HOST-side mitigation — the
watchdog's ``mitigate`` action gating next-round sampling on the pipelined
path — lives in ``observability/health.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.observability import telemetry as telem
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class QuarantineState:
    """Per-client quarantine bookkeeping, all ``[clients]`` float32 (a plain
    pytree: scans, donation and ``device_get`` handle it unchanged)."""

    quarantined: jax.Array  # 1.0 = masked out of aggregation
    strikes: jax.Array      # consecutive offense count while healthy
    release_in: jax.Array   # probation rounds remaining while quarantined
    dead_streak: jax.Array  # consecutive near-zero-update participations


def init_quarantine(n_clients: int) -> QuarantineState:
    z = jnp.zeros((n_clients,), jnp.float32)
    return QuarantineState(quarantined=z, strikes=z, release_in=z,
                           dead_streak=z)


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """Static thresholds compiled into the round program.

    - ``on_nonfinite``: a participating client whose packet or losses
      contain NaN/Inf commits an offense (the poisoned-update signal);
    - ``norm_outlier_ratio`` > 0 enables: update norm beyond that multiple
      of the healthy-cohort median is an offense (scaled/sign-flip attack
      proxy; requires the wrapped packets to be param-shaped);
    - ``dead_norm`` >= 0 enables: update norm at or below it for
      ``dead_rounds`` consecutive participations is an offense (a client
      pushing the pulled model straight back);
    - ``strikes_to_quarantine`` consecutive offenses trigger quarantine;
      an offense-free participation clears the strike count;
    - ``quarantine_rounds`` of probation later the client is released
      (recovery) with a clean record — re-offending re-quarantines it.
    """

    on_nonfinite: bool = True
    norm_outlier_ratio: float = 0.0
    dead_norm: float = -1.0
    dead_rounds: int = 3
    strikes_to_quarantine: int = 1
    quarantine_rounds: int = 5

    def __post_init__(self):
        if self.strikes_to_quarantine < 1:
            raise ValueError("strikes_to_quarantine must be >= 1")
        if self.quarantine_rounds < 1:
            raise ValueError("quarantine_rounds must be >= 1")
        if self.dead_rounds < 1:
            raise ValueError("dead_rounds must be >= 1")


def _masked_median(values: jax.Array, keep: jax.Array) -> jax.Array:
    """Median of ``values`` where ``keep`` — +inf padding sort trick, same
    order-statistics approach as the robust aggregators."""
    v = jnp.where(keep, values, jnp.inf)
    s = jnp.sort(v)
    k = jnp.sum(keep).astype(jnp.int32)
    lo = jnp.maximum((k - 1) // 2, 0)
    hi = jnp.maximum(k // 2, 0)
    return 0.5 * (jnp.take(s, lo) + jnp.take(s, hi))


def quarantine_step(
    q: QuarantineState,
    policy: QuarantinePolicy,
    *,
    mask: jax.Array,
    nonfinite: jax.Array,
    update_norm: jax.Array,
) -> QuarantineState:
    """One round of strike/quarantine/probation bookkeeping (jit-traceable).

    ``mask`` is the round's SAMPLED participation (pre-quarantine): only
    healthy sampled clients are judged, quarantined clients only serve
    probation. ``update_norm`` may be all-NaN when the packet layout gives
    no norm signal — the norm-driven checks then never fire."""
    part = (jnp.asarray(mask) > 0) & (q.quarantined < 0.5)
    finite_norm = jnp.isfinite(update_norm)

    offense = jnp.zeros_like(part)
    if policy.on_nonfinite:
        offense |= part & (jnp.asarray(nonfinite) > 0)
    if policy.norm_outlier_ratio > 0:
        healthy = part & finite_norm
        med = _masked_median(update_norm, healthy)
        outlier = (
            part
            & finite_norm
            & (update_norm
               > policy.norm_outlier_ratio * jnp.maximum(med, 1e-12))
        )
        # a median needs a cohort: with <3 healthy norms "outlier" is noise
        offense |= outlier & (jnp.sum(healthy) >= 3) & jnp.isfinite(med)

    dead_streak = q.dead_streak
    if policy.dead_norm >= 0:
        is_dead = part & finite_norm & (update_norm <= policy.dead_norm)
        dead_streak = jnp.where(
            part, jnp.where(is_dead, dead_streak + 1.0, 0.0), dead_streak
        )
        tripped = dead_streak >= policy.dead_rounds
        offense |= part & tripped
        dead_streak = jnp.where(tripped, 0.0, dead_streak)

    strikes = jnp.where(
        part, jnp.where(offense, q.strikes + 1.0, 0.0), q.strikes
    )

    # probation countdown first, then release, then (re-)entries — a client
    # released this round can immediately re-enter on a fresh offense next
    # round, never this one (its strikes were cleared on entry)
    release_in = jnp.where(
        q.quarantined > 0, jnp.maximum(q.release_in - 1.0, 0.0), q.release_in
    )
    released = (q.quarantined > 0) & (release_in <= 0)
    quarantined = jnp.where(released, 0.0, q.quarantined)
    strikes = jnp.where(released, 0.0, strikes)
    dead_streak = jnp.where(released, 0.0, dead_streak)

    entering = strikes >= policy.strikes_to_quarantine
    quarantined = jnp.where(entering, 1.0, quarantined)
    release_in = jnp.where(
        entering, float(policy.quarantine_rounds), release_in
    )
    strikes = jnp.where(entering, 0.0, strikes)

    return QuarantineState(
        quarantined=quarantined,
        strikes=strikes,
        release_in=release_in,
        dead_streak=dead_streak,
    )


@struct.dataclass
class QuarantineServerState:
    """Wrapper server state: the inner strategy's state + quarantine."""

    inner: Any
    quarantine: QuarantineState


class QuarantiningStrategy(Strategy):
    """Wrap any strategy with in-graph quarantine.

    Quarantined clients are removed from the aggregation mask BEFORE the
    inner ``aggregate`` runs (the inner strategy treats them exactly like
    unsampled clients — zero weight, no recompile), and the quarantine
    state is stepped from signals the round already computes:

    - per-client non-finite counts over the packet stack + train losses;
    - per-client update norm ``||packet - previous_global||`` when the
      packet pytree is param-shaped (checked statically at trace time —
      exotic packet layouts simply disable the norm-driven checks).

    ``n_clients`` is normally learned from ``bind_client_manager`` (the
    simulation calls it before ``init``); pass it explicitly for direct
    use. ``quarantine_mask(server_state)`` exposes the live mask — the
    simulation snapshots it per round for ``fl_quarantine_*`` gauges and
    ``quarantine`` JSONL events on both execution modes.
    """

    def __init__(
        self,
        inner: Strategy,
        policy: QuarantinePolicy | None = None,
        n_clients: int | None = None,
    ):
        self.inner = inner
        self.policy = policy or QuarantinePolicy()
        self._n_clients = n_clients
        self.weighted_aggregation = inner.weighted_aggregation
        self.weighted_eval_aggregation = inner.weighted_eval_aggregation
        # chunk-eligibility passthrough (server/simulation.py consults this
        # before the type-level check): only a host-consuming INNER
        # update_after_eval should force the pipelined path
        self.overrides_update_after_eval = (
            type(inner).update_after_eval is not Strategy.update_after_eval
        )

    @property
    def evaluate_after_fit(self) -> bool:
        return bool(getattr(self.inner, "evaluate_after_fit", False))

    def bind_client_manager(self, client_manager: Any) -> None:
        self._n_clients = client_manager.n_clients
        bind = getattr(self.inner, "bind_client_manager", None)
        if bind is not None:
            bind(client_manager)

    def init(self, params) -> QuarantineServerState:
        if self._n_clients is None:
            raise ValueError(
                "QuarantiningStrategy needs n_clients: pass it to the "
                "constructor or let FederatedSimulation bind its client "
                "manager first"
            )
        return QuarantineServerState(
            inner=self.inner.init(params),
            quarantine=init_quarantine(self._n_clients),
        )

    def global_params(self, server_state: QuarantineServerState):
        return self.inner.global_params(server_state.inner)

    def state_sharding_spec(self, server_state: QuarantineServerState,
                            clients_axis: str):
        """Quarantine bookkeeping is all ``[clients]``-shaped — shard it
        over the clients mesh axis like every other per-client stack; the
        inner strategy's state follows its own spec."""
        from jax.sharding import PartitionSpec as P

        from fl4health_tpu.strategies.base import inner_state_sharding_spec

        return QuarantineServerState(
            inner=inner_state_sharding_spec(
                self.inner, server_state.inner, clients_axis
            ),
            quarantine=P(clients_axis),
        )

    def state_rows(self, server_state: QuarantineServerState):
        """Per-client quarantine bookkeeping (strikes / probation / dead
        streak — all ``[C]``) plus whatever the inner strategy carries per
        client, for cohort-slot gather/scatter (``server/registry.py``).
        NOTE: under a sampled cohort, probation (``release_in``) counts a
        client's PARTICIPATING rounds — its row only steps when gathered —
        rather than wall-clock rounds as in the dense path."""
        return {
            "quarantine": server_state.quarantine,
            "inner": self.inner.state_rows(server_state.inner),
        }

    def scatter_state_rows(self, server_state: QuarantineServerState, rows):
        return QuarantineServerState(
            inner=self.inner.scatter_state_rows(
                server_state.inner, rows["inner"]
            ),
            quarantine=rows["quarantine"],
        )

    def divergence_reference(self, server_state: QuarantineServerState):
        return self.inner.divergence_reference(server_state.inner)

    def client_payload(self, server_state: QuarantineServerState, round_idx):
        return self.inner.client_payload(server_state.inner, round_idx)

    def quarantine_mask(self, server_state: QuarantineServerState) -> jax.Array:
        """[clients] 1.0 = currently quarantined (jit-traceable accessor)."""
        return server_state.quarantine.quarantined

    def _signals(self, results: FitResults, prev_global):
        """(nonfinite [C], update_norm [C]) from the round's own outputs."""
        try:
            nonfinite = telem.per_client_nonfinite(results.packets)
        except ValueError:  # no float leaves in the packet stack
            nonfinite = jnp.zeros_like(jnp.asarray(results.mask, jnp.float32))
        nonfinite = nonfinite + telem.nonfinite_in_losses(results.train_losses)
        # static structure check: packets that aren't param-shaped give no
        # norm signal (NaN disables the norm-driven policy checks)
        if (jax.tree_util.tree_structure(results.packets)
                == jax.tree_util.tree_structure(prev_global)):
            n2 = None
            for leaf, ref in zip(
                jax.tree_util.tree_leaves(results.packets),
                jax.tree_util.tree_leaves(prev_global),
            ):
                d = leaf.astype(jnp.float32) - ref.astype(jnp.float32)[None]
                d = jnp.where(jnp.isfinite(d), d, 0.0)
                s = jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=1)
                n2 = s if n2 is None else n2 + s
            update_norm = jnp.sqrt(n2)
        else:
            update_norm = jnp.full_like(nonfinite, jnp.nan)
        return nonfinite, update_norm

    def aggregate(
        self, server_state: QuarantineServerState, results: FitResults,
        round_idx,
    ) -> QuarantineServerState:
        prev_global = self.inner.global_params(server_state.inner)
        nonfinite, update_norm = self._signals(results, prev_global)
        healthy_mask = results.mask * (
            1.0 - server_state.quarantine.quarantined
        )
        if self.policy.on_nonfinite:
            # instant screen: a NaN/Inf packet is masked out of THIS round's
            # aggregate, not just future ones — detection after the poison
            # lands would be one round too late (the strike/quarantine state
            # then keeps the offender out while it keeps misbehaving)
            healthy_mask = healthy_mask * (
                1.0 - (nonfinite > 0).astype(healthy_mask.dtype)
            )
        new_inner = self.inner.aggregate(
            server_state.inner, results.replace(mask=healthy_mask), round_idx
        )
        new_q = quarantine_step(
            server_state.quarantine,
            self.policy,
            mask=results.mask,
            nonfinite=nonfinite,
            update_norm=update_norm,
        )
        return QuarantineServerState(inner=new_inner, quarantine=new_q)

    def update_after_eval(
        self, server_state: QuarantineServerState, eval_losses, eval_metrics,
        mask,
    ) -> QuarantineServerState:
        new_inner = self.inner.update_after_eval(
            server_state.inner, eval_losses, eval_metrics, mask
        )
        return server_state.replace(inner=new_inner)

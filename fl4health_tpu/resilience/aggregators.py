"""Byzantine-robust aggregation combinators — jit-compatible, statically
shaped, mask-driven.

The reference handles a misbehaving client by *not calling* it again
(Flower drops the gRPC peer); the SPMD build cannot drop a row from a
compiled program without recompiling, so robustness has to be expressed the
same way sampling already is: as math over a fixed ``[clients]`` axis with
masks. Every combinator here

- accepts the client-stacked packet pytree (leading ``[clients]`` axis on
  every leaf) plus a ``[clients]`` participation mask,
- treats non-finite submissions from *participating* clients as adversarial
  (they sort to the top and are out-voted/trimmed, never propagated),
- keeps all shapes static, so a quarantined or dropped client costs zero
  recompiles on either execution path (pipelined or chunked scan).

Estimators (classical Byzantine-robust FL menu):

- :func:`coordinate_median` — coordinate-wise median over participating
  clients (breakdown point ~1/2);
- :func:`trimmed_mean` — coordinate-wise mean after trimming the
  ``trim_fraction`` extremes from each end (Yin et al.-style);
- :func:`norm_bounded_mean` — weighted mean after clipping each client's
  update norm relative to a reference (the norm-bounding defense; also the
  only combinator here that honors sample-count weighting);
- :func:`krum_weights` — Krum / multi-Krum selection scores (Blanchard et
  al.): average the ``m`` clients whose closest-neighbor distance sums are
  smallest.

:class:`RobustFedAvg` packages them as a drop-in
:class:`~fl4health_tpu.strategies.base.Strategy` whose state is the plain
``FedAvgState`` — swappable with FedAvg without touching server state
structure, which is what lets ``bench.py`` time the robust-vs-plain
aggregation overhead in place.

Median/trimmed-mean/Krum are deliberately UNWEIGHTED: in the Byzantine
model the per-client sample counts are attacker-controlled inputs, so
weighting by them hands the adversary the estimator.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.core.aggregate import effective_weights, weighted_mean
from fl4health_tpu.core.types import Params, PyTree, StackedParams
from fl4health_tpu.observability import stages as stage_attr
from fl4health_tpu.strategies.base import FitResults, Strategy
from fl4health_tpu.strategies.fedavg import FedAvgState

ROBUST_METHODS = ("median", "trimmed_mean", "norm_bounded", "krum",
                  "multi_krum")


def _expand(v: jax.Array, leaf: jax.Array) -> jax.Array:
    """Reshape [clients] vector to broadcast against a [clients, ...] leaf."""
    return v.reshape((-1,) + (1,) * (leaf.ndim - 1))


def _sanitized(leaf: jax.Array, mask: jax.Array) -> jax.Array:
    """f32 copy with masked-out rows AND non-finite entries set to +inf, so
    an ascending sort pushes both past every honest value. A NaN would
    otherwise sort *after* +inf and break the 'first k rows are the
    participants' invariant the order statistics below rely on."""
    v = leaf.astype(jnp.float32)
    keep = _expand(mask > 0, v) & jnp.isfinite(v)
    return jnp.where(keep, v, jnp.inf)


def coordinate_median(stacked: StackedParams, mask: jax.Array) -> PyTree:
    """Masked coordinate-wise median over the clients axis.

    ``k = |participants|`` is a traced value: the sort is over the full
    static axis and the median indices are dynamic gathers, so partial
    cohorts never change program shapes. An empty cohort yields +inf
    coordinates — callers guard with their usual empty-cohort fallback
    (as :class:`RobustFedAvg` does)."""
    k = jnp.sum(jnp.asarray(mask) > 0).astype(jnp.int32)
    lo = jnp.maximum((k - 1) // 2, 0)
    hi = jnp.maximum(k // 2, 0)

    def _med(leaf: jax.Array) -> jax.Array:
        s = jnp.sort(_sanitized(leaf, mask), axis=0)
        return 0.5 * (jnp.take(s, lo, axis=0) + jnp.take(s, hi, axis=0))

    return jax.tree_util.tree_map(_med, stacked)


def trimmed_mean(
    stacked: StackedParams, mask: jax.Array, trim_fraction: float = 0.2
) -> PyTree:
    """Masked coordinate-wise trimmed mean: drop ``floor(trim_fraction*k)``
    values from EACH end of the sorted participating values, average the
    middle. ``trim_fraction`` may be static config OR a traced f32 scalar
    (the sweep engine hoists it so a trim-fraction sweep shares one
    compiled program — it only ever enters rank comparisons, never a
    shape); the realized trim count is clamped so at least the median
    survives tiny cohorts. Non-finite submissions sort to the top end and
    are removed whenever the trim budget covers the attacker count — the
    estimator's usual guarantee."""
    if isinstance(trim_fraction, jax.core.Tracer):
        # traced (the sweep's hoisted hvec input): validation becomes an
        # in-graph clamp of the [0, 0.5) rule — the host-side binding
        # validators reject bad values before they reach a trace
        trim_fraction = jnp.clip(
            jnp.asarray(trim_fraction, jnp.float32), 0.0, 0.4999
        )
    else:
        # concrete scalar (Python / numpy / jnp): validate loudly, as always
        trim_fraction = float(trim_fraction)
        if not 0.0 <= trim_fraction < 0.5:
            raise ValueError(
                f"trim_fraction must be in [0, 0.5); got {trim_fraction} "
                "(trimming half or more from each end leaves nothing)"
            )
    m = jnp.asarray(mask)
    k = jnp.sum(m > 0).astype(jnp.int32)
    t = jnp.clip(
        jnp.floor(trim_fraction * k.astype(jnp.float32)).astype(jnp.int32),
        0,
        jnp.maximum((k - 1) // 2, 0),
    )
    pos = jnp.arange(m.shape[0], dtype=jnp.int32)
    w = ((pos >= t) & (pos < k - t)).astype(jnp.float32)  # sorted-rank weights
    denom = jnp.maximum(jnp.sum(w), 1.0)

    def _tm(leaf: jax.Array) -> jax.Array:
        s = jnp.sort(_sanitized(leaf, mask), axis=0)
        ww = _expand(w, s)
        # where() then multiply: an untrimmed +inf must flow through (real
        # breakdown), but a trimmed one must not poison the sum (inf*0=nan)
        return jnp.sum(jnp.where(ww > 0, s, 0.0) * ww, axis=0) / denom

    return jax.tree_util.tree_map(_tm, stacked)


def _per_client_nonfinite_flag(stacked: StackedParams) -> jax.Array:
    """[C] bool — client row contains any NaN/Inf in a float leaf."""
    bad = None
    for leaf in jax.tree_util.tree_leaves(stacked):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            continue
        row = jnp.any(
            ~jnp.isfinite(leaf.reshape(leaf.shape[0], -1)), axis=1
        )
        bad = row if bad is None else bad | row
    if bad is None:
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        return jnp.zeros((n,), bool)
    return bad


def norm_bounded_mean(
    stacked: StackedParams,
    reference: Params,
    sample_counts: jax.Array,
    mask: jax.Array,
    max_norm: float,
    weighted: bool = True,
) -> PyTree:
    """Weighted mean after clipping each client's global update norm
    ``||packet - reference||`` to ``max_norm`` (the norm-bounding defense:
    a single scaled-up update can shift the mean by at most ``max_norm``).
    Non-finite coordinates are treated as zero *delta* — a NaN-poisoned
    client degrades to re-submitting the reference, not to poisoning the
    aggregate."""
    n2 = None
    for leaf, ref in zip(
        jax.tree_util.tree_leaves(stacked), jax.tree_util.tree_leaves(reference)
    ):
        d = leaf.astype(jnp.float32) - ref.astype(jnp.float32)[None]
        d = jnp.where(jnp.isfinite(d), d, 0.0)
        s = jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=1)
        n2 = s if n2 is None else n2 + s
    norm = jnp.sqrt(n2)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))

    def _clip(leaf: jax.Array, ref: jax.Array) -> jax.Array:
        r = ref.astype(jnp.float32)[None]
        d = leaf.astype(jnp.float32) - r
        d = jnp.where(jnp.isfinite(d), d, 0.0)
        return r + _expand(scale, d) * d

    clipped = jax.tree_util.tree_map(_clip, stacked, reference)
    w = effective_weights(sample_counts, mask, weighted)
    out = weighted_mean(clipped, w)
    return jax.tree_util.tree_map(
        lambda o, ref: o.astype(ref.dtype), out, reference
    )


def krum_weights(
    stacked: StackedParams,
    mask: jax.Array,
    num_byzantine: int,
    multi_m: int = 1,
) -> jax.Array:
    """Krum / multi-Krum selection as [C] normalized aggregation weights.

    Each participating client is scored by the sum of its squared distances
    to its ``n - f - 2`` closest participating peers (``f`` =
    ``num_byzantine``); the ``multi_m`` lowest scores are selected and
    averaged (``multi_m=1`` is classical Krum). Clients with non-finite
    rows, masked-out clients, and selections whose score is +inf (cohort
    smaller than ``multi_m``) get weight 0. All shapes static; ``multi_m``
    and ``num_byzantine`` are compile-time config."""
    m = jnp.asarray(mask)
    n_clients = m.shape[0]
    if not 1 <= multi_m <= n_clients:
        raise ValueError(f"multi_m must be in [1, {n_clients}]; got {multi_m}")
    part = m > 0
    n = jnp.sum(part).astype(jnp.int32)
    bad = _per_client_nonfinite_flag(stacked)

    d2 = jnp.zeros((n_clients, n_clients), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(stacked):
        v = leaf.astype(jnp.float32).reshape(n_clients, -1)
        v = jnp.where(jnp.isfinite(v), v, 0.0)
        sq = jnp.sum(jnp.square(v), axis=1)
        d2 = d2 + (sq[:, None] + sq[None, :] - 2.0 * (v @ v.T))
    d2 = jnp.maximum(d2, 0.0)  # matmul round-off can dip tiny negatives
    unusable = ~part | bad
    d2 = jnp.where(unusable[:, None] | unusable[None, :], jnp.inf, d2)
    d2 = jnp.where(jnp.eye(n_clients, dtype=bool), jnp.inf, d2)

    # closest c = n - f - 2 neighbors; clamp so tiny cohorts still score
    c = jnp.clip(n - num_byzantine - 2, 1, n_clients - 1)
    sorted_d = jnp.sort(d2, axis=1)
    csum = jnp.cumsum(sorted_d, axis=1)  # an inf neighbor poisons the score
    score = jnp.take_along_axis(
        csum, jnp.full((n_clients, 1), c - 1), axis=1
    )[:, 0]
    score = jnp.where(part & ~bad, score, jnp.inf)

    neg_vals, idx = jax.lax.top_k(-score, multi_m)
    sel = jnp.zeros((n_clients,), jnp.float32).at[idx].add(
        jnp.where(jnp.isfinite(neg_vals), 1.0, 0.0)
    )
    total = jnp.sum(sel)
    return jnp.where(total > 0, sel / jnp.maximum(total, 1.0), sel)


class RobustFedAvg(Strategy):
    """FedAvg with a Byzantine-robust reduction — a drop-in ``Strategy``.

    ``method`` selects the combinator (``"median"``, ``"trimmed_mean"``,
    ``"norm_bounded"``, ``"krum"``, ``"multi_krum"``); all run inside the
    compiled round programs on both execution modes. State is the plain
    ``FedAvgState``, so swapping FedAvg <-> RobustFedAvg never changes the
    server-state pytree (``bench.py`` relies on this to time the overhead
    in place). An effectively-empty cohort (all weights zero — empty mask,
    or every client rejected) keeps the previous params, mirroring FedAvg's
    empty-cohort rule."""

    def __init__(
        self,
        method: str = "median",
        *,
        trim_fraction: float = 0.2,
        max_update_norm: float = 10.0,
        num_byzantine: int = 1,
        multi_krum_m: int = 3,
        weighted_aggregation: bool = True,
    ):
        if method not in ROBUST_METHODS:
            raise ValueError(
                f"method must be one of {ROBUST_METHODS}; got {method!r}"
            )
        if max_update_norm <= 0:
            raise ValueError("max_update_norm must be positive")
        if num_byzantine < 0:
            raise ValueError("num_byzantine must be >= 0")
        self.method = method
        self.trim_fraction = trim_fraction
        self.max_update_norm = max_update_norm
        self.num_byzantine = num_byzantine
        self.multi_krum_m = multi_krum_m
        # honored by norm_bounded only; the order statistics are unweighted
        # by construction (see module docstring)
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> FedAvgState:
        return FedAvgState(params=params)

    def aggregate(
        self, server_state: FedAvgState, results: FitResults, round_idx
    ) -> FedAvgState:
        with stage_attr.stage("robust_aggregate"):
            stacked, mask = results.packets, results.mask
            if self.method == "median":
                new = coordinate_median(stacked, mask)
                ok = jnp.sum(mask) > 0
            elif self.method == "trimmed_mean":
                new = trimmed_mean(stacked, mask, self.trim_fraction)
                ok = jnp.sum(mask) > 0
            elif self.method == "norm_bounded":
                new = norm_bounded_mean(
                    stacked,
                    server_state.params,
                    results.sample_counts,
                    mask,
                    self.max_update_norm,
                    self.weighted_aggregation,
                )
                ok = jnp.sum(mask) > 0
            else:  # krum / multi_krum
                m = 1 if self.method == "krum" else self.multi_krum_m
                w = krum_weights(stacked, mask, self.num_byzantine, m)
                new = weighted_mean(stacked, w)
                ok = jnp.sum(w) > 0
            new_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n.astype(o.dtype), o),
                new,
                server_state.params,
            )
            return server_state.replace(params=new_params)

"""Parameter packers — structured payloads riding the exchange boundary.

Reference behavior (/root/reference/fl4health/parameter_exchange/parameter_packer.py:13-142):
packers concatenate auxiliary state (control variates, clipping bits, adaptive
losses, layer names, sparse COO components) onto the flat NumPy weight list and
split it back on the far side, because Flower's wire format is an opaque list.

TPU-native design: the "wire" is a pytree, so a packed payload is simply a
typed container (flax.struct dataclass) whose fields keep their structure —
pack/unpack become field access and the whole payload can be client-stacked,
sharded, and consumed by jit aggregation without any index bookkeeping.
A flat-list codec for the cross-silo transport lives in
``fl4health_tpu.transport.codec``.
"""

from __future__ import annotations

from typing import Any, Generic, TypeVar

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core.types import Params, PyTree

T = TypeVar("T")


@struct.dataclass
class Packet:
    """Generic exchange payload: model params + optional auxiliary pytree."""

    params: Params
    aux: Any = None


@struct.dataclass
class ControlVariatesPacket:
    """SCAFFOLD payload: weights (or deltas) + control-variate updates.

    Reference: ParameterPackerWithControlVariates (parameter_packer.py:23),
    split at size_of_model_params.
    """

    params: Params
    control_variates: Params


@struct.dataclass
class ClippingBitPacket:
    """Client-level DP payload: clipped update + clipping-indicator bit.

    Reference: ParameterPackerWithClippingBit (parameter_packer.py:45).
    """

    params: Params
    clipping_bit: jax.Array  # scalar float (0/1)


@struct.dataclass
class AdaptiveConstraintPacket:
    """FedProx-family payload: weights + train loss for mu adaptation.

    Reference: ParameterPackerAdaptiveConstraint (parameter_packer.py:57).
    """

    params: Params
    loss_for_adaptation: jax.Array  # scalar


@struct.dataclass
class LayerMaskPacket:
    """Dynamic-layer payload: full-shaped params + per-leaf selection mask.

    The reference ships (tensors, names) for an arbitrary layer subset
    (ParameterPackerWithLayerNames, parameter_packer.py:72). Under SPMD we keep
    static shapes: every leaf is present, ``leaf_mask`` is a pytree of scalar
    0/1 floats marking which leaves this client actually "sent". Aggregation
    averages each leaf only over senders (strategies/fedavg_dynamic_layer.py:17).
    """

    params: Params
    leaf_mask: PyTree  # same structure, scalar 0/1 per leaf


@struct.dataclass
class SparseMaskPacket:
    """Sparse payload: params + dense 0/1 element mask per leaf.

    The reference ships COO (values, indices, shapes, names)
    (SparseCooParameterPacker, parameter_packer.py:94). A dense mask is the
    XLA-friendly encoding with identical semantics; the transport codec can
    convert to real COO at the host boundary for wire compactness.
    """

    params: Params
    element_mask: PyTree  # same structure/shape, 0/1


def packet_like(params: Params) -> Packet:
    return Packet(params=params, aux=None)


def full_leaf_mask(params: Params) -> PyTree:
    return jax.tree_util.tree_map(lambda _: jnp.ones((), jnp.float32), params)


def full_element_mask(params: Params) -> PyTree:
    return jax.tree_util.tree_map(lambda x: jnp.ones_like(x, jnp.float32), params)

"""Parameter exchangers — which part of the model crosses the exchange boundary.

Reference surface (/root/reference/fl4health/parameter_exchange/):
- ParameterExchanger ABC: push_parameters / pull_parameters (parameter_exchanger_base.py:8)
- FullParameterExchanger (full_exchanger.py:10)
- FixedLayerExchanger / LayerExchangerWithExclusions (layer_exchanger.py:17,56)
- DynamicLayerExchanger — drift-norm threshold / top-% selection (layer_exchanger.py:119,
  selection criteria parameter_selection_criteria.py:74-199)
- SparseCooParameterExchanger — scored parameter subsets (sparse_coo_parameter_exchanger.py:18)

TPU-native design: an exchanger is a pair of pure functions over pytrees.
``push(local_params, initial_params)`` produces the payload sent "up";
``pull(payload, local_params)`` merges a received payload into local params.
Partial exchange is expressed with boolean leaf masks (static structure) so
push/pull jit-compile; dynamic selection computes the mask from drift norms
inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params, PyTree
from fl4health_tpu.exchange.packer import LayerMaskPacket, SparseMaskPacket


class FullExchanger:
    """Exchange every leaf (full_exchanger.py:10).

    All exchangers share one protocol: ``push(params, initial_params=None)``
    and ``pull(payload, local)`` — callers can swap exchangers polymorphically
    like the reference's ParameterExchanger ABC.
    """

    def push(self, params: Params, initial_params: Params | None = None) -> Params:
        del initial_params
        return params

    def pull(self, payload: Params, local: Params) -> Params:
        del local
        return payload


@dataclasses.dataclass(frozen=True)
class FixedLayerExchanger:
    """Exchange only leaves whose dotted path satisfies ``include``.

    Covers FixedLayerExchanger (model.layers_to_exchange()) and
    LayerExchangerWithExclusions (e.g. FedBN excluding norm layers,
    layer_exchanger.py:56) — exclusion is just the negated predicate.
    """

    include: Callable[[str], bool]

    def mask(self, params: Params) -> PyTree:
        return ptu.select_by_path(params, self.include)

    def push(self, params: Params, initial_params: Params | None = None) -> Params:
        # Non-exchanged leaves are zeroed; pull() never reads them. Keeping the
        # full structure keeps stacked shapes static across clients.
        del initial_params
        mask = self.mask(params)
        return jax.tree_util.tree_map(
            lambda m, p: p if m else jnp.zeros_like(p), mask, params
        )

    def pull(self, payload: Params, local: Params) -> Params:
        mask = self.mask(local)
        return ptu.merge_by_mask(mask, payload, local)


def fixed_exchanger_excluding(excluded: Sequence[str]) -> FixedLayerExchanger:
    """Exchange all leaves except those whose path contains an excluded marker."""
    excluded = tuple(excluded)
    return FixedLayerExchanger(
        include=lambda path: not any(s in path for s in excluded)
    )


def fixed_exchanger_including(included: Sequence[str]) -> FixedLayerExchanger:
    """Exchange only leaves whose path contains one of the markers."""
    included = tuple(included)
    return FixedLayerExchanger(include=lambda path: any(s in path for s in included))


def norm_exclusion_exchanger() -> FixedLayerExchanger:
    """FedBN: exchange everything except normalization statistics/params.

    Reference: clients/fedbn_client.py:7 + LayerExchangerWithExclusions.
    Matches flax naming conventions (BatchNorm/LayerNorm/GroupNorm modules and
    batch_stats collections).
    """
    exact = {"bn", "norm", "batch_stats", "batchnorm", "layernorm", "groupnorm"}
    prefixes = ("BatchNorm", "LayerNorm", "GroupNorm", "bn_", "norm_")

    def _is_norm_segment(seg: str) -> bool:
        return seg.lower() in exact or seg.startswith(prefixes)

    # Match whole path segments, not raw substrings — 'subnet.kernel' must NOT
    # be excluded just because 'bn' appears inside 'subnet'.
    return FixedLayerExchanger(
        include=lambda path: not any(_is_norm_segment(s) for s in path.split("."))
    )


@dataclasses.dataclass(frozen=True)
class DynamicLayerExchanger:
    """Per-round leaf selection by drift norm (layer_exchanger.py:119).

    Selection criteria mirror parameter_selection_criteria.py:
    - threshold mode: select leaf if ||local - initial||_2 (optionally
      normalized by sqrt(n)) exceeds ``threshold`` (:74,114)
    - top-k mode: select the ceil(exchange_fraction * n_leaves) largest-drift
      leaves (:143-199)
    Output is a LayerMaskPacket; FedAvgDynamicLayer aggregates per-leaf over
    senders only.
    """

    mode: str = "threshold"  # "threshold" | "topk"
    threshold: float = 0.1
    exchange_fraction: float = 0.5
    normalized: bool = True
    # the simulation hands pull() the strategy's FULL payload (a packet with
    # the updated-leaf mask), not just its params — retention needs the mask
    wants_packet_payload = True

    def __post_init__(self):
        if self.mode not in ("threshold", "topk"):
            raise ValueError(f"mode must be 'threshold' or 'topk', got {self.mode!r}")

    def push(self, params: Params, initial_params: Params | None = None) -> LayerMaskPacket:
        if initial_params is None:
            raise ValueError("DynamicLayerExchanger.push needs initial_params "
                             "(drift is measured against the round's received params)")
        drift = ptu.tree_sub(params, initial_params)
        norms = jax.tree_util.tree_map(
            lambda d: jnp.linalg.norm(d.reshape(-1))
            / (jnp.sqrt(jnp.float32(d.size)) if self.normalized else 1.0),
            drift,
        )
        flat_norms, treedef = jax.tree_util.tree_flatten(norms)
        scores = jnp.stack(flat_norms)
        if self.mode == "threshold":
            sel = (scores > self.threshold).astype(jnp.float32)
        else:
            import math

            # static python math: k must be a trace-time constant for the
            # [:k] slice (int() of a jnp value fails under jit in jax>=0.9).
            # epsilon keeps mathematically-integral products (0.1*30) from
            # ceiling up one extra leaf on binary round-off.
            k = max(1, math.ceil(self.exchange_fraction * len(flat_norms) - 1e-9))
            top = jnp.argsort(-scores)[:k]
            sel = jnp.zeros((len(flat_norms),), jnp.float32).at[top].set(1.0)
        leaf_mask = jax.tree_util.tree_unflatten(
            treedef, [sel[i] for i in range(len(flat_norms))]
        )
        masked = jax.tree_util.tree_map(
            lambda m, p: (m * p).astype(p.dtype), leaf_mask, params
        )
        return LayerMaskPacket(params=masked, leaf_mask=leaf_mask)

    def pull(self, payload: LayerMaskPacket | Params, local: Params) -> Params:
        # The strategy's payload is a LayerMaskPacket whose leaf_mask marks
        # server leaves refreshed by aggregation: only those replace local
        # weights; everything else stays client-local (the reference ships
        # only the aggregated layer subset back, fedavg_dynamic_layer.py).
        # A bare params payload (e.g. a checkpoint restore) replaces fully.
        if not isinstance(payload, LayerMaskPacket):
            return jax.tree_util.tree_map(
                lambda srv, loc: srv.astype(loc.dtype), payload, local
            )
        return jax.tree_util.tree_map(
            lambda m, srv, loc: (m * srv + (1.0 - m) * loc).astype(loc.dtype),
            payload.leaf_mask,
            payload.params,
            local,
        )


@dataclasses.dataclass(frozen=True)
class SparseExchanger:
    """Scored element-subset exchange (sparse_coo_parameter_exchanger.py:18).

    ``score_fn(params, initial_params) -> score tree`` (same shapes); the top
    ``sparsity_level`` fraction of ALL elements (global top-k over the flat
    vector, matching largest_final_magnitude_scores-style criteria) is sent.
    """

    sparsity_level: float = 0.1
    score_fn: Callable[[Params, Params], PyTree] = None  # type: ignore[assignment]
    wants_packet_payload = True

    def _scores(self, params: Params, initial: Params) -> PyTree:
        if self.score_fn is not None:
            return self.score_fn(params, initial)
        # Default: largest final magnitude (parameter_selection_criteria.py)
        return jax.tree_util.tree_map(jnp.abs, params)

    def push(self, params: Params, initial_params: Params | None = None) -> SparseMaskPacket:
        if initial_params is None and self.score_fn is not None:
            raise ValueError("SparseExchanger.push needs initial_params when a "
                             "drift-based score_fn is set")
        scores = self._scores(params, initial_params)
        flat_scores, unravel = ptu.ravel(scores)
        n = flat_scores.shape[0]
        k = max(1, min(n, int(round(self.sparsity_level * n))))
        # Exact top-k (ties broken by index) — a >=threshold test over-selects
        # when scores tie, e.g. mostly-zero weights would degrade to full exchange.
        _, top_idx = jax.lax.top_k(flat_scores, k)
        mask_flat = jnp.zeros((n,), jnp.float32).at[top_idx].set(1.0)
        mask = unravel(mask_flat)
        masked = jax.tree_util.tree_map(
            lambda m, p: (m * p).astype(p.dtype), mask, params
        )
        return SparseMaskPacket(params=masked, element_mask=mask)

    def pull(self, payload: SparseMaskPacket | Params, local: Params) -> Params:
        # element_mask marks server elements refreshed by aggregation (see
        # DynamicLayerExchanger.pull note); bare params replace fully.
        if not isinstance(payload, SparseMaskPacket):
            return jax.tree_util.tree_map(
                lambda srv, loc: srv.astype(loc.dtype), payload, local
            )
        return jax.tree_util.tree_map(
            lambda m, srv, loc: (m * srv + (1.0 - m) * loc).astype(loc.dtype),
            payload.element_mask,
            payload.params,
            local,
        )

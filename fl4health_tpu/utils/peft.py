"""PEFT / LoRA parameter filtering — the pytree equivalent of peft adapters.

Parity surface (/root/reference/fl4health/utils/peft_parameter_extraction.py:7
``get_all_peft_parameters_from_model``: collects the adapter-injected
parameters from a HF peft model so only they cross the wire;
/root/reference/examples/fedllm_example trains LoRA adapters federally).

TPU-native design: adapters are ordinary params named ``lora_a``/``lora_b``
(models/transformer.py LoraDense). "PEFT" is then two orthogonal filters on
the SAME pytree:

- the exchanger filter (what crosses the wire) — ``lora_exchanger()``,
- the optimizer mask (what trains locally)     — ``lora_trainable_mask`` +
  ``masked_optimizer``.

No module surgery, no adapter classes: path predicates compose with every
existing exchanger/strategy because the param structure never changes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import optax

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger

# Path segments that mark PEFT-trainable leaves: the LoRA factors plus the
# task head (peft convention: `modules_to_save=["classifier"]`).
LORA_MARKERS: tuple[str, ...] = ("lora_a", "lora_b", "classifier")


def peft_parameter_paths(params: Params, markers: Sequence[str] = LORA_MARKERS) -> list[str]:
    """Dotted paths of all PEFT parameters (get_all_peft_parameters_from_model
    equivalent — returns paths rather than tensors because pytree leaves are
    addressed, not owned)."""
    marks = tuple(markers)
    paths = []
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for key_path, _ in flat:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        if any(m in dotted.split(".") for m in marks):
            paths.append(dotted)
    return paths


def lora_exchanger(markers: Sequence[str] = LORA_MARKERS) -> FixedLayerExchanger:
    """Wire filter: only adapters (+ head) cross the wire — the federated
    LoRA exchange the fedllm example gets from peft's state-dict filtering.

    Matches whole path SEGMENTS (like ``lora_trainable_mask``), not raw
    substrings: a module merely named "aux_classifier_head" must not leak
    onto the wire while staying frozen locally.
    """
    marks = tuple(markers)
    return FixedLayerExchanger(
        include=lambda path: any(m in path.split(".") for m in marks)
    )


def lora_trainable_mask(params: Params, markers: Sequence[str] = LORA_MARKERS):
    """Bool pytree: True where the leaf should train (adapters + head)."""
    marks = tuple(markers)
    return ptu.select_by_path(
        params, lambda path: any(m in path.split(".") for m in marks)
    )


def masked_optimizer(
    tx: optax.GradientTransformation, trainable_mask
) -> optax.GradientTransformation:
    """Freeze untrainable leaves: real updates where mask is True, zeros
    elsewhere (optax.multi_transform over the bool mask). The frozen base
    weights still live in params, so exchangers/checkpointers see the full
    model."""
    labels = jax.tree_util.tree_map(
        lambda t: "train" if t else "freeze", trainable_mask
    )
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )

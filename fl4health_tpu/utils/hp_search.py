"""Hyperparameter sweep + selection — the research-harness role.

Parity surface (/root/reference/research/*/find_best_hp.py, e.g.
research/flamby/find_best_hp.py:36 ``main``: walk a sweep directory of
hp_folders each holding Run*/server.out logs, average the final weighted
loss over runs, pick the folder with the lowest mean): the reference selects
hyperparameters by scraping per-run log files produced by Slurm jobs.

TPU-native design: runs are in-process simulations, so the sweep is a
function — `sweep(builder, grid, n_seeds)` executes every config x seed,
aggregates the selection metric over seeds, and returns the ranked results.
A directory-walking twin (`find_best_hp_dir`) keeps the reference's
file-based contract for sweeps executed as separate jobs that dropped
JsonReporter outputs.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from pathlib import Path
from typing import Any, Callable, Mapping, Sequence

import numpy as np


@dataclasses.dataclass
class HpResult:
    params: dict[str, Any]
    scores: list[float]  # one per seed

    @property
    def mean_score(self) -> float:
        return float(np.mean(self.scores))


def hp_grid(**axes: Sequence[Any]) -> list[dict[str, Any]]:
    """Cartesian product of named axes -> list of hp dicts."""
    names = sorted(axes)
    return [
        dict(zip(names, combo))
        for combo in itertools.product(*(axes[n] for n in names))
    ]


def sweep(
    builder: Callable[..., Any],
    grid: Sequence[Mapping[str, Any]],
    n_rounds: int,
    n_seeds: int = 1,
    score: Callable[[Any], float] | None = None,
    minimize: bool = True,
) -> list[HpResult]:
    """Run every hp dict (x seeds), rank by the mean selection score.

    ``builder(seed=..., **hp)`` returns a FederatedSimulation (or any object
    with ``fit(n_rounds) -> history``); ``score(history)`` defaults to the
    final round's checkpoint eval loss (the reference's weighted-loss
    selection). Results come back sorted best-first.
    """
    if score is None:
        score = lambda history: float(history[-1].eval_losses["checkpoint"])  # noqa: E731
    results = []
    for hp in grid:
        scores = []
        for seed in range(n_seeds):
            sim = builder(seed=seed, **hp)
            history = sim.fit(n_rounds)
            if isinstance(history, tuple):  # DP servers: (history, epsilon)
                history = history[0]
            scores.append(score(history))
        results.append(HpResult(params=dict(hp), scores=scores))
    return sorted(results, key=lambda r: r.mean_score if minimize else -r.mean_score)


def _lookup(record: Mapping[str, Any], dotted: str) -> float | None:
    """Resolve a dotted metric path ("eval_losses.checkpoint") in a record."""
    node: Any = record
    for part in dotted.split("."):
        if not isinstance(node, Mapping) or part not in node:
            return None
        node = node[part]
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def _final_metric_from_doc(doc: Any, metric: str) -> float | None:
    """Last-record metric from any supported document shape:
    - JsonReporter dump: {"rounds": {"1": {...}, ...}} — last round's record,
      metric as a dotted path (e.g. "eval_losses.checkpoint");
    - a list of flat records (JSONL-style) — last record carrying the metric.
    """
    if isinstance(doc, Mapping) and isinstance(doc.get("rounds"), Mapping):
        rounds = doc["rounds"]
        # non-integer round keys (stray config/summary files swept up by the
        # *.json glob) make the file invalid, not the whole sweep
        int_keys = []
        for key in rounds:
            try:
                int_keys.append((int(key), key))
            except (TypeError, ValueError):
                continue
        for _, key in sorted(int_keys, reverse=True):
            value = _lookup(rounds[key], metric)
            if value is not None:
                return value
        return None
    records = doc if isinstance(doc, list) else [doc]
    for rec in reversed(records):
        value = _lookup(rec, metric)
        if value is not None:
            return value
    return None


def find_best_hp_dir(
    sweep_dir: str | Path,
    metric: str = "eval_losses.checkpoint",
    minimize: bool = True,
) -> tuple[Path | None, float | None]:
    """File-based selection (find_best_hp.py:36 semantics): each hp folder
    holds per-run JSON files — JsonReporter dumps (any name, nested
    {"rounds": ...}; reporting/base.py) or JSONL metric records. The last
    record's ``metric`` (a dotted path) counts per run; the folder with the
    best mean over runs wins."""
    sweep_dir = Path(sweep_dir)
    best_folder, best_score = None, None
    for hp_folder in sorted(p for p in sweep_dir.iterdir() if p.is_dir()):
        run_scores = []
        run_dirs = sorted(hp_folder.glob("Run*")) or [hp_folder]
        for run in run_dirs:
            # ONE score per run: the newest parseable dump wins, so a stale
            # reporter file left beside a re-run's dump cannot double-count
            candidates = sorted(
                run.glob("*.json"), key=lambda f: f.stat().st_mtime,
                reverse=True,
            )
            for metrics_file in candidates:
                text = metrics_file.read_text()
                try:
                    doc = json.loads(text)
                except json.JSONDecodeError:
                    try:
                        doc = [
                            json.loads(line)
                            for line in text.splitlines()
                            if line.strip()
                        ]
                    except json.JSONDecodeError:
                        continue
                value = _final_metric_from_doc(doc, metric)
                if value is not None:
                    run_scores.append(value)
                    break
        if not run_scores:
            continue
        mean = float(np.mean(run_scores))
        better = best_score is None or (
            mean <= best_score if minimize else mean >= best_score
        )
        if better:
            best_folder, best_score = hp_folder, mean
    return best_folder, best_score

"""Shared tunnel-probe helpers for bench.py and tools/tpu_watch.py.

The axon TPU tunnel hangs at backend init when down, so liveness is decided
by a subprocess probe under a timeout. Both the bench parent and the watcher
need the identical policy for "which platform strings count as the chip" —
keeping it here prevents the two from drifting (r5 review finding).

The probe child prints a sentinel-prefixed line so trailing plugin banners
or info messages on stdout can never be misread as a platform string.
"""

from __future__ import annotations

import json
import subprocess
import sys

_SENTINEL = "FL4HEALTH_PLATFORM="

_PROBE_SRC = (
    "import jax; "
    f"print('{_SENTINEL}' + jax.devices()[0].platform)"
)


def probe_platform(timeout_s: int, cwd: str | None = None) -> str:
    """Return the live backend's platform string, 'down' on timeout (a dead
    tunnel hangs at backend init), or 'error: <stderr tail>' when the probe
    child crashed outright — a broken environment (missing plugin, bad
    PYTHONPATH) must stay distinguishable from a dead tunnel in the logs."""
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        return "down"
    if res.returncode != 0:
        tail = res.stderr.strip().splitlines()
        return f"error: {tail[-1][:200] if tail else f'rc={res.returncode}'}"
    for line in reversed(res.stdout.splitlines()):
        if line.startswith(_SENTINEL):
            return line[len(_SENTINEL):].strip()
    return ""


def is_accelerator(platform: str) -> bool:
    """Any live backend that isn't XLA:CPU is the tunneled chip (the axon
    plugin's exact platform string can't be confirmed while the tunnel is
    down, so don't gate on the literal 'tpu')."""
    return platform not in ("", "cpu", "down") and not platform.startswith("error")


def live_device_summary() -> dict:
    """Identity + published peaks of the ALREADY-initialized backend's
    first device — the in-process complement of ``probe_platform`` (which
    exists for the pre-init "is the tunnel even alive" question). Shared by
    the observability run manifest and ``bench.py`` provenance so the
    "which chip, what peak" policy lives in one place."""
    import jax

    from fl4health_tpu.observability import device_specs

    devices = jax.devices()
    d = devices[0]
    kind = getattr(d, "device_kind", "unknown")
    return {
        "platform": d.platform,
        "device_kind": kind,
        "device_count": len(devices),
        "accelerator": is_accelerator(d.platform),
        "peak_bf16_flops": device_specs.peak_bf16_flops(kind),
        "device_memory_bytes": device_specs.device_memory_bytes(d),
    }


def last_json_line(text: str) -> dict | None:
    """Parse the LAST valid JSON object line from child stdout (later lines
    supersede earlier partial/progress output)."""
    for line in reversed(text.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None

"""Process-boot platform handling shared by every entry script.

The axon sitecustomize imports jax at interpreter boot and forces
``jax_platforms="axon,cpu"``, overriding the JAX_PLATFORMS env var — so a
script that wants the CPU backend (tests, sweeps, examples on a host whose
TPU tunnel may be absent or wedged) must override via jax.config BEFORE the
backend initializes. One implementation here instead of a copy per script
(examples/_lib.py, research sweeps, __graft_entry__.py all need it).
"""

from __future__ import annotations

import os


def honor_cpu_platform_request() -> bool:
    """If the environment asks for cpu FIRST (``JAX_PLATFORMS=cpu,...``),
    force the cpu backend before initialization. Returns True if forced.
    Call before any jax computation; safe to call repeatedly."""
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] != "cpu":
        return False
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False
    return True

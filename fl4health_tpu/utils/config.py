"""YAML config loading + validation.

Parity: /root/reference/fl4health/utils/config.py:19-98 — load_config /
check_config (requires n_server_rounds, positive-int checks), narrow_dict_type
runtime narrowing, epochs-xor-steps helper.
"""

from __future__ import annotations

from typing import Any, Mapping, TypeVar

T = TypeVar("T")


class InvalidConfigError(ValueError):
    pass


def load_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    check_config(cfg)
    return cfg


def _positive_int(val: Any) -> bool:
    # bool is an int subclass; `n_server_rounds: true` must not validate
    return isinstance(val, int) and not isinstance(val, bool) and val > 0


def check_config(config: Mapping[str, Any]) -> None:
    """Required keys + type/positivity checks (utils/config.py:29)."""
    if not isinstance(config, Mapping):
        # yaml.safe_load of an empty file returns None; report it as the
        # config error it is, not a TypeError from the `in` below
        raise InvalidConfigError(
            f"config must be a mapping, got {type(config).__name__}"
        )
    if "n_server_rounds" not in config:
        raise InvalidConfigError("config missing required key n_server_rounds")
    if not _positive_int(config["n_server_rounds"]):
        raise InvalidConfigError("n_server_rounds must be a positive integer")
    for key in ("local_epochs", "local_steps", "batch_size"):
        if key in config and config[key] is not None:
            if not _positive_int(config[key]):
                raise InvalidConfigError(f"{key} must be a positive integer")


def narrow_dict_type(config: Mapping[str, Any], key: str, ty: type[T]) -> T:
    """Typed access with a clear error (utils/config.py:47)."""
    if key not in config:
        raise InvalidConfigError(f"config missing key {key}")
    val = config[key]
    if not isinstance(val, ty):
        raise InvalidConfigError(
            f"config[{key!r}] should be {ty.__name__}, got {type(val).__name__}"
        )
    return val


def epochs_steps_from_config(config: Mapping[str, Any]) -> tuple[int | None, int | None]:
    """Exactly one of local_epochs / local_steps (utils/config.py:98)."""
    epochs = config.get("local_epochs")
    steps = config.get("local_steps")
    if (epochs is None) == (steps is None):
        raise InvalidConfigError("specify exactly one of local_epochs / local_steps")
    return epochs, steps

"""Reproducibility helpers.

Parity: /root/reference/fl4health/utils/random.py:11-86 —
set_all_random_seeds (torch/np/random + deterministic flags) and RNG
state save/restore. JAX is functional so "seeding" is key construction, but
host-side NumPy/python RNGs (partitioners, batch order) still need seeding.
"""

from __future__ import annotations

import random as _random

import jax
import numpy as np


def set_all_random_seeds(seed: int = 42) -> jax.Array:
    """Seed python + NumPy global RNGs and return the root JAX key."""
    _random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def save_random_state() -> tuple:
    return (_random.getstate(), np.random.get_state())


def restore_random_state(state: tuple) -> None:
    py_state, np_state = state
    _random.setstate(py_state)
    np.random.set_state(np_state)

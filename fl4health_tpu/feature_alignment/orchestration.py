"""Tabular feature-alignment orchestration — the two-poll protocol.

Parity surface (/root/reference/fl4health/servers/
tabular_feature_alignment_server.py:27 ``TabularFeatureAlignmentServer``,
/root/reference/fl4health/clients/tabular_data_client.py:22
``TabularDataClient``): before round 1 the server runs up to two polls —
(1) if it has no feature-info source of truth, poll ONE random client for
its schema (the source of truth for alignment, :156); broadcast it via the
config with ``source_specified`` flipped true; (2) after clients align
their local frames to that schema, poll one client for the model's
input/output dimensions (:113,:168) — only then is the global model
initializable and normal federated rounds begin.

TPU-native design: polls are in-process property lookups
(server/servers.py poll_clients); the schema travels as JSON (never
pickle); client-side alignment is the numpy/pandas preprocessor
(feature_alignment/preprocessor.py) whose output feeds the standard
stacked-tensor engine. Model construction stays deferred exactly as in the
reference — the simulation is built only after both polls resolve.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from fl4health_tpu.feature_alignment.preprocessor import TabularFeaturesPreprocessor
from fl4health_tpu.feature_alignment.schema import TabularFeaturesInfoEncoder
from fl4health_tpu.server.servers import poll_clients

logger = logging.getLogger(__name__)

# Wire keys (constants.py:25 equivalents).
FEATURE_INFO = "feature_info"
SOURCE_SPECIFIED = "source_specified"
INPUT_DIMENSION = "input_dimension"
OUTPUT_DIMENSION = "output_dimension"


class TabularDataClient:
    """Client half (tabular_data_client.py:22): owns a raw DataFrame; on the
    first poll offers its own schema; on the second poll aligns its frame to
    the server-chosen schema and reports the encoded dimensions.
    """

    def __init__(self, df, id_column: str, target_columns: Sequence[str]):
        self.df = df
        self.id_column = id_column
        self.target_columns = list(target_columns)
        self.aligned: tuple[np.ndarray, np.ndarray] | None = None
        self.preprocessor: TabularFeaturesPreprocessor | None = None

    # -- the get_properties handler (:146) ---------------------------------
    def get_properties(self, request: Mapping[str, Any]) -> dict[str, Any]:
        if not request.get(SOURCE_SPECIFIED, False):
            # Poll 1: offer the local schema as a source-of-truth candidate.
            encoder = TabularFeaturesInfoEncoder.encoder_from_dataframe(
                self.df, self.id_column, self.target_columns
            )
            return {FEATURE_INFO: encoder.to_json()}
        # Poll 2: align to the broadcast schema, report dimensions. The
        # output dimension is the schema's target width (number of classes
        # for an ordinal/binary target), not the encoded column count — the
        # model head must cover every class the source of truth knows.
        self.align(request[FEATURE_INFO])
        assert self.aligned is not None
        x, _y = self.aligned
        encoder = TabularFeaturesInfoEncoder.from_json(request[FEATURE_INFO])
        return {
            INPUT_DIMENSION: int(x.shape[1]),
            OUTPUT_DIMENSION: max(int(encoder.get_target_dimension()), 1),
        }

    # -- alignment (setup_client, :85-135) ---------------------------------
    def align(self, feature_info_json: str) -> tuple[np.ndarray, np.ndarray]:
        """Fit the preprocessor induced by the GLOBAL schema on the LOCAL
        frame and encode. Columns the schema knows but the frame lacks are
        imputed with the schema's fill values; local-only columns drop — the
        definition of alignment."""
        encoder = TabularFeaturesInfoEncoder.from_json(feature_info_json)
        self.preprocessor = TabularFeaturesPreprocessor(encoder).fit(self.df)
        self.aligned = self.preprocessor.preprocess_features(self.df)
        return self.aligned

    def aligned_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        assert self.aligned is not None, "align() has not run (poll 2 missing)"
        return self.aligned


class TabularFeatureAlignmentServer:
    """Server half: two pre-training polls, then deferred model construction
    and the normal federated rounds.

    ``sim_builder(input_dim, output_dim, clients)`` receives the ALIGNED
    clients and builds the FederatedSimulation (the reference's
    ``initialize_parameters`` + FlServer.fit composition, :113-160).
    """

    def __init__(
        self,
        config: dict[str, Any],
        clients: Sequence[TabularDataClient],
        sim_builder: Callable[[int, int, Sequence[TabularDataClient]], Any],
        feature_info_source: str | None = None,
        seed: int = 0,
    ):
        self.config = dict(config)
        self.clients = list(clients)
        self.sim_builder = sim_builder
        self.tab_features_info = feature_info_source
        self.seed = seed
        self.source_info_gathered = False
        self.dimension_info: dict[str, int] = {}
        self.initial_polls_complete = False
        self.sim = None

    # ------------------------------------------------------------------
    def poll_clients_for_feature_info(self) -> str:
        """Poll 1 (:161): ONE random client's schema becomes the source of
        truth."""
        logger.info("Feature info source unspecified — polling one random client.")
        idx = int(np.random.default_rng(self.seed).integers(len(self.clients)))
        request = {**self.config, SOURCE_SPECIFIED: False}
        props = poll_clients([self.clients[idx].get_properties], request)[0]
        return str(props[FEATURE_INFO])

    def poll_clients_for_dimension_info(self) -> tuple[int, int]:
        """Poll 2 (:168): ALL clients align (the broadcast does real work on
        every client); dimensions are read from the first since aligned
        frames agree by construction."""
        request = {
            **self.config,
            SOURCE_SPECIFIED: True,
            FEATURE_INFO: self.config[FEATURE_INFO],
        }
        results = poll_clients(
            [c.get_properties for c in self.clients], request
        )
        dims = {(r[INPUT_DIMENSION], r[OUTPUT_DIMENSION]) for r in results}
        assert len(dims) == 1, f"aligned clients disagree on dimensions: {dims}"
        return results[0][INPUT_DIMENSION], results[0][OUTPUT_DIMENSION]

    # ------------------------------------------------------------------
    def fit(self, n_rounds: int):
        if not self.initial_polls_complete:
            if self.tab_features_info is None:
                feature_info = self.poll_clients_for_feature_info()
            else:
                logger.info("Feature info source specified — broadcasting as-is.")
                feature_info = self.tab_features_info
            self.config[FEATURE_INFO] = feature_info
            self.source_info_gathered = True

            in_dim, out_dim = self.poll_clients_for_dimension_info()
            self.dimension_info[INPUT_DIMENSION] = in_dim
            self.dimension_info[OUTPUT_DIMENSION] = out_dim
            self.initial_polls_complete = True
            logger.info("Feature alignment complete: input_dim=%d output_dim=%d",
                        in_dim, out_dim)

        self.sim = self.sim_builder(
            self.dimension_info[INPUT_DIMENSION],
            self.dimension_info[OUTPUT_DIMENSION],
            self.clients,
        )
        return self.sim.fit(n_rounds)

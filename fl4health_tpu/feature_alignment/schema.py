"""Tabular feature schema: types, per-feature info, JSON-serializable encoder.

Parity targets (/root/reference/fl4health/feature_alignment/):
- tabular_type.py:8 TabularType + per-type default fill values (:15-37).
- tabular_feature.py:13 TabularFeature (name, type, fill value, metadata;
  metadata = categories for BINARY/ORDINAL, vocabulary for STRING).
- tab_features_info_encoder.py:14 TabularFeaturesInfoEncoder — the
  JSON-serializable "source of truth" one client provides and the server
  broadcasts so every client encodes identically.
- handle_types.py:470-568 type inference from raw columns.

Host-side by design: schema negotiation happens once before training (the
reference ships it inside config dicts over gRPC); no jit surface.
"""

from __future__ import annotations

import enum
import json
import re
from dataclasses import dataclass, field
from typing import Any

import numpy as np


class TabularType(str, enum.Enum):
    """(tabular_type.py:8)"""

    NUMERIC = "numeric"
    BINARY = "binary"
    STRING = "string"
    ORDINAL = "ordinal"

    @staticmethod
    def get_default_fill_value(tabular_type: "TabularType | str") -> Any:
        """Per-type imputation default (tabular_type.py:15-37)."""
        t = TabularType(tabular_type)
        if t is TabularType.NUMERIC:
            return 0.0
        if t is TabularType.BINARY:
            return 0
        if t is TabularType.STRING:
            return "N/A"
        return "UNKNOWN"  # ORDINAL


@dataclass
class TabularFeature:
    """Per-column info (tabular_feature.py:13)."""

    feature_name: str
    feature_type: TabularType
    fill_value: Any = None
    metadata: list = field(default_factory=list)

    def __post_init__(self):
        self.feature_type = TabularType(self.feature_type)
        if self.fill_value is None:
            self.fill_value = TabularType.get_default_fill_value(self.feature_type)

    def get_feature_name(self) -> str:
        return self.feature_name

    def get_feature_type(self) -> TabularType:
        return self.feature_type

    def get_fill_value(self) -> Any:
        return self.fill_value

    def get_metadata(self) -> list:
        return self.metadata

    def get_metadata_dimension(self) -> int:
        """Aligned width of this feature (tabular_feature.py:57-62)."""
        if self.feature_type in (TabularType.BINARY, TabularType.ORDINAL):
            return len(self.metadata)
        if self.feature_type is TabularType.NUMERIC:
            return 1
        raise ValueError("metadata dimension undefined for STRING features")

    def to_json(self) -> str:
        return json.dumps(
            {
                "feature_name": self.feature_name,
                "feature_type": self.feature_type.value,
                "fill_value": self.fill_value,
                "metadata": list(self.metadata),
            }
        )

    @staticmethod
    def from_json(s: str) -> "TabularFeature":
        d = json.loads(s)
        return TabularFeature(
            d["feature_name"], TabularType(d["feature_type"]),
            d.get("fill_value"), d.get("metadata") or [],
        )


_WORD = re.compile(r"(?u)\b\w\w+\b")  # sklearn CountVectorizer token pattern


def tokenize(text: str) -> list[str]:
    return _WORD.findall(str(text).lower())


def build_vocabulary(column) -> list[str]:
    """Sorted token vocabulary of a string column (the reference fits a
    CountVectorizer for the same purpose, tab_features_info_encoder.py:76-81)."""
    vocab = set()
    for value in column:
        vocab.update(tokenize(value))
    return sorted(vocab)


def infer_feature_type(column) -> TabularType:
    """Column type inference (handle_types.py:470-500 semantics): bools and
    two-valued columns are BINARY; numeric dtypes are NUMERIC; free-text
    (multi-token) object columns are STRING; other object columns ORDINAL."""
    arr = np.asarray(column)
    non_null = arr[[v == v and v is not None for v in arr]] if arr.dtype == object else arr
    uniques = np.unique(non_null.astype(str) if arr.dtype == object else non_null)
    if len(uniques) <= 2:
        return TabularType.BINARY
    if np.issubdtype(arr.dtype, np.number) or np.issubdtype(arr.dtype, np.bool_):
        return TabularType.NUMERIC
    # Object column: free text if values are multi-token on average.
    sample = [str(v) for v in non_null[:50]]
    avg_tokens = np.mean([len(tokenize(v)) for v in sample]) if sample else 0
    if avg_tokens > 1.5:
        return TabularType.STRING
    return TabularType.ORDINAL


class TabularFeaturesInfoEncoder:
    """The serializable schema (tab_features_info_encoder.py:14). Targets are
    not included in tabular_features."""

    def __init__(self, tabular_features: list[TabularFeature],
                 tabular_targets: list[TabularFeature]):
        self.tabular_features = sorted(tabular_features, key=lambda f: f.feature_name)
        self.tabular_targets = sorted(tabular_targets, key=lambda f: f.feature_name)

    def get_tabular_features(self) -> list[TabularFeature]:
        return self.tabular_features

    def get_tabular_targets(self) -> list[TabularFeature]:
        return self.tabular_targets

    def get_feature_columns(self) -> list[str]:
        return sorted(f.feature_name for f in self.tabular_features)

    def get_target_columns(self) -> list[str]:
        return sorted(f.feature_name for f in self.tabular_targets)

    def features_by_type(self, t: TabularType) -> list[TabularFeature]:
        return sorted(
            (f for f in self.tabular_features if f.feature_type == t),
            key=lambda f: f.feature_name,
        )

    def get_target_dimension(self) -> int:
        """Width of the aligned target block (tab_features_info_encoder.py:52)."""
        return sum(t.get_metadata_dimension() for t in self.tabular_targets)

    @staticmethod
    def _construct_tab_feature(df, name: str, ftype: TabularType,
                               fill_values: dict | None) -> TabularFeature:
        """(tab_features_info_encoder.py:60-82)"""
        fill = None if fill_values is None else fill_values.get(name)
        col = df[name]
        if ftype in (TabularType.ORDINAL, TabularType.BINARY):
            cats = sorted({str(v) for v in col if v == v and v is not None})
            return TabularFeature(name, ftype, fill, cats)
        if ftype is TabularType.STRING:
            return TabularFeature(name, ftype, fill, build_vocabulary(col))
        return TabularFeature(name, ftype, fill)

    @staticmethod
    def encoder_from_dataframe(df, id_column: str, target_columns,
                               fill_values: dict | None = None
                               ) -> "TabularFeaturesInfoEncoder":
        """Infer the schema from a raw dataframe (tab_features_info_encoder.py:84)."""
        if isinstance(target_columns, str):
            target_columns = [target_columns]
        features, targets = [], []
        for name in sorted(df.columns):
            if name == id_column:
                continue
            ftype = infer_feature_type(df[name])
            feat = TabularFeaturesInfoEncoder._construct_tab_feature(
                df, name, ftype, fill_values
            )
            (targets if name in target_columns else features).append(feat)
        return TabularFeaturesInfoEncoder(features, targets)

    def to_json(self) -> str:
        return json.dumps(
            {
                "tabular_features": json.dumps([f.to_json() for f in self.tabular_features]),
                "tabular_targets": json.dumps([t.to_json() for t in self.tabular_targets]),
            }
        )

    @staticmethod
    def from_json(s: str) -> "TabularFeaturesInfoEncoder":
        d = json.loads(s)
        return TabularFeaturesInfoEncoder(
            [TabularFeature.from_json(f) for f in json.loads(d["tabular_features"])],
            [TabularFeature.from_json(t) for t in json.loads(d["tabular_targets"])],
        )

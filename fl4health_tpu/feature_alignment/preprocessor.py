"""Numpy column transforms driven by the negotiated schema.

Parity: TabularFeaturesPreprocessor (/root/reference/fl4health/
feature_alignment/tab_features_preprocessor.py:18) — one transform per
column from its TabularType, features one-hot / targets ordinal, unknown
categories handled, missing values imputed with the schema's fill value,
string columns TF-IDF'd against the shared vocabulary
(string_columns_transformer.py:9,50). Output column order is the sorted
feature-name order the reference's ColumnTransformer uses (:147-166), so
every client produces identically-shaped aligned arrays.

Built on numpy instead of sklearn pipelines: the transforms are small,
deterministic, and dependency-free; ``set_feature_pipeline`` keeps the
reference's per-column customization hook (:168).
"""

from __future__ import annotations

import math
from typing import Callable

import numpy as np

from fl4health_tpu.feature_alignment.schema import (
    TabularFeature,
    TabularFeaturesInfoEncoder,
    TabularType,
    tokenize,
)


def _impute(col: np.ndarray, fill_value) -> np.ndarray:
    out = np.array(col, dtype=object)
    missing = np.asarray([v is None or v != v for v in out])
    out[missing] = fill_value
    return out


class _NumericTransform:
    """Impute + min-max scale (tab_features_preprocessor.py:48-55). The scaler
    is fit explicitly via ``fit`` (TabularFeaturesPreprocessor.fit does this on
    the training dataframe) — or lazily on the first column seen — and the
    stored min/max are reused afterwards, matching sklearn's fit-then-transform
    pipeline so train and validation/test scale consistently."""

    def __init__(self, feature: TabularFeature):
        self.feature = feature
        self.lo: float | None = None
        self.scale: float = 1.0

    def fit(self, col: np.ndarray) -> "_NumericTransform":
        vals = _impute(col, self.feature.fill_value).astype(np.float64)
        lo, hi = float(np.min(vals)), float(np.max(vals))
        self.lo = lo
        self.scale = (hi - lo) if hi > lo else 1.0
        return self

    def __call__(self, col: np.ndarray) -> np.ndarray:
        if self.lo is None:
            self.fit(col)
        vals = _impute(col, self.feature.fill_value).astype(np.float64)
        return ((vals - self.lo) / self.scale)[:, None]


def _numeric_transform(feature: TabularFeature) -> Callable[[np.ndarray], np.ndarray]:
    return _NumericTransform(feature)


def _categorical_transform(feature: TabularFeature, one_hot: bool
                           ) -> Callable[[np.ndarray], np.ndarray]:
    """One-hot with ignored unknowns (features) or ordinal with a dedicated
    unknown code (targets) (tab_features_preprocessor.py:66-101)."""
    categories = [str(c) for c in feature.metadata]
    index = {c: i for i, c in enumerate(categories)}

    def transform(col: np.ndarray) -> np.ndarray:
        vals = [str(v) for v in _impute(col, feature.fill_value)]
        codes = np.asarray([index.get(v, -1) for v in vals])
        if one_hot:
            out = np.zeros((len(vals), len(categories)), np.float64)
            known = codes >= 0
            out[np.nonzero(known)[0], codes[known]] = 1.0  # unknown -> all-zero row
            return out
        unknown_code = len(categories) + 1  # (:78-90 OrdinalEncoder unknown_value)
        return np.where(codes >= 0, codes, unknown_code).astype(np.float64)[:, None]

    return transform


class _TfidfTransform:
    """TF-IDF against the shared vocabulary (string_columns_transformer.py:50
    wraps TfidfVectorizer(vocabulary=...)): smooth idf, l2-normalized rows —
    sklearn's defaults. idf is fit once (explicitly via ``fit`` or lazily on
    the first corpus), like the reference's fitted TfidfVectorizer."""

    def __init__(self, feature: TabularFeature):
        self.feature = feature
        self.vocab = {tok: i for i, tok in enumerate(feature.metadata)}
        self.idf: np.ndarray | None = None

    def _counts(self, col: np.ndarray) -> np.ndarray:
        docs = [tokenize(x) for x in _impute(col, self.feature.fill_value)]
        counts = np.zeros((len(docs), len(self.vocab)), np.float64)
        for row, doc in enumerate(docs):
            for tok in doc:
                j = self.vocab.get(tok)
                if j is not None:
                    counts[row, j] += 1.0
        return counts

    def fit(self, col: np.ndarray) -> "_TfidfTransform":
        counts = self._counts(col)
        n = counts.shape[0]
        df = np.count_nonzero(counts, axis=0)
        self.idf = np.log((1.0 + n) / (1.0 + df)) + 1.0  # smooth_idf
        return self

    def __call__(self, col: np.ndarray) -> np.ndarray:
        counts = self._counts(col)
        if self.idf is None:
            n = counts.shape[0]
            df = np.count_nonzero(counts, axis=0)
            self.idf = np.log((1.0 + n) / (1.0 + df)) + 1.0
        tfidf = counts * self.idf[None, :]
        norms = np.linalg.norm(tfidf, axis=1, keepdims=True)
        return tfidf / np.maximum(norms, 1e-12)


def _tfidf_transform(feature: TabularFeature) -> Callable[[np.ndarray], np.ndarray]:
    return _TfidfTransform(feature)


def _default_transform(feature: TabularFeature, one_hot: bool):
    t = feature.feature_type
    if t is TabularType.NUMERIC:
        return _numeric_transform(feature)
    if t in (TabularType.BINARY, TabularType.ORDINAL):
        return _categorical_transform(feature, one_hot=one_hot)
    return _tfidf_transform(feature)


class TabularFeaturesPreprocessor:
    """Schema-driven dataframe -> aligned arrays (tab_features_preprocessor.py:18)."""

    def __init__(self, tab_feature_encoder: TabularFeaturesInfoEncoder):
        self.encoder = tab_feature_encoder
        self.features_to_pipelines: dict[str, Callable] = {
            f.feature_name: _default_transform(f, one_hot=True)
            for f in tab_feature_encoder.get_tabular_features()
        }
        self.targets_to_pipelines: dict[str, Callable] = {
            t.feature_name: _default_transform(t, one_hot=False)
            for t in tab_feature_encoder.get_tabular_targets()
        }

    def fit(self, df) -> "TabularFeaturesPreprocessor":
        """Explicitly fit all stateful column transforms (scalers, idf) on the
        TRAINING dataframe. Callers that preprocess multiple splits should fit
        here first; otherwise transforms lazily fit on the first column they
        see, which makes call order significant."""
        n = len(df)
        for feature in self.encoder.get_tabular_features():
            pipe = self.features_to_pipelines[feature.feature_name]
            if hasattr(pipe, "fit"):
                pipe.fit(self._get_column(df, feature.feature_name,
                                          feature.fill_value, n))
        for target in self.encoder.get_tabular_targets():
            pipe = self.targets_to_pipelines[target.feature_name]
            if hasattr(pipe, "fit"):
                pipe.fit(self._get_column(df, target.feature_name,
                                          target.fill_value, n))
        return self

    def set_feature_pipeline(self, feature_name: str, transform: Callable) -> None:
        """Per-column customization hook (tab_features_preprocessor.py:168)."""
        if feature_name in self.features_to_pipelines:
            self.features_to_pipelines[feature_name] = transform
        if feature_name in self.targets_to_pipelines:
            self.targets_to_pipelines[feature_name] = transform

    def _get_column(self, df, name: str, fill_value, n_rows: int) -> np.ndarray:
        # Columns missing entirely from a client's dataframe are synthesized
        # from the fill value — the core of cross-client alignment.
        if name in df.columns:
            return np.asarray(df[name], dtype=object)
        return np.full((n_rows,), fill_value, dtype=object)

    def preprocess_features(self, df) -> tuple[np.ndarray, np.ndarray]:
        """-> (aligned_features, aligned_targets) (tabular_data_client.py:113)."""
        n = len(df)
        blocks = []
        for feature in self.encoder.get_tabular_features():  # sorted order
            col = self._get_column(df, feature.feature_name, feature.fill_value, n)
            blocks.append(self.features_to_pipelines[feature.feature_name](col))
        x = np.concatenate(blocks, axis=1) if blocks else np.zeros((n, 0))

        target_blocks = []
        for target in self.encoder.get_tabular_targets():
            col = self._get_column(df, target.feature_name, target.fill_value, n)
            target_blocks.append(self.targets_to_pipelines[target.feature_name](col))
        y = np.concatenate(target_blocks, axis=1) if target_blocks else np.zeros((n, 0))
        if y.shape[1] == 1:
            y = y[:, 0]
        return x.astype(np.float32), y.astype(np.float32)

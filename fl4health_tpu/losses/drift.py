"""Weight-drift (proximal) losses.

Parity: /root/reference/fl4health/losses/weight_drift_loss.py:5 — l2 distance
between current model params and a reference snapshot, scaled by a penalty
weight. Used by FedProx, Ditto, MR-MTL (clients/adaptive_drift_constraint_client.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params


def weight_drift_loss(
    params: Params, reference_params: Params, weight: jax.Array | float = 1.0
) -> jax.Array:
    """weight * ||params - ref||^2 summed over all leaves.

    The reference computes sum of squared per-tensor l2 norms — identical to
    the global squared norm used here.
    """
    drift = ptu.tree_sub(params, reference_params)
    sq = sum(
        jnp.sum(jnp.square(x.astype(jnp.float32)))
        for x in jax.tree_util.tree_leaves(drift)
    )
    return jnp.asarray(weight, jnp.float32) * sq

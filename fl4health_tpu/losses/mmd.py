"""Maximum-mean-discrepancy losses: multi-kernel MK-MMD and deep-kernel MMD.

Parity targets:
- MkMmdLoss (/root/reference/fl4health/losses/mkmmd_loss.py:11): MK-MMD over a
  bank of RBF kernels with length-scales ``gammas`` and simplex-ish weights
  ``betas``; betas are re-optimized by a quadratic program
  (min b^T Q b  s.t.  b^T d = 1, b >= 0) following Gretton et al., "Optimal
  Kernel Choice for Large-Scale Two-Sample Tests".
- DeepMmdLoss (/root/reference/fl4health/losses/deep_mmd_loss.py:40): learned
  deep kernel (Liu et al., "Learning Deep Kernels for Non-Parametric
  Two-Sample Tests") trained by maximizing the MMD t-statistic.

TPU-native design notes:
- Everything is vectorized over the kernel bank (no per-kernel Python loops on
  the hot path) and jit-traceable, so the losses can live inside the client's
  ``lax.scan`` train loop.
- The reference solves its beta QP with qpth/cvxpy on the host. Here the QP is
  solved *on device* with an equality-constrained closed form (one linear
  solve) refined by projected gradient descent onto
  {b >= 0, d^T b = 1} — deterministic, differentiable-free, compiled. The
  final betas are clamped and sum-normalized exactly as the reference does
  (mkmmd_loss.py:436-437), so downstream semantics match.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct


def default_gammas() -> jax.Array:
    """2^[-3.5 : 1 : 0.25] — the reference's 19-kernel bank (mkmmd_loss.py:48-50)."""
    return jnp.power(2.0, jnp.arange(-3.5, 1.25, 0.25, dtype=jnp.float32))


def uniform_betas(n_kernels: int) -> jax.Array:
    """Deterministic unit-sum init (reference uses random unit-sum; uniform is
    the seedless equivalent)."""
    return jnp.full((n_kernels,), 1.0 / n_kernels, jnp.float32)


def _sq_dists(a: jax.Array, b: jax.Array) -> jax.Array:
    """||a_i - b_j||^2, clamped at 0 (numerical PSD guard, mkmmd_loss.py:123-127)."""
    d = (
        jnp.sum(a**2, axis=1)[:, None]
        + jnp.sum(b**2, axis=1)[None, :]
        - 2.0 * a @ b.T
    )
    return jnp.maximum(d, 0.0)


def _normalize_rows(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), eps)


def _all_h_u(x: jax.Array, y: jax.Array, gammas: jax.Array) -> jax.Array:
    """h-statistic per kernel over all sample pairs -> [K, n, n].

    h_u(j, k) = u(x_j, x_k) + u(y_j, y_k) - u(x_j, y_k) - u(y_j, x_k) with
    u = exp(-||.||^2 / gamma) (mkmmd_loss.py:153-165).
    """
    ip = jnp.stack([_sq_dists(x, x), _sq_dists(y, y), _sq_dists(x, y), _sq_dists(y, x)])
    e = jnp.exp(-ip[None, :, :, :] / gammas[:, None, None, None])  # [K, 4, n, n]
    return e[:, 0] + e[:, 1] - e[:, 2] - e[:, 3]


def _all_h_u_linear(x: jax.Array, y: jax.Array, gammas: jax.Array) -> jax.Array:
    """Linear-time h-statistic over quadruples v_i = [x_{2i-1}, x_{2i},
    y_{2i-1}, y_{2i}] -> [K, n//2] (mkmmd_loss.py:73-96,135-150)."""
    n = (x.shape[0] // 2) * 2
    x, y = x[:n], y[:n]
    x0, x1 = x[0::2], x[1::2]
    y0, y1 = y[0::2], y[1::2]
    ip = jnp.stack(
        [
            jnp.sum((x0 - x1) ** 2, axis=1),
            jnp.sum((y0 - y1) ** 2, axis=1),
            jnp.sum((x0 - y1) ** 2, axis=1),
            jnp.sum((x1 - y0) ** 2, axis=1),
        ]
    )  # [4, n//2]
    e = jnp.exp(-ip[None] / gammas[:, None, None])  # [K, 4, n//2]
    return e[:, 0] + e[:, 1] - e[:, 2] - e[:, 3]


def _pair_weights(mask: jax.Array | None, n: int) -> jax.Array:
    """[n, n] pair validity from a [n] example mask (all-ones when None).

    Ragged batches are zero-padded under jit (engine.Batch.example_mask);
    padded rows must not contribute to the MMD statistics — the reference
    never sees them because torch loaders yield true-sized batches.
    """
    if mask is None:
        return jnp.ones((n, n), jnp.float32)
    m = mask.astype(jnp.float32)
    return m[:, None] * m[None, :]


def _quad_weights(mask: jax.Array | None, n_half: int) -> jax.Array:
    """[n//2] quadruple validity: all four members must be real samples."""
    if mask is None:
        return jnp.ones((n_half,), jnp.float32)
    m = mask.astype(jnp.float32)
    n = n_half * 2
    return m[:n:2] * m[1:n:2]


def _hat_d(all_h_u: jax.Array, weights: jax.Array | None = None) -> jax.Array:
    """Per-kernel MMD estimate: (weighted) mean over all sample dims -> [K]."""
    flat = all_h_u.reshape(all_h_u.shape[0], -1)
    if weights is None:
        return jnp.mean(flat, axis=1)
    w = weights.reshape(-1)
    return flat @ w / jnp.maximum(jnp.sum(w), 1e-12)


def _hat_q_full(all_h_u: jax.Array, hat_d: jax.Array,
                weights: jax.Array | None = None) -> jax.Array:
    """Kernel covariance Q_k [K, K] from the full h-statistic
    (mkmmd_loss.py:285-306): Cov est with the n^2-1 correction."""
    k, n, _ = all_h_u.shape
    centered = all_h_u - hat_d[:, None, None]
    flat = centered.reshape(k, -1)
    if weights is None:
        return (flat @ flat.T) / (n * n - 1.0)
    w = weights.reshape(-1)
    flat = flat * w[None, :]
    denom = jnp.maximum(jnp.sum(w) - 1.0, 1.0)
    return (flat @ flat.T) / denom


def _hat_q_linear(all_h_u_lin: jax.Array,
                  quad_w: jax.Array | None = None) -> jax.Array:
    """Linear-approximation Q_k from paired quadruple differences
    (mkmmd_loss.py:244-270)."""
    k, n_vi = all_h_u_lin.shape
    w = (n_vi // 2) * 2
    pairs = all_h_u_lin[:, :w].reshape(k, w // 2, 2)
    delta = pairs[:, :, 0] - pairs[:, :, 1]  # [K, W]
    if quad_w is None:
        return (delta @ delta.T) / delta.shape[1]
    qw = quad_w[:w].reshape(w // 2, 2)
    pw = qw[:, 0] * qw[:, 1]
    delta = delta * pw[None, :]
    return (delta @ delta.T) / jnp.maximum(jnp.sum(pw), 1.0)


def mkmmd(
    x: jax.Array,
    y: jax.Array,
    betas: jax.Array,
    gammas: jax.Array | None = None,
    normalize_features: bool = False,
    linear: bool = False,
    mask: jax.Array | None = None,
) -> jax.Array:
    """MK-MMD(x, y) = betas . hat_d (mkmmd_loss.py:231-251).

    ``mask`` is a 0/1 per-example validity vector (shared by x and y, which
    are paired per-sample batches here); padded rows are excluded from the
    statistics."""
    gammas = default_gammas() if gammas is None else gammas
    if normalize_features:
        x, y = _normalize_rows(x), _normalize_rows(y)
    if linear:
        h_u = _all_h_u_linear(x, y, gammas)
        w = _quad_weights(mask, h_u.shape[1]) if mask is not None else None
    else:
        h_u = _all_h_u(x, y, gammas)
        w = _pair_weights(mask, x.shape[0]) if mask is not None else None
    return jnp.dot(betas, _hat_d(h_u, w))


def _project_simplex_like(z: jax.Array, d: jax.Array, iters: int = 40) -> jax.Array:
    """Project z onto {b >= 0, d^T b = 1} by alternating projections."""
    dd = jnp.maximum(jnp.dot(d, d), 1e-12)

    def body(b, _):
        b = b + (1.0 - jnp.dot(d, b)) / dd * d  # hyperplane
        b = jnp.maximum(b, 0.0)  # orthant
        return b, None

    z, _ = jax.lax.scan(body, z, None, length=iters)
    return z


def optimize_betas(
    x: jax.Array,
    y: jax.Array,
    gammas: jax.Array | None = None,
    lambda_m: float = 1e-5,
    minimize_type_two_error: bool = True,
    normalize_features: bool = False,
    linear: bool = False,
    pg_steps: int = 100,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Re-optimize the kernel weights (mkmmd_loss.py:389-437), on device.

    minimize_type_two_error=True  -> QP: min b^T (2Q + lam I) b  s.t. b^T d = 1,
    b >= 0 (minimizing feature distance / test power direction).
    minimize_type_two_error=False -> the max of the convex objective over the
    constraint polytope is at a vertex; pick the best vertex
    (mkmmd_loss.py:337-357).
    Fallback when no kernel has positive hat_d: one-hot at extreme d_k/Q_kk
    (mkmmd_loss.py:311-335).
    """
    gammas = default_gammas() if gammas is None else gammas
    if normalize_features:
        x, y = _normalize_rows(x), _normalize_rows(y)
    if linear:
        h_u = _all_h_u_linear(x, y, gammas)
        w = _quad_weights(mask, h_u.shape[1]) if mask is not None else None
        d = _hat_d(h_u, w)
        q_k = _hat_q_linear(h_u, w)
    else:
        h_u = _all_h_u(x, y, gammas)
        w = _pair_weights(mask, x.shape[0]) if mask is not None else None
        d = _hat_d(h_u, w)
        q_k = _hat_q_full(h_u, d, w)

    k = d.shape[0]
    reg_q = 2.0 * q_k + lambda_m * jnp.eye(k, dtype=q_k.dtype)

    # Fallback: no positive hat_d -> single extreme kernel.
    base_values = d / jnp.maximum(jnp.diagonal(reg_q), 1e-12)
    extreme_idx = jnp.argmax(base_values) if minimize_type_two_error else jnp.argmin(base_values)
    beta_extreme = jax.nn.one_hot(extreme_idx, k, dtype=d.dtype)

    if minimize_type_two_error:
        # Equality-constrained closed form as warm start: b ∝ R^{-1} d.
        b0 = jnp.linalg.solve(reg_q, d)
        denom = jnp.dot(d, b0)
        b0 = jnp.where(jnp.abs(denom) > 1e-12, b0 / denom, jnp.full_like(b0, 1.0 / k))
        b0 = _project_simplex_like(b0, d)
        eta = 1.0 / (jnp.linalg.norm(reg_q) + 1e-12)

        def pg(b, _):
            b = b - eta * (reg_q @ b)
            return _project_simplex_like(b, d), None

        beta_opt, _ = jax.lax.scan(pg, b0, None, length=pg_steps)
    else:
        # Best vertex e_i / d_i of the polytope for the convex maximization.
        verts = 1.0 / jnp.where(jnp.abs(d) > 1e-12, d, 1e-12)
        obj = jnp.diagonal(reg_q) * verts**2
        best = jnp.argmax(obj)
        beta_opt = jax.nn.one_hot(best, k, dtype=d.dtype) * verts[best]

    any_positive = jnp.any(d > 0)
    raw = jnp.where(any_positive, beta_opt, beta_extreme)
    # Reference tail: clamp >= 0 and normalize to unit sum (mkmmd_loss.py:436-437).
    raw = jnp.maximum(raw, 0.0)
    total = jnp.sum(raw)
    return jnp.where(total > 1e-12, raw / total, jnp.full_like(raw, 1.0 / k))


# ---------------------------------------------------------------------------
# Deep-kernel MMD
# ---------------------------------------------------------------------------

class DeepKernelNet(nn.Module):
    """Featurizer for the learned kernel (deep_mmd_loss.py:5 ModelLatentF):
    three softplus hidden layers + linear output."""

    hidden_size: int = 10
    output_size: int = 50

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for _ in range(3):
            x = nn.softplus(nn.Dense(self.hidden_size)(x))
        return nn.Dense(self.output_size)(x)


@struct.dataclass
class DeepMmdState:
    """Learned-kernel state carried in the client's persistent extra state."""

    params: Any  # {"featurizer", "log_epsilon", "sigma_q_root", "sigma_phi_root"}
    opt_state: Any


class DeepMmd:
    """Deep-kernel MMD with the training protocol of deep_mmd_loss.py:40.

    Stateless namespace: the learnable kernel lives in a ``DeepMmdState``
    pytree so it can ride inside jit/scan carries. ``value`` computes the
    (unbiased) MMD estimate through the current kernel; ``train_step`` does
    one t-statistic ascent step on the kernel parameters.
    """

    def __init__(
        self,
        input_size: int,
        hidden_size: int = 10,
        output_size: int = 50,
        lr: float = 0.001,
        is_unbiased: bool = True,
        gaussian_degree: int = 1,
        optimization_steps: int = 5,
    ):
        self.net = DeepKernelNet(hidden_size, output_size)
        self.input_size = input_size
        self.tx = optax.adamw(lr)
        self.is_unbiased = is_unbiased
        self.gaussian_degree = gaussian_degree
        self.optimization_steps = optimization_steps

    def init(self, rng: jax.Array) -> DeepMmdState:
        k_net, k_eps = jax.random.split(rng)
        featurizer = self.net.init(k_net, jnp.zeros((1, self.input_size)))["params"]
        params = {
            "featurizer": featurizer,
            # epsilon = sigmoid-ish exp(log_eps)/(1+exp(log_eps)); init from
            # U(0,1)*1e-10 as the reference does (deep_mmd_loss.py:119-121).
            "log_epsilon": jnp.log(jax.random.uniform(k_eps, (1,)) * 1e-10 + 1e-30),
            "sigma_q_root": jnp.sqrt(jnp.asarray([2.0 * 32 * 32])),
            "sigma_phi_root": jnp.sqrt(jnp.asarray([0.005])),
        }
        return DeepMmdState(params=params, opt_state=self.tx.init(params))

    def _mmd_and_var(self, params, x: jax.Array, y: jax.Array, with_var: bool,
                     mask: jax.Array | None = None):
        """Deep-kernel MMD estimate (deep_mmd_loss.py:166-226 mmdu +
        h1_mean_var_gram). ``mask`` excludes zero-padded rows (shared by the
        paired x/y batches) from all kernel sums."""
        nx, ny = x.shape[0], y.shape[0]
        feats = self.net.apply({"params": params["featurizer"]}, jnp.concatenate([x, y], 0))
        fx, fy = feats[:nx], feats[nx:]
        eps = jax.nn.sigmoid(params["log_epsilon"][0])
        sigma_q = params["sigma_q_root"][0] ** 2
        sigma_phi = params["sigma_phi_root"][0] ** 2

        def kernel(da, db):
            # da: deep-feature distances, db: original-feature distances
            smooth = (1.0 - eps) * jnp.exp(
                -((da / sigma_phi) ** self.gaussian_degree) - db / sigma_q
            )
            return smooth + eps * jnp.exp(-db / sigma_q)

        pw = _pair_weights(mask, nx)
        m = jnp.ones((nx,), jnp.float32) if mask is None else mask.astype(jnp.float32)
        n_valid = jnp.maximum(jnp.sum(m), 2.0)

        k_x = kernel(_sq_dists(fx, fx), _sq_dists(x, x)) * pw
        k_y = kernel(_sq_dists(fy, fy), _sq_dists(y, y)) * pw
        k_xy = kernel(_sq_dists(fx, fy), _sq_dists(x, y)) * pw

        if self.is_unbiased:
            xx = (jnp.sum(k_x) - jnp.sum(jnp.diagonal(k_x))) / (n_valid * (n_valid - 1))
            yy = (jnp.sum(k_y) - jnp.sum(jnp.diagonal(k_y))) / (n_valid * (n_valid - 1))
            xy = (jnp.sum(k_xy) - jnp.sum(jnp.diagonal(k_xy))) / (n_valid * (n_valid - 1))
        else:
            xx = jnp.sum(k_x) / (n_valid * n_valid)
            yy = jnp.sum(k_y) / (n_valid * n_valid)
            xy = jnp.sum(k_xy) / (n_valid * n_valid)
        mmd2 = xx - 2.0 * xy + yy
        if not with_var:
            return mmd2, None
        h = k_x + k_y - k_xy - k_xy.T
        v1 = (4.0 / n_valid**3) * jnp.dot(jnp.sum(h, axis=1), jnp.sum(h, axis=1))
        v2 = (4.0 / n_valid**4) * jnp.sum(h) ** 2
        return mmd2, v1 - v2 + 1e-8

    def value(self, state: DeepMmdState, x: jax.Array, y: jax.Array,
              mask: jax.Array | None = None) -> jax.Array:
        """MMD through the current kernel; gradients flow to x/y only (the
        kernel is a constant here, as in compute_kernel deep_mmd_loss.py:279)."""
        params = jax.lax.stop_gradient(state.params)
        mmd2, _ = self._mmd_and_var(params, x, y, with_var=False, mask=mask)
        return mmd2

    def train_step(self, state: DeepMmdState, x: jax.Array, y: jax.Array,
                   rng: jax.Array, mask: jax.Array | None = None) -> DeepMmdState:
        """One ascent step on J = MMD^2 / sqrt(Var) (deep_mmd_loss.py:228-277)."""
        x = jax.lax.stop_gradient(x)
        y = jax.lax.stop_gradient(y)
        perm = jax.random.permutation(rng, y.shape[0])
        if mask is not None:
            # Shuffle only among valid rows is not expressible with static
            # shapes; instead permute rows+mask together so pairing stays valid.
            y = y[perm]
            y_mask = mask[perm]
            joint = mask * y_mask  # rows valid on both sides
        else:
            y = y[perm]
            joint = None

        def stat(params):
            mmd2, var = self._mmd_and_var(params, x, y, with_var=True, mask=joint)
            return -mmd2 / jnp.sqrt(jnp.maximum(var, 1e-12))

        grads = jax.grad(stat)(state.params)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        return DeepMmdState(
            params=optax.apply_updates(state.params, updates), opt_state=new_opt
        )

    def train(self, state: DeepMmdState, x: jax.Array, y: jax.Array,
              rng: jax.Array, mask: jax.Array | None = None) -> DeepMmdState:
        """``optimization_steps`` kernel updates (forward, deep_mmd_loss.py:310)."""

        def body(s, k):
            return self.train_step(s, x, y, k, mask), None

        keys = jax.random.split(rng, self.optimization_steps)
        state, _ = jax.lax.scan(body, state, keys)
        return state

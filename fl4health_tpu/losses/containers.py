"""Loss containers and meters.

Parity: /root/reference/fl4health/utils/losses.py:10-234 — TrainingLosses /
EvaluationLosses (backward loss + named additional losses) and LossMeter with
AVERAGE / ACCUMULATION modes.

TPU shape: containers are struct dataclasses (scan-carry friendly); the meter
is a running (sum, count) pytree updated inside jit.
"""

from __future__ import annotations

import enum
from typing import Mapping

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainingLosses:
    backward: jax.Array  # the loss that was differentiated
    additional: Mapping[str, jax.Array] = struct.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"backward": self.backward, **dict(self.additional)}


@struct.dataclass
class EvaluationLosses:
    checkpoint: jax.Array  # the loss used for checkpoint selection
    additional: Mapping[str, jax.Array] = struct.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"checkpoint": self.checkpoint, **dict(self.additional)}


class LossMeterType(enum.Enum):
    AVERAGE = "AVERAGE"
    ACCUMULATION = "ACCUMULATION"


@struct.dataclass
class LossMeter:
    """Running reduction of loss dicts (utils/losses.py LossMeter).

    State: {key: sum} + count; AVERAGE divides at compute, ACCUMULATION
    doesn't. A ``weight`` lets callers mask padded steps.
    """

    sums: Mapping[str, jax.Array]
    count: jax.Array
    meter_type: str = struct.field(pytree_node=False, default="AVERAGE")

    @classmethod
    def create(cls, keys: tuple[str, ...], meter_type: str = "AVERAGE") -> "LossMeter":
        return cls(
            sums={k: jnp.zeros((), jnp.float32) for k in keys},
            count=jnp.zeros((), jnp.float32),
            meter_type=meter_type,
        )

    def update(self, losses: Mapping[str, jax.Array], weight=1.0) -> "LossMeter":
        w = jnp.asarray(weight, jnp.float32)
        new_sums = {
            k: self.sums[k] + w * jnp.asarray(losses[k], jnp.float32)
            for k in self.sums
        }
        return self.replace(sums=new_sums, count=self.count + w)

    def compute(self) -> dict:
        if self.meter_type == "ACCUMULATION":
            return dict(self.sums)
        c = jnp.maximum(self.count, 1.0)
        return {k: v / c for k, v in self.sums.items()}

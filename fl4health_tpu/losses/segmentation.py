"""Segmentation losses — masked soft Dice + CE with deep supervision.

Parity surface (/root/reference/fl4health/clients/nnunet_client.py:326
``get_criterion`` -> nnunetv2 DC_and_CE / DC_and_BCE losses; :659
``compute_loss_and_additional_losses`` applying per-scale deep-supervision
weights; :703 ``mask_data`` implementing the ignore-label contract).

TPU-native design: everything is mask arithmetic on static shapes. The
ignore label becomes a per-voxel weight (no boolean indexing — XLA needs
static shapes); deep-supervision targets are produced by strided slicing
(exact nearest-neighbour when strides are the pooling factors, so no
jax.image resampling pass); dice is the memory-efficient batch formulation
(one running numerator/denominator per class, background excluded).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax


def _voxel_weights(target: jax.Array, example_mask: jax.Array,
                   ignore_label: int | None) -> jax.Array:
    """[B, *S] weights: 0 on padded examples and ignore-labelled voxels."""
    w = jnp.broadcast_to(
        example_mask.reshape((-1,) + (1,) * (target.ndim - 1)),
        target.shape,
    ).astype(jnp.float32)
    if ignore_label is not None:
        w = w * (target != ignore_label).astype(jnp.float32)
    return w


def masked_soft_dice_loss(
    logits: jax.Array,
    target: jax.Array,
    weights: jax.Array,
    include_background: bool = False,
    smooth: float = 1e-5,
) -> jax.Array:
    """Batch soft Dice loss: 1 - mean-over-classes of the dataset-batch dice.

    logits [B, *S, C]; target [B, *S] int; weights [B, *S] in {0,1}. The
    batch (not per-image) formulation matches nnU-Net's ``batch_dice=True``
    regional default; background (class 0) excluded unless asked for.
    """
    n_classes = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(
        jnp.clip(target, 0, n_classes - 1), n_classes, dtype=probs.dtype
    )
    w = weights[..., None]
    axes = tuple(range(probs.ndim - 1))  # sum over batch + spatial
    inter = jnp.sum(probs * onehot * w, axis=axes)
    denom = jnp.sum(probs * w, axis=axes) + jnp.sum(onehot * w, axis=axes)
    dice = (2.0 * inter + smooth) / (denom + smooth)
    if not include_background and n_classes > 1:
        dice = dice[1:]
    return 1.0 - jnp.mean(dice)


def masked_voxel_cross_entropy(
    logits: jax.Array, target: jax.Array, weights: jax.Array
) -> jax.Array:
    """Mean CE over valid voxels."""
    n_classes = logits.shape[-1]
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.clip(target, 0, n_classes - 1)
    )
    return jnp.sum(per * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def masked_dice_ce_loss(
    logits: jax.Array,
    target: jax.Array,
    example_mask: jax.Array,
    ignore_label: int | None = None,
    dice_weight: float = 1.0,
    ce_weight: float = 1.0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """-> (total, dice_term, ce_term). The DC_and_CE combination with the
    reference's ignore-label masking (nnunet_client.py:703-730) folded into
    voxel weights."""
    w = _voxel_weights(target, example_mask, ignore_label)
    dice = masked_soft_dice_loss(logits, target, w)
    ce = masked_voxel_cross_entropy(logits, target, w)
    return dice_weight * dice + ce_weight * ce, dice, ce


def downsample_target(target: jax.Array, factor: Sequence[int]) -> jax.Array:
    """Nearest-neighbour pool of an integer map by strided slicing. Exact for
    pooling factors that divide the extent (the planner guarantees this)."""
    slices = (slice(None),) + tuple(slice(None, None, int(f)) for f in factor)
    return target[slices]


def deep_supervision_weights(n_outputs: int) -> list[float]:
    """Per-scale loss weights 1, 1/2, 1/4, ... with the LOWEST resolution
    zeroed (when there is more than one output) and the rest normalized to
    sum to 1 — the nnU-Net deep-supervision convention the reference
    delegates to nnunetv2."""
    w = [1.0 / (2.0**i) for i in range(n_outputs)]
    if n_outputs > 1:
        w[-1] = 0.0
    total = sum(w)
    return [x / total for x in w]


def deep_supervision_loss(
    preds: dict[str, jax.Array],
    target: jax.Array,
    example_mask: jax.Array,
    ds_strides: Sequence[Sequence[int]],
    ignore_label: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Weighted multi-scale Dice+CE over {"prediction", "ds_1", ...}.

    ``ds_strides[i-1]`` is the cumulative downsampling factor of ``ds_i``
    (models/unet.py deep_supervision_strides). Returns (total, dice, ce)
    where dice/ce are the full-resolution terms (the ones worth reporting).
    """
    n_outputs = 1 + len(ds_strides)
    weights = deep_supervision_weights(n_outputs)
    total, full_dice, full_ce = masked_dice_ce_loss(
        preds["prediction"], target, example_mask, ignore_label
    )
    loss = weights[0] * total
    for i, factor in enumerate(ds_strides, start=1):
        if weights[i] == 0.0:
            continue
        t = downsample_target(target, factor)
        term, _, _ = masked_dice_ce_loss(
            preds[f"ds_{i}"], t, example_mask, ignore_label
        )
        loss = loss + weights[i] * term
    return loss, full_dice, full_ce

"""Contrastive losses: MOON and NT-Xent.

Parity: /root/reference/fl4health/losses/contrastive_loss.py:6 (MoonContrastiveLoss)
and :95 (NtXentLoss), and cosine_similarity_loss.py:5.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cosine_similarity(a: jax.Array, b: jax.Array, axis=-1, eps=1e-8) -> jax.Array:
    """Cosine similarity along ``axis`` (shared by every contrastive loss)."""
    a_n = a / jnp.maximum(jnp.linalg.norm(a, axis=axis, keepdims=True), eps)
    b_n = b / jnp.maximum(jnp.linalg.norm(b, axis=axis, keepdims=True), eps)
    return jnp.sum(a_n * b_n, axis=axis)


_cos = cosine_similarity


def moon_contrastive_loss(
    features: jax.Array,
    positive_pairs: jax.Array,
    negative_pairs: jax.Array,
    temperature: float = 0.5,
    mask: jax.Array | None = None,
    negative_mask: jax.Array | None = None,
) -> jax.Array:
    """MOON model-contrastive loss (contrastive_loss.py:6).

    features:       [B, D]   current local-model features z
    positive_pairs: [P, B, D] features from the global model (usually P=1)
    negative_pairs: [N, B, D] features from previous local models
    negative_mask:  [N] optional 0/1 validity per negative row (e.g. MOON's
                    not-yet-populated old-model buffer slots)
    loss = -log( sum_p exp(cos(z, z_p)/t) /
                 (sum_p exp(cos(z,z_p)/t) + sum_n exp(cos(z,z_n)/t)) )
    """
    pos = _cos(features[None], positive_pairs) / temperature  # [P, B]
    neg = _cos(features[None], negative_pairs) / temperature  # [N, B]
    if negative_mask is not None:
        neg = jnp.where(negative_mask[:, None] > 0, neg, -1e9)
    logits = jnp.concatenate([pos, neg], axis=0).T  # [B, P+N]
    n_pos = positive_pairs.shape[0]
    log_prob = jax.nn.log_softmax(logits, axis=-1)
    per_example = -jax.scipy.special.logsumexp(
        log_prob[:, :n_pos], axis=-1
    ) if n_pos > 1 else -log_prob[:, 0]
    if mask is not None:
        m = mask.astype(jnp.float32)
        return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per_example)


def ntxent_loss(
    features: jax.Array,
    transformed_features: jax.Array,
    temperature: float = 0.5,
    mask: jax.Array | None = None,
) -> jax.Array:
    """NT-Xent (SimCLR) loss (contrastive_loss.py:95).

    features / transformed_features: [B, D] paired views; for each anchor the
    positive is its pair, negatives are all other samples in the 2B batch.
    """
    b = features.shape[0]
    z = jnp.concatenate([features, transformed_features], axis=0)  # [2B, D]
    sim = _cos(z[:, None, :], z[None, :, :]) / temperature  # [2B, 2B]
    valid = jnp.ones((b,), jnp.float32) if mask is None else mask.astype(jnp.float32)
    valid2 = jnp.concatenate([valid, valid])
    # exclude self-similarity and padded columns
    neg_inf = jnp.finfo(sim.dtype).min
    diag = jnp.eye(2 * b, dtype=bool)
    sim = jnp.where(diag | (valid2[None, :] < 0.5), neg_inf, sim)
    pos_idx = jnp.concatenate([jnp.arange(b) + b, jnp.arange(b)])
    log_prob = jax.nn.log_softmax(sim, axis=-1)
    per_anchor = -log_prob[jnp.arange(2 * b), pos_idx]
    return jnp.sum(per_anchor * valid2) / jnp.maximum(jnp.sum(valid2), 1.0)


def cosine_similarity_loss(
    features: jax.Array, reference_features: jax.Array, mask: jax.Array | None = None
) -> jax.Array:
    """Mean |cos| similarity to a reference feature bank
    (cosine_similarity_loss.py:5) — minimized to push features apart."""
    per = jnp.abs(_cos(features, reference_features))
    if mask is not None:
        m = mask.astype(jnp.float32)
        return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per)


def perfcl_loss(
    local_features: jax.Array,
    old_local_features: jax.Array,
    global_features: jax.Array,
    old_global_features: jax.Array,
    initial_global_features: jax.Array,
    temperature: float = 0.5,
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """PerFCL dual contrastive losses (perfcl_loss.py:7).

    Returns (global_contrastive, local_contrastive):
    - global: pull current global-extractor features toward the frozen initial
      (aggregated) global features, away from previous-round global features.
    - local: pull current local features toward previous local features, away
      from current global features.
    """
    g = moon_contrastive_loss(
        global_features,
        initial_global_features[None],
        old_global_features[None],
        temperature,
        mask,
    )
    l = moon_contrastive_loss(
        local_features,
        old_local_features[None],
        # Negative pair is the frozen AGGREGATED global features z_g, not the
        # live ones (perfcl_loss.py:85-89).
        initial_global_features[None],
        temperature,
        mask,
    )
    return g, l

"""FedOpt family — server-side adaptive optimizers (FedAdam/FedYogi/FedAdaGrad).

The reference uses Flower's FedOpt strategies directly (README "FedOpt" row;
examples/fedopt_example). Semantics (Reddi et al. 2021): treat the weighted
client average as a pseudo-gradient Delta_t = avg(x_i) - x and apply a server
optimizer. Here the server optimizer is any optax transformation, compiled
into the round program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct

from fl4health_tpu.core import aggregate as agg, pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FedOptState:
    params: Params
    opt_state: Any


class FedOpt(Strategy):
    """Server-optimizer strategy over the pseudo-gradient."""

    def __init__(self, tx: optax.GradientTransformation, weighted_aggregation: bool = True):
        self.tx = tx
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> FedOptState:
        return FedOptState(params=params, opt_state=self.tx.init(params))

    def state_sharding_spec(self, server_state: FedOptState, clients_axis: str):
        """With a ZeRO-1/2 sharded server optimizer (``parallel/zero.py``,
        wired by ``MeshConfig(zero1=True)``) the optimizer's flat-vector
        state leaves are partitioned over the replica (clients) axis —
        cross-replica sharding of the weight update (Xu et al.): each
        replica owns 1/N of the momenta and the update all-gathers once.
        Params (and scalar counts) replicate. Without a sharded optimizer
        the whole state replicates (None)."""
        from fl4health_tpu.parallel.zero import (
            Zero2ShardedOptimizer,
            ZeroShardedOptimizer,
        )

        if not isinstance(self.tx, (ZeroShardedOptimizer, Zero2ShardedOptimizer)):
            return None
        from jax.sharding import PartitionSpec as P

        opt_spec = jax.tree_util.tree_map(
            lambda leaf: (P(self.tx.axis_name)
                          if getattr(leaf, "ndim", 0) >= 1 else P()),
            server_state.opt_state,
        )
        return FedOptState(
            params=P(), opt_state=opt_spec
        )

    def aggregate(self, server_state: FedOptState, results: FitResults, round_idx) -> FedOptState:
        avg = agg.aggregate(
            results.packets, results.sample_counts, results.mask,
            self.weighted_aggregation,
        )
        # pseudo-gradient: descent direction is x - avg
        pseudo_grad = ptu.tree_sub(server_state.params, avg)
        updates, new_opt = self.tx.update(
            pseudo_grad, server_state.opt_state, server_state.params
        )
        new_params = optax.apply_updates(server_state.params, updates)
        any_client = jnp.sum(results.mask) > 0
        new_params, new_opt = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o),
            (new_params, new_opt),
            (server_state.params, server_state.opt_state),
        )
        return FedOptState(params=new_params, opt_state=new_opt)


# The factories below build their server optimizer through
# ``optax.inject_hyperparams``, so the SERVER LEARNING RATE lives as a
# traced leaf of ``FedOptState.opt_state`` (``opt_state.hyperparams
# ["learning_rate"]``) instead of a Python constant baked into the jaxpr.
# Two configs differing only in server lr therefore share one compiled
# round program — the sweep engine (fl4health_tpu/sweep/) rebinds it per
# cell with zero recompiles (pinned by tests/sweep/test_hoisting.py).
# Everything else (betas, eps, momentum) stays STATIC on purpose: optax
# folds expressions like ``1 - b1`` in Python double precision when the
# scalar is a constant but in f32 when it is traced, so injecting them
# would shift trajectories by ~1ulp — whereas the lr enters as a single
# f32 multiply whose bits match the constant-folded build exactly
# (bit-identity pinned by tests).
#
# COMPAT NOTE: the opt_state pytree structure changed (a plain optax
# chain tuple -> InjectHyperparamsState). Server-state checkpoints saved
# by a pre-hoisting build do not restore into the new template; re-save
# from a fresh run (checkpoints here are per-run artifacts, not a stable
# wire format).

def fed_adam(lr: float = 0.1, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3,
             weighted_aggregation: bool = True) -> FedOpt:
    """FedAdam (Reddi et al. defaults: tau=1e-3)."""
    return FedOpt(
        optax.inject_hyperparams(
            optax.adam, static_args=("b1", "b2", "eps", "eps_root")
        )(learning_rate=lr, b1=b1, b2=b2, eps=eps),
        weighted_aggregation,
    )


def fed_yogi(lr: float = 0.1, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3,
             weighted_aggregation: bool = True) -> FedOpt:
    return FedOpt(
        optax.inject_hyperparams(
            optax.yogi, static_args=("b1", "b2", "eps")
        )(learning_rate=lr, b1=b1, b2=b2, eps=eps),
        weighted_aggregation,
    )


def fed_adagrad(lr: float = 0.1, eps: float = 1e-3,
                weighted_aggregation: bool = True) -> FedOpt:
    return FedOpt(
        optax.inject_hyperparams(
            optax.adagrad, static_args=("eps", "initial_accumulator_value")
        )(learning_rate=lr, eps=eps),
        weighted_aggregation,
    )


def fed_avg_m(lr: float = 1.0, momentum: float = 0.9,
              weighted_aggregation: bool = True) -> FedOpt:
    """Server momentum (FedAvgM)."""
    return FedOpt(
        optax.inject_hyperparams(
            optax.sgd, static_args=("momentum", "nesterov")
        )(learning_rate=lr, momentum=momentum),
        weighted_aggregation,
    )

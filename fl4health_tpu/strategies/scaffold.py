"""SCAFFOLD — stochastic controlled averaging.

Parity: /root/reference/fl4health/strategies/scaffold.py:28 (server side;
client in fl4health_tpu.clients.scaffold). Packed payload = weights plus
control variates (ParameterPackerWithControlVariates). Server updates
(scaffold.py:303,325):
    x  <- x + server_lr * (mean_i(y_i) - x)          [unweighted]
    c  <- c + (|S| / N) * mean_i(delta_c_i)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import aggregate as agg, pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import ControlVariatesPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class ScaffoldState:
    params: Params
    control_variates: Params


class Scaffold(Strategy):
    """Server half of SCAFFOLD. Aggregation is UNWEIGHTED by algorithm design
    (strategies/scaffold.py docstring + aggregate :245)."""

    weighted_aggregation = False

    def __init__(self, learning_rate: float = 1.0):
        self.server_lr = learning_rate

    def init(self, params: Params) -> ScaffoldState:
        return ScaffoldState(
            params=params, control_variates=ptu.tree_zeros_like(params)
        )

    def client_payload(self, server_state: ScaffoldState, round_idx):
        return ControlVariatesPacket(
            params=server_state.params,
            control_variates=server_state.control_variates,
        )

    def aggregate(self, server_state: ScaffoldState, results: FitResults, round_idx):
        packets: ControlVariatesPacket = results.packets
        y_bar = agg.aggregate(
            packets.params, results.sample_counts, results.mask, weighted=False
        )
        delta_c_bar = agg.aggregate(
            packets.control_variates, results.sample_counts, results.mask,
            weighted=False,
        )
        n_sampled = jnp.sum(results.mask)
        n_total = jnp.asarray(results.mask.shape[0], jnp.float32)
        any_client = n_sampled > 0
        # x += lr * (y_bar - x)
        new_params = ptu.tree_axpy(
            self.server_lr, ptu.tree_sub(y_bar, server_state.params),
            server_state.params,
        )
        # c += (|S|/N) * delta_c_bar
        new_c = ptu.tree_axpy(
            n_sampled / n_total, delta_c_bar, server_state.control_variates
        )
        new_params, new_c = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o),
            (new_params, new_c),
            (server_state.params, server_state.control_variates),
        )
        return ScaffoldState(params=new_params, control_variates=new_c)

"""FedBuff — staleness-discounted buffered-async aggregation as a wrapper.

Reference point: Nguyen et al., "Federated Learning with Buffered
Asynchronous Aggregation" (FedBuff, arXiv:2106.06639) — the server
aggregates a buffer of K client updates as they arrive, each discounted by
a function of its staleness (server versions elapsed since that client
pulled). In this repo the asynchrony itself is resolved to a static event
plan (``server/async_schedule.py``), so the strategy layer's job reduces
to one pure function: turn an event's ``(arrivals, staleness)`` row into
the aggregation mask the inner strategy consumes.

That folding is exact for every strategy in the repo because aggregation
weights already flow through ``FitResults.mask`` as FLOATS: the core
``effective_weights`` computes ``w_i = n_i * mask_i / sum`` — a fractional
mask entry IS a per-client weight multiplier. So ``FedBuff(inner)`` keeps
the inner strategy's state and math untouched (its state IS the inner
state, like ``RobustFedAvg``) and composes with ``RobustFedAvg``,
``QuarantiningStrategy``, ``CompressingStrategy``, FedOpt-family server
optimizers, SCAFFOLD — anything whose ``aggregate`` honors the mask.

With every arrival at staleness 0 the discount is exactly 1.0 and the mask
is bit-identical to the synchronous one — the simulation's
``async == sync`` pin (K = cohort, no stragglers) holds through this
wrapper by construction.

The same mask-folding carries FedBuff over the client REGISTRY
(``async_config + CohortConfig``): there the ``C`` axis is cohort slots
seated from a ``RegistryEventPlan``, per-slot sample counts become a
traced event input (the seated occupant's count rides the pending
buffer), and occupancy swaps happen host-side between events — all
outside the strategy, so this wrapper needs no registry awareness. Its
state-passthrough design is what lets per-client inner rows (EF
residuals, quarantine strikes) gather/scatter through the registry's
``Strategy.state_rows`` hooks unchanged.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fl4health_tpu.server.async_schedule import staleness_discount
from fl4health_tpu.strategies.base import (
    FitResults,
    Strategy,
    inner_state_sharding_spec,
)


class FedBuff(Strategy):
    """Wrap any strategy with staleness-discounted async aggregation.

    ``async_aggregation_mask(arrivals, staleness)`` is the one async-only
    hook — the simulation's async round programs call it to build the
    event's mask; everything else delegates, so a FedBuff-wrapped strategy
    run synchronously (``async_config=None``) is bit-identical to the bare
    inner strategy.

    staleness_exponent: discount ``1/(1+s)^exponent`` (0.5 = FedBuff's
        ``1/sqrt(1+s)``).
    max_staleness: updates staler than this get weight 0 (dropped from
        the aggregate; their client still restarts). None = no cap.
    """

    def __init__(
        self,
        inner: Strategy,
        staleness_exponent: float = 0.5,
        max_staleness: int | None = None,
    ):
        if staleness_exponent < 0:
            raise ValueError("staleness_exponent must be >= 0")
        if max_staleness is not None and max_staleness < 0:
            raise ValueError("max_staleness must be >= 0 (or None)")
        self.inner = inner
        self.staleness_exponent = float(staleness_exponent)
        self.max_staleness = max_staleness
        self.weighted_aggregation = inner.weighted_aggregation
        self.weighted_eval_aggregation = inner.weighted_eval_aggregation
        # chunk-eligibility passthrough (server/simulation.py consults this
        # before the type-level check) — same contract as the other
        # wrapper strategies
        inner_overrides = getattr(inner, "overrides_update_after_eval", None)
        if inner_overrides is None:
            inner_overrides = (type(inner).update_after_eval
                               is not Strategy.update_after_eval)
        self.overrides_update_after_eval = inner_overrides
        inner_qmask = getattr(inner, "quarantine_mask", None)
        if inner_qmask is not None:
            # state passthrough: FedBuff's state IS the inner state
            self.quarantine_mask = inner_qmask

    # -- the async hook -------------------------------------------------
    def async_aggregation_mask(self, arrivals: jax.Array,
                               staleness: jax.Array,
                               exponent=None) -> jax.Array:
        """[C] fractional aggregation mask for one buffer-fill event:
        ``arrivals * 1/(1+staleness)^exponent`` (0 past ``max_staleness``).
        Jit-traceable; a staleness-0 arrival row returns ``arrivals``
        bit-identically (the discount is exactly 1.0).

        ``exponent`` (default: this wrapper's configured
        ``staleness_exponent``) may be a traced f32 scalar — the async
        round programs pass the CURRENT ``strategy.staleness_exponent`` as
        a program input each dispatch, so rebinding the attribute (the
        sweep engine's scalar hoisting) changes the discount with zero
        recompiles. ``max_staleness`` stays static by design: it is a
        hard drop rule, part of the experiment's identity."""
        disc = staleness_discount(
            jnp.asarray(staleness, jnp.float32),
            self.staleness_exponent if exponent is None else exponent,
            self.max_staleness,
        )
        return jnp.asarray(arrivals, jnp.float32) * disc.astype(jnp.float32)

    # -- pure delegation (state passthrough) ----------------------------
    @property
    def evaluate_after_fit(self) -> bool:
        return bool(getattr(self.inner, "evaluate_after_fit", False))

    def bind_client_manager(self, client_manager: Any) -> None:
        bind = getattr(self.inner, "bind_client_manager", None)
        if bind is not None:
            bind(client_manager)

    def init(self, params) -> Any:
        return self.inner.init(params)

    def state_sharding_spec(self, server_state: Any, clients_axis: str):
        return inner_state_sharding_spec(
            self.inner, server_state, clients_axis
        )

    def global_params(self, server_state: Any):
        return self.inner.global_params(server_state)

    def state_rows(self, server_state: Any):
        # state passthrough: FedBuff's state IS the inner state, so its
        # per-client rows are exactly the inner strategy's rows
        return self.inner.state_rows(server_state)

    def scatter_state_rows(self, server_state: Any, rows):
        return self.inner.scatter_state_rows(server_state, rows)

    def divergence_reference(self, server_state: Any):
        return self.inner.divergence_reference(server_state)

    def client_payload(self, server_state: Any, round_idx):
        return self.inner.client_payload(server_state, round_idx)

    def aggregate(self, server_state: Any, results: FitResults, round_idx):
        # the event's staleness discount is already folded into
        # results.mask by the async round program (or absent entirely on a
        # synchronous run) — the inner strategy sees plain weighted masks
        return self.inner.aggregate(server_state, results, round_idx)

    def update_after_eval(self, server_state, eval_losses, eval_metrics,
                          mask):
        return self.inner.update_after_eval(
            server_state, eval_losses, eval_metrics, mask
        )

"""FLASH — server-side adaptive optimization with drift-aware third moment.

Parity: /root/reference/fl4health/strategies/flash.py:21
(_update_parameters :125-142, aggregate_fit :143-171):
    Delta_t = x_bar - x
    m_t = b1*m + (1-b1)*Delta
    v_t = b2*v + (1-b2)*Delta^2
    b3  = |v_{t-1}| / (|Delta^2 - v_t| + |v_{t-1}|)        (elementwise)
    d_t = b3*d_{t-1} + (1-b3)*(Delta^2 - v_t)
    x  += eta * m_t / (sqrt(v_t) - d_t + tau)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import aggregate as agg, pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FlashState:
    params: Params
    m: Params
    v: Params
    d: Params


class Flash(Strategy):
    def __init__(
        self,
        eta: float = 0.1,
        beta_1: float = 0.9,
        beta_2: float = 0.99,
        tau: float = 1e-3,
        weighted_aggregation: bool = True,
    ):
        self.eta = eta
        self.b1 = beta_1
        self.b2 = beta_2
        self.tau = tau
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> FlashState:
        z = ptu.tree_zeros_like(params)
        return FlashState(params=params, m=z, v=z, d=z)

    def aggregate(self, server_state: FlashState, results: FitResults, round_idx):
        x_bar = agg.aggregate(
            results.packets, results.sample_counts, results.mask,
            self.weighted_aggregation,
        )

        def upd(x, xb, m, v, d):
            delta = xb - x
            m_t = self.b1 * m + (1 - self.b1) * delta
            v_t = self.b2 * v + (1 - self.b2) * jnp.square(delta)
            gap = jnp.square(delta) - v_t
            b3 = jnp.abs(v) / (jnp.abs(gap) + jnp.abs(v) + 1e-12)
            d_t = b3 * d + (1 - b3) * gap
            x_t = x + self.eta * m_t / (jnp.sqrt(v_t) - d_t + self.tau)
            return x_t, m_t, v_t, d_t

        out = jax.tree_util.tree_map(
            upd, server_state.params, x_bar, server_state.m, server_state.v,
            server_state.d,
        )
        # out leaves are 4-tuples; transpose to four trees
        treedef = jax.tree_util.tree_structure(server_state.params)
        flat = jax.tree_util.tree_leaves(out, is_leaf=lambda t: isinstance(t, tuple))
        x_t = jax.tree_util.tree_unflatten(treedef, [t[0] for t in flat])
        m_t = jax.tree_util.tree_unflatten(treedef, [t[1] for t in flat])
        v_t = jax.tree_util.tree_unflatten(treedef, [t[2] for t in flat])
        d_t = jax.tree_util.tree_unflatten(treedef, [t[3] for t in flat])
        any_client = jnp.sum(results.mask) > 0
        x_t = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), x_t, server_state.params
        )
        return FlashState(params=x_t, m=m_t, v=v_t, d=d_t)

"""FedPM — Bayesian aggregation of binary parameter masks.

Parity: /root/reference/fl4health/strategies/fedpm.py:12 (aggregate_bayesian)
+ FedPmServer's periodic Beta-posterior reset (servers/fedpm_server.py:14).

Clients train Bernoulli probability scores over frozen weights and sample
binary masks for exchange (clients/fedpm_client.py:18; model side in
fl4health_tpu.models.masked). The server keeps Beta(alpha, beta) posteriors
per parameter:
    alpha += sum_i m_i ;  beta += sum_i (1 - m_i)
    theta  = (alpha - 1) / (alpha + beta - 2)
and broadcasts theta as the new global probability scores.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FedPmState:
    params: Params  # probability scores (theta) pytree
    alpha: Params
    beta: Params
    rounds_since_reset: jax.Array


class FedPm(Strategy):
    def __init__(self, reset_frequency: int | None = None):
        """reset_frequency: reset Beta posteriors to uniform every k rounds
        (FedPmServer reset logic); None = never."""
        self.reset_frequency = reset_frequency

    def init(self, params: Params) -> FedPmState:
        ones = jax.tree_util.tree_map(lambda x: jnp.ones_like(x, jnp.float32), params)
        return FedPmState(
            params=params,
            alpha=ones,
            beta=ones,
            rounds_since_reset=jnp.zeros((), jnp.int32),
        )

    def aggregate(self, server_state: FedPmState, results: FitResults, round_idx):
        masks = results.packets  # stacked binary masks, same tree as params
        m = results.mask

        def acc(a, stacked):
            mm = m.reshape((-1,) + (1,) * (stacked.ndim - 1))
            return a + jnp.sum(stacked.astype(jnp.float32) * mm, axis=0)

        def acc_inv(b, stacked):
            mm = m.reshape((-1,) + (1,) * (stacked.ndim - 1))
            return b + jnp.sum((1.0 - stacked.astype(jnp.float32)) * mm, axis=0)

        alpha = jax.tree_util.tree_map(acc, server_state.alpha, masks)
        beta = jax.tree_util.tree_map(acc_inv, server_state.beta, masks)
        theta = jax.tree_util.tree_map(
            lambda a, b: jnp.clip((a - 1.0) / jnp.maximum(a + b - 2.0, 1e-12), 0.0, 1.0),
            alpha, beta,
        )
        rounds = server_state.rounds_since_reset + 1
        if self.reset_frequency is not None:
            do_reset = rounds >= self.reset_frequency
            alpha = jax.tree_util.tree_map(
                lambda a: jnp.where(do_reset, jnp.ones_like(a), a), alpha
            )
            beta = jax.tree_util.tree_map(
                lambda b: jnp.where(do_reset, jnp.ones_like(b), b), beta
            )
            rounds = jnp.where(do_reset, 0, rounds)
        return FedPmState(
            params=theta, alpha=alpha, beta=beta, rounds_since_reset=rounds
        )

"""FedDG-GA — generalization-adjustment aggregation weights.

Parity: /root/reference/fl4health/strategies/feddg_ga.py:98 (+ the adaptive-
constraint combination, feddg_ga_with_adaptive_constraint.py:15).

Semantics (verified against weight_and_aggregate_results :333 and
update_weights_by_ga :382-451):
- aggregation: params = sum_i w_i * params_i with per-client adjustment
  weights w_i (initialized 1/N, kept normalized to sum 1);
- after the post-aggregation evaluation round, per-client generalization gap
  g_i = eval_metric(global model on client i) - fit_metric(local model on
  client i, post local fit). With the LOSS fairness metric the "fit" value is
  the client's val loss evaluated right after local training
  (evaluate_after_fit=True);
- centered gaps d_i = g_i - mean(g); if max|d| == 0 weights are unchanged;
  else w_i += signal * step_size(round) * d_i / max|d|, clipped to [0, 1] and
  renormalized to sum 1;
- step_size(round) decays linearly: s - (round-1) * s / num_rounds (:453-477);
- requires full participation + fixed sampling (:205-210).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core.aggregate import weighted_mean
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FedDgGaState:
    params: Params
    adjustment_weights: jax.Array  # [n_clients], sums to 1
    local_val_losses: jax.Array  # [n_clients] post-fit pre-agg val losses
    round_idx: jax.Array


class FedDgGa(Strategy):
    evaluate_after_fit = True

    def __init__(
        self,
        n_clients: int,
        num_rounds: int,
        adjustment_weight_step_size: float = 0.2,
        signal: float = 1.0,  # +1 for loss metrics, -1 for accuracy-like
    ):
        self.n_clients = n_clients
        self.num_rounds = num_rounds
        self.step_size = adjustment_weight_step_size
        self.signal = signal

    def init(self, params: Params) -> FedDgGaState:
        return FedDgGaState(
            params=params,
            adjustment_weights=jnp.full((self.n_clients,), 1.0 / self.n_clients),
            local_val_losses=jnp.zeros((self.n_clients,)),
            round_idx=jnp.zeros((), jnp.int32),
        )

    def aggregate(self, server_state: FedDgGaState, results: FitResults, round_idx):
        new_params = weighted_mean(results.packets, server_state.adjustment_weights)
        return server_state.replace(
            params=new_params,
            local_val_losses=results.train_losses["val_checkpoint_post_fit"],
            round_idx=round_idx,
        )

    def update_after_eval(self, server_state: FedDgGaState, eval_losses, eval_metrics, mask):
        gaps = eval_losses["checkpoint"] - server_state.local_val_losses
        centered = gaps - jnp.mean(gaps)
        max_dev = jnp.max(jnp.abs(centered))
        step = self.step_size - (
            (server_state.round_idx.astype(jnp.float32) - 1.0)
            * self.step_size / self.num_rounds
        )
        delta = jnp.where(
            max_dev > 0, self.signal * step * centered / jnp.maximum(max_dev, 1e-12), 0.0
        )
        w = jnp.clip(server_state.adjustment_weights + delta, 0.0, 1.0)
        w = w / jnp.maximum(jnp.sum(w), 1e-12)
        return server_state.replace(adjustment_weights=w)

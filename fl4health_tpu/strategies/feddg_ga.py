"""FedDG-GA — generalization-adjustment aggregation weights.

Parity: /root/reference/fl4health/strategies/feddg_ga.py:98; the adaptive-
constraint combination (feddg_ga_with_adaptive_constraint.py:15) is
``FedDgGaAdaptiveConstraint`` below.

Semantics (verified against weight_and_aggregate_results :333 and
update_weights_by_ga :382-451):
- aggregation: params = sum_i w_i * params_i with per-client adjustment
  weights w_i (initialized 1/N, kept normalized to sum 1);
- after the post-aggregation evaluation round, per-client generalization gap
  g_i = eval_metric(global model on client i) - fit_metric(local model on
  client i, post local fit). With the LOSS fairness metric the "fit" value is
  the client's val loss evaluated right after local training
  (evaluate_after_fit=True);
- centered gaps d_i = g_i - mean(g); if max|d| == 0 weights are unchanged;
  else w_i += signal * step_size(round) * d_i / max|d|, clipped to [0, 1] and
  renormalized to sum 1;
- step_size(round) decays linearly: s - (round-1) * s / num_rounds (:453-477);
- requires full participation + fixed sampling (:205-210).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core.aggregate import weighted_mean
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FedDgGaState:
    params: Params
    adjustment_weights: jax.Array  # [n_clients], sums to 1
    local_val_losses: jax.Array  # [n_clients] post-fit pre-agg val losses
    round_idx: jax.Array


class FedDgGa(Strategy):
    evaluate_after_fit = True

    def __init__(
        self,
        n_clients: int,
        num_rounds: int,
        adjustment_weight_step_size: float = 0.2,
        signal: float = 1.0,  # +1 for loss metrics, -1 for accuracy-like
    ):
        self.n_clients = n_clients
        self.num_rounds = num_rounds
        self.step_size = adjustment_weight_step_size
        self.signal = signal

    def init(self, params: Params) -> FedDgGaState:
        return FedDgGaState(
            params=params,
            adjustment_weights=jnp.full((self.n_clients,), 1.0 / self.n_clients),
            local_val_losses=jnp.zeros((self.n_clients,)),
            round_idx=jnp.zeros((), jnp.int32),
        )

    def aggregate(self, server_state: FedDgGaState, results: FitResults, round_idx):
        # The reference forces full participation (:205-210), but the NaN
        # failure screen (simulation.py fit_round) can still zero a client's
        # mask row — its poisoned params/val-loss must not enter the average.
        w = server_state.adjustment_weights * results.mask
        w = w / jnp.maximum(jnp.sum(w), 1e-12)
        new_params = weighted_mean(results.packets, w)
        new_val = jnp.where(
            results.mask > 0,
            results.train_losses["val_checkpoint_post_fit"],
            server_state.local_val_losses,
        )
        any_client = jnp.sum(results.mask) > 0
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), new_params, server_state.params
        )
        return server_state.replace(
            params=new_params,
            local_val_losses=new_val,
            round_idx=round_idx,
        )

    def update_after_eval(self, server_state: FedDgGaState, eval_losses, eval_metrics, mask):
        gaps = eval_losses["checkpoint"] - server_state.local_val_losses
        centered = gaps - jnp.mean(gaps)
        max_dev = jnp.max(jnp.abs(centered))
        step = self.step_size - (
            (server_state.round_idx.astype(jnp.float32) - 1.0)
            * self.step_size / self.num_rounds
        )
        delta = jnp.where(
            max_dev > 0, self.signal * step * centered / jnp.maximum(max_dev, 1e-12), 0.0
        )
        w = jnp.clip(server_state.adjustment_weights + delta, 0.0, 1.0)
        w = w / jnp.maximum(jnp.sum(w), 1e-12)
        return server_state.replace(adjustment_weights=w)


@struct.dataclass
class FedDgGaAdaptiveConstraintState:
    params: Params
    adjustment_weights: jax.Array
    local_val_losses: jax.Array
    round_idx: jax.Array
    drift_penalty_weight: jax.Array  # mu
    previous_loss: jax.Array
    loss_drop_streak: jax.Array


class FedDgGaAdaptiveConstraint(Strategy):
    """FedDG-GA aggregation + FedProx-style mu adaptation.

    Parity: /root/reference/fl4health/strategies/
    feddg_ga_with_adaptive_constraint.py:15 — clients run the adaptive-drift
    constraint (packing their vanilla train loss next to the weights,
    clients/fedprox.py), parameters aggregate with the GA adjustment weights,
    and the drift penalty weight adapts from the aggregated train-loss
    trajectory exactly as in FedAvgWithAdaptiveConstraint (:216-231 rules).
    """

    evaluate_after_fit = True

    def __init__(
        self,
        n_clients: int,
        num_rounds: int,
        adjustment_weight_step_size: float = 0.2,
        signal: float = 1.0,
        initial_drift_penalty_weight: float = 0.1,
        adapt_loss_weight: bool = True,
        loss_weight_delta: float = 0.1,
        loss_weight_patience: int = 5,
        weighted_train_losses: bool = True,
    ):
        self.ga = FedDgGa(
            n_clients, num_rounds, adjustment_weight_step_size, signal
        )
        self.mu0 = initial_drift_penalty_weight
        self.adapt = adapt_loss_weight
        self.delta = loss_weight_delta
        self.patience = loss_weight_patience
        self.weighted_train_losses = weighted_train_losses

    def init(self, params: Params) -> FedDgGaAdaptiveConstraintState:
        ga = self.ga.init(params)
        return FedDgGaAdaptiveConstraintState(
            params=ga.params,
            adjustment_weights=ga.adjustment_weights,
            local_val_losses=ga.local_val_losses,
            round_idx=ga.round_idx,
            drift_penalty_weight=jnp.asarray(self.mu0, jnp.float32),
            previous_loss=jnp.asarray(jnp.inf, jnp.float32),
            loss_drop_streak=jnp.zeros((), jnp.int32),
        )

    def client_payload(self, server_state, round_idx):
        from fl4health_tpu.strategies.fedprox import AdaptiveConstraintPayload

        return AdaptiveConstraintPayload(
            params=server_state.params,
            drift_penalty_weight=server_state.drift_penalty_weight,
        )

    def aggregate(self, server_state, results: FitResults, round_idx):
        from fl4health_tpu.core import aggregate as agg
        from fl4health_tpu.strategies.fedprox import adapt_drift_penalty

        packets = results.packets  # AdaptiveConstraintPacket
        w = server_state.adjustment_weights * results.mask
        w = w / jnp.maximum(jnp.sum(w), 1e-12)
        new_params = weighted_mean(packets.params, w)
        train_loss = agg.aggregate_losses(
            packets.loss_for_adaptation, results.sample_counts, results.mask,
            self.weighted_train_losses,
        )
        mu, streak = adapt_drift_penalty(
            server_state.drift_penalty_weight, server_state.loss_drop_streak,
            train_loss, server_state.previous_loss, self.patience, self.delta,
            self.adapt,
        )
        any_client = jnp.sum(results.mask) > 0
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), new_params, server_state.params
        )
        new_val = jnp.where(
            results.mask > 0,
            results.train_losses["val_checkpoint_post_fit"],
            server_state.local_val_losses,
        )
        return server_state.replace(
            params=new_params,
            local_val_losses=new_val,
            round_idx=round_idx,
            drift_penalty_weight=mu,
            previous_loss=jnp.where(any_client, train_loss, server_state.previous_loss),
            loss_drop_streak=streak,
        )

    def update_after_eval(self, server_state, eval_losses, eval_metrics, mask):
        # Same GA rule; FedDgGa.update_after_eval only reads fields the combo
        # state also carries and returns it via .replace, so delegate.
        return self.ga.update_after_eval(server_state, eval_losses, eval_metrics, mask)

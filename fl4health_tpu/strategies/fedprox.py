"""FedAvg with adaptive proximal constraint (FedProx-style mu adaptation).

Parity: /root/reference/fl4health/strategies/fedavg_with_adaptive_constraint.py:16.
Clients pack their train loss next to the weights
(ParameterPackerAdaptiveConstraint); the server tracks the aggregated train
loss trajectory: if it falls ``loss_weight_patience`` rounds in a row,
mu -= loss_weight_delta (floored at 0); on any increase, mu += delta and the
counter resets (:216-231). The adapted mu is broadcast back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import aggregate as agg
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import AdaptiveConstraintPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class AdaptiveConstraintState:
    params: Params
    drift_penalty_weight: jax.Array  # mu
    previous_loss: jax.Array
    loss_drop_streak: jax.Array  # consecutive improvements


@struct.dataclass
class AdaptiveConstraintPayload:
    params: Params
    drift_penalty_weight: jax.Array


def adapt_drift_penalty(
    mu: jax.Array,
    streak: jax.Array,
    train_loss: jax.Array,
    previous_loss: jax.Array,
    patience: int,
    delta: float,
    adapt: bool,
) -> tuple[jax.Array, jax.Array]:
    """The shared mu/streak rules (:216-231): drop mu after ``patience``
    consecutive loss improvements, raise it on any increase. Used by
    FedAvgWithAdaptiveConstraint and FedDgGaAdaptiveConstraint."""
    improved = train_loss <= previous_loss
    streak = jnp.where(improved, streak + 1, 0)
    if adapt:
        hit = streak >= patience
        mu = jnp.where(hit, jnp.maximum(mu - delta, 0.0), mu)
        mu = jnp.where(~improved, mu + delta, mu)
        streak = jnp.where(hit, 0, streak)
    return mu, streak


class FedAvgWithAdaptiveConstraint(Strategy):
    def __init__(
        self,
        initial_drift_penalty_weight: float = 0.1,
        adapt_loss_weight: bool = True,
        loss_weight_delta: float = 0.1,
        loss_weight_patience: int = 5,
        weighted_aggregation: bool = True,
        weighted_train_losses: bool = True,
    ):
        self.mu0 = initial_drift_penalty_weight
        self.adapt = adapt_loss_weight
        self.delta = loss_weight_delta
        self.patience = loss_weight_patience
        self.weighted_aggregation = weighted_aggregation
        self.weighted_train_losses = weighted_train_losses

    def init(self, params: Params) -> AdaptiveConstraintState:
        return AdaptiveConstraintState(
            params=params,
            drift_penalty_weight=jnp.asarray(self.mu0, jnp.float32),
            previous_loss=jnp.asarray(jnp.inf, jnp.float32),
            loss_drop_streak=jnp.zeros((), jnp.int32),
        )

    def client_payload(self, server_state, round_idx):
        return AdaptiveConstraintPayload(
            params=server_state.params,
            drift_penalty_weight=server_state.drift_penalty_weight,
        )

    def aggregate(self, server_state, results: FitResults, round_idx):
        packets: AdaptiveConstraintPacket = results.packets
        new_params = agg.aggregate(
            packets.params, results.sample_counts, results.mask,
            self.weighted_aggregation,
        )
        train_loss = agg.aggregate_losses(
            packets.loss_for_adaptation, results.sample_counts, results.mask,
            self.weighted_train_losses,
        )
        mu, streak = adapt_drift_penalty(
            server_state.drift_penalty_weight, server_state.loss_drop_streak,
            train_loss, server_state.previous_loss, self.patience, self.delta,
            self.adapt,
        )
        any_client = jnp.sum(results.mask) > 0
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), new_params, server_state.params
        )
        return AdaptiveConstraintState(
            params=new_params,
            drift_penalty_weight=mu,
            previous_loss=jnp.where(any_client, train_loss, server_state.previous_loss),
            loss_drop_streak=streak,
        )

"""FedPCA — federated principal-component merging.

Parity: /root/reference/fl4health/strategies/fedpca.py:18 (merging client
subspaces by SVD of stacked, singular-value-scaled principal components) and
clients/fed_pca_client.py:18 (local SVD). Model side: fl4health_tpu.models.pca.

One-shot protocol (no training rounds): each client sends its top-k principal
axes U_i [D, k] and singular values S_i [k]; the server stacks S_i-scaled
axes row-wise and re-runs SVD to get the merged subspace.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class PcaPacket:
    components: jax.Array  # [D, k] column principal axes (U)
    singular_values: jax.Array  # [k]


@struct.dataclass
class FedPcaState:
    components: jax.Array
    singular_values: jax.Array


class FedPCA(Strategy):
    def __init__(self, n_components: int):
        self.n_components = n_components

    def init(self, params) -> FedPcaState:
        # params is a dummy shape carrier: {"components": [D,k], "singular_values": [k]}
        return FedPcaState(
            components=params["components"],
            singular_values=params["singular_values"],
        )

    def global_params(self, server_state: FedPcaState):
        return {
            "components": server_state.components,
            "singular_values": server_state.singular_values,
        }

    def aggregate(self, server_state: FedPcaState, results: FitResults, round_idx):
        pk: PcaPacket = results.packets
        # [clients, D, k] * [clients, 1, k] -> stack scaled axes as rows
        scaled = pk.components * pk.singular_values[:, None, :]
        mask = results.mask.reshape((-1, 1, 1))
        scaled = scaled * mask
        n, d, k = scaled.shape
        stacked = jnp.transpose(scaled, (0, 2, 1)).reshape((n * k, d))  # rows = axes
        # SVD of the stacked subspace matrix; right-singular vectors span the merge
        _, s, vt = jnp.linalg.svd(stacked, full_matrices=False)
        comp = vt[: self.n_components].T  # [D, k]
        sv = s[: self.n_components]
        return FedPcaState(components=comp, singular_values=sv)

"""FedAvg — weighted/unweighted parameter averaging.

Parity: /root/reference/fl4health/strategies/basic_fedavg.py:29 (BasicFedAvg,
aggregate_fit :232, aggregate_evaluate :280) over aggregate_utils.py:8,35.
Deterministic summation order comes for free from the stacked reduction
(replacing decode_and_pseudo_sort_results, utils/functions.py:84).
"""

from __future__ import annotations

import jax
from flax import struct

from fl4health_tpu.core import aggregate as agg
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class FedAvgState:
    params: Params


class FedAvg(Strategy):
    def __init__(self, weighted_aggregation: bool = True):
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> FedAvgState:
        return FedAvgState(params=params)

    def aggregate(self, server_state: FedAvgState, results: FitResults, round_idx) -> FedAvgState:
        new_params = agg.aggregate(
            results.packets,
            results.sample_counts,
            mask=results.mask,
            weighted=self.weighted_aggregation,
        )
        # An empty cohort (all-zero mask) keeps the previous params.
        import jax.numpy as jnp

        any_client = jnp.sum(results.mask) > 0
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), new_params, server_state.params
        )
        return server_state.replace(params=new_params)

"""Model merging — one-shot parameter averaging with evaluation.

Parity: /root/reference/fl4health/strategies/model_merge_strategy.py:26 +
servers/model_merge_server.py:23 + clients/model_merge_client.py:23: clients
send locally-trained weights once; the server merges (uniform or weighted) and
runs a federated evaluation. No training rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.core import aggregate as agg
from fl4health_tpu.core.types import Params
from fl4health_tpu.strategies.base import FitResults, Strategy
from fl4health_tpu.strategies.fedavg import FedAvgState


class ModelMergeStrategy(Strategy):
    def __init__(self, weighted: bool = False):
        self.weighted_aggregation = weighted

    def init(self, params: Params) -> FedAvgState:
        return FedAvgState(params=params)

    def aggregate(self, server_state: FedAvgState, results: FitResults, round_idx):
        merged = agg.aggregate(
            results.packets, results.sample_counts, results.mask,
            self.weighted_aggregation,
        )
        any_client = jnp.sum(results.mask) > 0
        merged = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o), merged, server_state.params
        )
        return FedAvgState(params=merged)

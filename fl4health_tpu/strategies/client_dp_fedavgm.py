"""Client-level DP-FedAvg with server momentum and adaptive clipping.

Parity: /root/reference/fl4health/strategies/client_dp_fedavgm.py:33 (+
noisy_aggregate.py:47,70; adaptive clipping per arXiv 1905.03871). Clients
send their CLIPPED weight-update delta plus a clipping-indicator bit
(ParameterPackerWithClippingBit; client half in
fl4health_tpu.clients.clipping). Server:

    delta_bar = (sum_i delta_i) / |S| + N(0, (z * C / |S|)^2)     [unweighted]
    v         = beta * v + delta_bar                               [momentum]
    x        += v
    b_bar     = (sum_i b_i + N(0, z_b^2)) / |S|                    [noised]
    C        *= exp(-lr_C * (b_bar - target_quantile))             [geometric]

Weighted aggregation (reference noisy_aggregate.py:70
``gaussian_noisy_weighted_aggregate``; McMahan et al. arXiv 1710.06963):

    w_k       = min(n_k / example_cap, 1)       (cap defaults to sum_k n_k)
    coef_k    = w_k / (q * W),  W = sum_k w_k,  q = fraction_fit
    delta_bar = (sum_{i in S} coef_i delta_i
                 + N(0, (z * C * max_{i in S} w_i / q)^2)) / |S|

matching the reference exactly, including its final 1/|S| normalization
(noisy_aggregate.py:41 applies ``1/n_clients`` to the already
coefficient-scaled sum).

Adaptive clipping additionally *modifies the update-noise multiplier*
(reference client_dp_fedavgm.py:181 ``modify_noise_multiplier``, Algorithm 1
of arXiv 1905.03871): z_delta = (z^-2 - (2 z_b)^-2)^(-1/2), so the privacy
accountant's z covers both the noised update and the noised clipping bit.
Applied only when both z and z_b are positive (z=0 configs stay
deterministic for tests; the reference crashes on those inputs).

**Deliberate divergence — adaptive-clipping bound-update ordering.** This
implementation computes the round's noise scale sigma from the PRE-round
clipping bound C_t and only then applies the geometric bound update to
produce C_{t+1} (both inside one compiled ``aggregate``). The reference
interleaves differently: it updates the bound from the incoming clipping
bits *before* building the next broadcast, so the sigma its server applies
in round t can reflect a partially-updated bound depending on call order.
The pre-round-bound convention here is the standard reading of
arXiv 1905.03871 Alg. 1 (noise calibrated to the bound the clients actually
clipped with) and is self-consistent: clients clip round t's update with
C_t, and sigma_t = z * C_t * (...) matches that sensitivity exactly. Do not
expect bitwise parity with the reference on adaptive-clipping runs; the
accounting (epsilon) is unaffected because z, not C, drives it.

**Sampling-fraction coupling.** With ``weighted_aggregation=True`` the
per-client coefficients divide by the sampling fraction q
(``fraction_fit``). If the configured q does not equal the client manager's
actual sampling fraction, sigma is mis-scaled by their ratio versus the
logged epsilon — e.g. leaving the old default q=1 while a manager samples
q=0.25 under-scales the noise 4x. ``fraction_fit`` therefore defaults to
None = "derive from the client manager at setup"
(``bind_client_manager``), and an explicitly configured value is asserted
equal to the manager's fraction when weighted aggregation is on.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import ClippingBitPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class ClientDpFedAvgMState:
    params: Params
    momentum: Params
    clipping_bound: jax.Array
    rng: jax.Array


@struct.dataclass
class ClippingPayload:
    params: Params
    clipping_bound: jax.Array


class ClientLevelDPFedAvgM(Strategy):
    def __init__(
        self,
        noise_multiplier: float = 1.0,
        server_momentum: float = 0.9,
        initial_clipping_bound: float = 0.1,
        adaptive_clipping: bool = False,
        bit_noise_multiplier: float = 1.0,
        clipping_learning_rate: float = 0.2,
        clipping_quantile: float = 0.5,
        weighted_aggregation: bool = False,
        fraction_fit: float | None = None,
        per_client_example_cap: float | None = None,
        seed: int = 0,
    ):
        self.z = noise_multiplier
        self.beta = server_momentum
        self.c0 = initial_clipping_bound
        self.adaptive = adaptive_clipping
        self.z_bit = bit_noise_multiplier
        self.lr_c = clipping_learning_rate
        self.quantile = clipping_quantile
        self.weighted_aggregation = weighted_aggregation
        # None = derive from the client manager at bind_client_manager (the
        # FederatedSimulation setup hook); standalone use falls back to 1.0.
        self.fraction_fit = fraction_fit
        self.example_cap = per_client_example_cap
        self.seed = seed
        # fail at construction, not mid-round (ref client_dp_fedavgm.py:195)
        self.effective_noise_multiplier()
        if (weighted_aggregation and fraction_fit is not None
                and not fraction_fit > 0.0):
            raise ValueError(
                f"fraction_fit must be positive, got {fraction_fit}: the "
                "weighted coefficients divide by it"
            )

    def bind_client_manager(self, client_manager) -> None:
        """Derive (or validate) the sampling fraction q from the client
        manager actually used (ADVICE round 5): with q<1 sampling, the old
        default q=1 under-scales sigma by 1/q versus the logged epsilon."""
        fraction = getattr(client_manager, "fraction", None)
        if self.fraction_fit is None:
            if self.weighted_aggregation and fraction is None:
                raise ValueError(
                    f"{type(client_manager).__name__} exposes no sampling "
                    "fraction; pass fraction_fit explicitly so the weighted "
                    "DP coefficients (and sigma) are scaled by the true q"
                )
            if (self.weighted_aggregation and fraction is not None
                    and not float(fraction) > 0.0):
                # same rejection the constructor applies to an explicit
                # value: the weighted coefficients divide by q
                raise ValueError(
                    f"client manager sampling fraction {float(fraction)} is "
                    "not positive; the weighted DP coefficients divide by it"
                )
            self.fraction_fit = float(fraction) if fraction is not None else 1.0
        elif (self.weighted_aggregation and fraction is not None
              and not math.isclose(self.fraction_fit, float(fraction),
                                   rel_tol=1e-9, abs_tol=1e-12)):
            raise ValueError(
                f"fraction_fit={self.fraction_fit} does not match the client "
                f"manager's sampling fraction {float(fraction)}; with "
                "weighted_aggregation the coefficients divide by q, so a "
                "mismatch mis-scales sigma by their ratio vs the logged "
                "epsilon (omit fraction_fit to derive it from the manager)"
            )

    @property
    def _q(self) -> float:
        """The sampling fraction used in the weighted coefficients; 1.0 when
        never bound to a manager (standalone full-participation use)."""
        return 1.0 if self.fraction_fit is None else self.fraction_fit

    def effective_noise_multiplier(self) -> float:
        """The update-noise multiplier actually applied to delta_bar.

        Under adaptive clipping some privacy budget is spent on the noised
        clipping bit, so the update noise must be raised to keep the
        accountant's z honest: z_delta = (z^-2 - (2 z_b)^-2)^(-1/2)
        (ref client_dp_fedavgm.py:181, arXiv 1905.03871 Alg. 1). Identity
        when adaptive clipping is off or either multiplier is zero.
        """
        if not (self.adaptive and self.z > 0.0 and self.z_bit > 0.0):
            return self.z
        sqrt_arg = self.z ** -2.0 - (2.0 * self.z_bit) ** -2.0
        if sqrt_arg <= 0.0:
            raise ValueError(
                "noise_multiplier and bit_noise_multiplier are ill-related "
                f"for adaptive clipping: z^-2 - (2 z_b)^-2 = {sqrt_arg:.4g} "
                "<= 0; raise bit_noise_multiplier or lower noise_multiplier"
            )
        return sqrt_arg ** -0.5

    def init(self, params: Params) -> ClientDpFedAvgMState:
        return ClientDpFedAvgMState(
            params=params,
            momentum=ptu.tree_zeros_like(params),
            clipping_bound=jnp.asarray(self.c0, jnp.float32),
            rng=jax.random.PRNGKey(self.seed),
        )

    def client_payload(self, server_state, round_idx):
        return ClippingPayload(
            params=server_state.params,
            clipping_bound=server_state.clipping_bound,
        )

    def aggregate(self, server_state, results: FitResults, round_idx):
        packets: ClippingBitPacket = results.packets
        n_sampled = jnp.maximum(jnp.sum(results.mask), 1.0)
        rng, k_delta, k_bit = jax.random.split(server_state.rng, 3)
        z_eff = self.effective_noise_multiplier()

        if self.weighted_aggregation:
            # McMahan weighted path (ref noisy_aggregate.py:70): coefficient
            # per client from capped sample counts, noise scaled by the
            # largest participating coefficient; cap/W over the full cohort
            # (the reference computes them from the startup sample-count poll
            # of every registered client, client_dp_fedavgm.py:332).
            counts = results.sample_counts.astype(jnp.float32)
            cap = (jnp.sum(counts) if self.example_cap is None
                   else jnp.asarray(self.example_cap, jnp.float32))
            w = jnp.minimum(counts / jnp.maximum(cap, 1.0), 1.0)
            total_w = jnp.maximum(jnp.sum(w), 1e-12)
            coef = w / (self._q * total_w)

            def weighted_sum(stacked):
                cc = (coef * results.mask).reshape(
                    (-1,) + (1,) * (stacked.ndim - 1))
                return jnp.sum(stacked * cc, axis=0) / n_sampled

            delta_bar = jax.tree_util.tree_map(weighted_sum, packets.params)
            max_w = jnp.max(jnp.where(results.mask > 0, w, 0.0))
            # sensitivity of the coefficient-scaled sum is C*max(w)/q; the
            # reference's final 1/n normalization applies to noise too
            sigma = (z_eff * server_state.clipping_bound * max_w
                     / self._q / n_sampled)
        else:
            # unweighted masked mean of clipped deltas
            def mean_delta(stacked):
                mm = results.mask.reshape((-1,) + (1,) * (stacked.ndim - 1))
                return jnp.sum(stacked * mm, axis=0) / n_sampled

            delta_bar = jax.tree_util.tree_map(mean_delta, packets.params)
            # Gaussian mechanism: sensitivity C/|S| per coordinate-vector
            sigma = z_eff * server_state.clipping_bound / n_sampled
        leaves, treedef = jax.tree_util.tree_flatten(delta_bar)
        keys = jax.random.split(k_delta, len(leaves))
        noised = [
            l + sigma * jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype)
            for l, k in zip(leaves, keys)
        ]
        delta_bar = jax.tree_util.tree_unflatten(treedef, noised)

        new_momentum = ptu.tree_axpy(self.beta, server_state.momentum, delta_bar)
        new_params = ptu.tree_add(server_state.params, new_momentum)

        any_client = jnp.sum(results.mask) > 0
        bound = server_state.clipping_bound
        if self.adaptive:
            bit_sum = jnp.sum(packets.clipping_bit * results.mask)
            b_bar = (bit_sum + self.z_bit * jax.random.normal(k_bit, ())) / n_sampled
            # empty cohort: b_bar would be pure bit-noise — hold the bound
            # (the reference returns early with no results, base_server)
            bound = jnp.where(
                any_client,
                bound * jnp.exp(-self.lr_c * (b_bar - self.quantile)),
                bound,
            )
        new_params, new_momentum = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o),
            (new_params, new_momentum),
            (server_state.params, server_state.momentum),
        )
        return ClientDpFedAvgMState(
            params=new_params,
            momentum=new_momentum,
            clipping_bound=bound,
            rng=rng,
        )

"""Client-level DP-FedAvg with server momentum and adaptive clipping.

Parity: /root/reference/fl4health/strategies/client_dp_fedavgm.py:33 (+
noisy_aggregate.py:47,70; adaptive clipping per arXiv 1905.03871). Clients
send their CLIPPED weight-update delta plus a clipping-indicator bit
(ParameterPackerWithClippingBit; client half in
fl4health_tpu.clients.clipping). Server:

    delta_bar = (sum_i delta_i) / |S| + N(0, (z * C / |S|)^2)     [unweighted]
    v         = beta * v + delta_bar                               [momentum]
    x        += v
    b_bar     = (sum_i b_i + N(0, z_b^2)) / |S|                    [noised]
    C        *= exp(-lr_C * (b_bar - target_quantile))             [geometric]
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import ClippingBitPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class ClientDpFedAvgMState:
    params: Params
    momentum: Params
    clipping_bound: jax.Array
    rng: jax.Array


@struct.dataclass
class ClippingPayload:
    params: Params
    clipping_bound: jax.Array


class ClientLevelDPFedAvgM(Strategy):
    def __init__(
        self,
        noise_multiplier: float = 1.0,
        server_momentum: float = 0.9,
        initial_clipping_bound: float = 0.1,
        adaptive_clipping: bool = False,
        bit_noise_multiplier: float = 1.0,
        clipping_learning_rate: float = 0.2,
        clipping_quantile: float = 0.5,
        weighted_aggregation: bool = False,
        seed: int = 0,
    ):
        self.z = noise_multiplier
        self.beta = server_momentum
        self.c0 = initial_clipping_bound
        self.adaptive = adaptive_clipping
        self.z_bit = bit_noise_multiplier
        self.lr_c = clipping_learning_rate
        self.quantile = clipping_quantile
        self.weighted_aggregation = weighted_aggregation
        self.seed = seed

    def init(self, params: Params) -> ClientDpFedAvgMState:
        return ClientDpFedAvgMState(
            params=params,
            momentum=ptu.tree_zeros_like(params),
            clipping_bound=jnp.asarray(self.c0, jnp.float32),
            rng=jax.random.PRNGKey(self.seed),
        )

    def client_payload(self, server_state, round_idx):
        return ClippingPayload(
            params=server_state.params,
            clipping_bound=server_state.clipping_bound,
        )

    def aggregate(self, server_state, results: FitResults, round_idx):
        packets: ClippingBitPacket = results.packets
        n_sampled = jnp.maximum(jnp.sum(results.mask), 1.0)
        rng, k_delta, k_bit = jax.random.split(server_state.rng, 3)

        # unweighted masked mean of clipped deltas
        def mean_delta(stacked):
            mm = results.mask.reshape((-1,) + (1,) * (stacked.ndim - 1))
            return jnp.sum(stacked * mm, axis=0) / n_sampled

        delta_bar = jax.tree_util.tree_map(mean_delta, packets.params)
        # Gaussian mechanism: sensitivity C/|S| per coordinate-vector
        sigma = self.z * server_state.clipping_bound / n_sampled
        leaves, treedef = jax.tree_util.tree_flatten(delta_bar)
        keys = jax.random.split(k_delta, len(leaves))
        noised = [
            l + sigma * jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype)
            for l, k in zip(leaves, keys)
        ]
        delta_bar = jax.tree_util.tree_unflatten(treedef, noised)

        new_momentum = ptu.tree_axpy(self.beta, server_state.momentum, delta_bar)
        new_params = ptu.tree_add(server_state.params, new_momentum)

        bound = server_state.clipping_bound
        if self.adaptive:
            bit_sum = jnp.sum(packets.clipping_bit * results.mask)
            b_bar = (bit_sum + self.z_bit * jax.random.normal(k_bit, ())) / n_sampled
            bound = bound * jnp.exp(-self.lr_c * (b_bar - self.quantile))

        any_client = jnp.sum(results.mask) > 0
        new_params, new_momentum = jax.tree_util.tree_map(
            lambda n, o: jnp.where(any_client, n, o),
            (new_params, new_momentum),
            (server_state.params, server_state.momentum),
        )
        return ClientDpFedAvgMState(
            params=new_params,
            momentum=new_momentum,
            clipping_bound=bound,
            rng=rng,
        )

"""Strategy abstraction — server-side aggregation as pure functions.

Reference surface: flwr Strategy subclasses in /root/reference/fl4health/strategies/
own configure_fit/aggregate_fit/aggregate_evaluate plus wire pack/unpack.

TPU-native design: a Strategy owns a ``ServerState`` pytree and two pure
functions — ``client_payload`` (what every client receives this round;
broadcast is free under SPMD) and ``aggregate`` (stacked client packets ->
new server state), both jit-compiled into the round program. Client sampling
lives in ``fl4health_tpu.server.client_manager`` and produces a mask, so a
partially-sampled cohort never changes program shapes.
"""

from __future__ import annotations

from typing import Any, Generic, TypeVar

import jax
from flax import struct

from fl4health_tpu.core.types import Params

S = TypeVar("S")


@struct.dataclass
class FitResults:
    """Stacked results of one fit round — what aggregate() consumes.

    packets:       client-stacked payload pytree (params or richer packet)
    sample_counts: [clients] train-set sizes
    train_losses:  dict of [clients] scalars from local training meters
    train_metrics: dict of [clients] metric values
    mask:          [clients] 1.0 = participated this round
    """

    packets: Any
    sample_counts: jax.Array
    train_losses: Any
    train_metrics: Any
    mask: jax.Array


def replace_global_params(strategy: "Strategy", server_state: Any, params) -> Any:
    """``server_state`` with the innermost strategy's params replaced,
    through any wrapper nesting (CompressingStrategy, QuarantiningStrategy,
    ... — wrappers expose ``.inner`` on both the strategy and its state).
    The direct ``state.replace(params=...)`` only works on unwrapped
    states; every params-installation path (checkpoint import, evaluate
    server hydration) must go through this instead."""
    if hasattr(strategy, "inner") and hasattr(server_state, "inner"):
        return server_state.replace(inner=replace_global_params(
            strategy.inner, server_state.inner, params
        ))
    return server_state.replace(params=params)


def inner_state_sharding_spec(inner: "Strategy", server_state: Any,
                              clients_axis: str):
    """Delegate ``state_sharding_spec`` to a wrapped strategy for use
    inside a wrapper's own spec pytree. A wrapper state embeds the inner
    SPEC tree, so the inner strategy's "no preference" (no hook, or the
    hook returning None) must become an explicit replicate-everything
    ``P()`` leaf rather than None — None would read as "no spec for this
    subtree" and mis-shard the wrapper state."""
    from jax.sharding import PartitionSpec as P

    hook = getattr(inner, "state_sharding_spec", None)
    spec = hook(server_state, clients_axis) if hook else None
    return P() if spec is None else spec


class Strategy:
    """Base protocol. Subclasses override any of the four methods.

    All methods must be jit-traceable (no data-dependent Python control flow).
    """

    weighted_aggregation: bool = True
    weighted_eval_aggregation: bool = True

    def bind_client_manager(self, client_manager: Any) -> None:
        """Setup-time hook: FederatedSimulation calls this with its client
        manager before training so a strategy can derive/validate sampling
        assumptions (e.g. DP-FedAvgM's ``fraction_fit`` against the
        manager's sampling fraction). Runs host-side once; default no-op."""

    def init(self, params: Params) -> Any:
        """Build initial server state from initial model params."""
        raise NotImplementedError

    def state_sharding_spec(self, server_state: Any, clients_axis: str):
        """Optional per-leaf ``PartitionSpec`` pytree (prefix) for the
        server state on a client mesh; ``None`` = fully replicated.

        Strategies whose state carries per-client ``[C, ...]`` leaves
        (wrapper bookkeeping, EF residuals) or replica-sharded optimizer
        vectors (the ZeRO-1 server optimizer) override this so the round
        program's ``in_shardings``/``out_shardings`` keep those leaves
        split instead of replicating the whole state
        (``parallel/program.py RoundProgramBuilder``)."""
        return None

    def state_rows(self, server_state: Any) -> Any:
        """Per-client rows of the server state: a pytree whose every leaf
        carries a leading ``[C]`` client axis (wrapper bookkeeping like
        quarantine strikes, error-feedback residuals), or ``None`` when
        the strategy keeps no per-client server state.

        Cohort-slot execution (``server/registry.py``) gathers these rows
        for the sampled cohort into fixed ``[K]`` slot tensors before each
        round and scatters the updated rows back into the host registry
        afterwards. Strategies exposing rows MUST (a) initialize every
        client's row identically in ``init`` (client-symmetric start — the
        registry derives un-touched clients' rows from one prototype) and
        (b) keep client ``i``'s row a function of client ``i``'s
        participation only. Wrapper strategies compose by embedding the
        inner strategy's rows under an ``"inner"`` key; state-passthrough
        wrappers (``FedBuff``, whose state IS the inner state) delegate
        wholesale."""
        return None

    def scatter_state_rows(self, server_state: Any, rows: Any) -> Any:
        """Inverse of :meth:`state_rows`: the server state with its
        per-client rows replaced by ``rows`` (the same structure
        ``state_rows`` returned, leaves re-gathered to a new leading
        axis). Must be pure tree surgery — no math — so gather/scatter
        round-trips bit-identically."""
        if jax.tree_util.tree_leaves(rows):
            raise ValueError(
                f"{type(self).__name__} has no per-client state rows to "
                "scatter into (state_rows() is None)"
            )
        return server_state

    def global_params(self, server_state: Any) -> Params:
        """The current global model params (for checkpointing/eval)."""
        return server_state.params

    def divergence_reference(self, server_state: Any) -> Params:
        """Reference point for the in-graph weight-divergence telemetry
        (observability/telemetry.py): each client stack's l2 distance is
        measured from THIS tree after aggregation. Default: the aggregated
        global model. Strategies whose broadcast differs from their stored
        globals (e.g. a server-momentum strategy whose payload folds in the
        momentum step) may override so divergence measures distance from
        what clients will actually pull next round. Jit-traceable."""
        return self.global_params(server_state)

    def client_payload(self, server_state: Any, round_idx: jax.Array) -> Any:
        """What is broadcast to clients this round (configure_fit's parameters)."""
        return server_state.params

    def aggregate(self, server_state: Any, results: FitResults, round_idx: jax.Array) -> Any:
        """aggregate_fit: consume stacked packets, produce new server state."""
        raise NotImplementedError

    def update_after_eval(
        self,
        server_state: Any,
        eval_losses: Any,
        eval_metrics: Any,
        mask: jax.Array,
    ) -> Any:
        """Consume per-client post-aggregation eval results ([clients] arrays).

        Needed by strategies whose next-round weights depend on evaluation of
        the aggregated model (FedDG-GA's generalization gaps,
        strategies/feddg_ga.py:382 update_weights_by_ga). Default: no-op.
        """
        return server_state

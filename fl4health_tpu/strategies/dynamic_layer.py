"""Aggregation for partial payloads: dynamic layers and sparse elements.

Parity:
- FedAvgDynamicLayer (/root/reference/fl4health/strategies/fedavg_dynamic_layer.py:17):
  clients send arbitrary layer subsets; each layer is averaged over the
  clients that sent it.
- FedAvgSparseCooTensor (strategies/fedavg_sparse_coo_tensor.py:18): same at
  element granularity with COO-packed tensors.

TPU shape: payloads are full-shaped with 0/1 masks (LayerMaskPacket /
SparseMaskPacket), so "average over senders" is a masked sum divided by the
per-leaf (or per-element) sender count. Layers nobody sent keep the previous
global value.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import LayerMaskPacket, SparseMaskPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class MaskedAvgState:
    params: Params


class FedAvgDynamicLayer(Strategy):
    """Per-leaf sender-averaged aggregation; weighted by sample counts among
    senders (the reference uses weighted averaging within the sender set)."""

    def __init__(self, weighted_aggregation: bool = True):
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> MaskedAvgState:
        return MaskedAvgState(params=params)

    def aggregate(self, server_state: MaskedAvgState, results: FitResults, round_idx):
        packets: LayerMaskPacket = results.packets
        counts = (
            results.sample_counts if self.weighted_aggregation
            else jnp.ones_like(results.sample_counts)
        )
        cohort = results.mask * counts  # [clients]

        def agg_leaf(stacked_vals: jax.Array, stacked_sel: jax.Array, prev: jax.Array):
            # stacked_sel: [clients] scalar 0/1 per leaf
            w = cohort * stacked_sel
            total = jnp.sum(w)
            wn = jnp.where(total > 0, w / jnp.maximum(total, 1e-12), w)
            wb = wn.reshape((-1,) + (1,) * (stacked_vals.ndim - 1))
            avg = jnp.sum(stacked_vals.astype(jnp.float32) * wb, axis=0)
            return jnp.where(total > 0, avg, prev.astype(jnp.float32)).astype(prev.dtype)

        new_params = jax.tree_util.tree_map(
            agg_leaf, packets.params, packets.leaf_mask, server_state.params
        )
        return MaskedAvgState(params=new_params)


class FedAvgSparse(Strategy):
    """Element-granular sender-averaged aggregation (sparse COO semantics)."""

    def __init__(self, weighted_aggregation: bool = True):
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> MaskedAvgState:
        return MaskedAvgState(params=params)

    def aggregate(self, server_state: MaskedAvgState, results: FitResults, round_idx):
        packets: SparseMaskPacket = results.packets
        counts = (
            results.sample_counts if self.weighted_aggregation
            else jnp.ones_like(results.sample_counts)
        )
        cohort = results.mask * counts

        def agg_leaf(stacked_vals: jax.Array, stacked_mask: jax.Array, prev: jax.Array):
            wb = cohort.reshape((-1,) + (1,) * (stacked_vals.ndim - 1))
            w = stacked_mask.astype(jnp.float32) * wb  # [clients, ...]
            total = jnp.sum(w, axis=0)  # per element
            s = jnp.sum(stacked_vals.astype(jnp.float32) * w, axis=0)
            avg = s / jnp.maximum(total, 1e-12)
            return jnp.where(total > 0, avg, prev.astype(jnp.float32)).astype(prev.dtype)

        new_params = jax.tree_util.tree_map(
            agg_leaf, packets.params, packets.element_mask, server_state.params
        )
        return MaskedAvgState(params=new_params)

"""Aggregation for partial payloads: dynamic layers and sparse elements.

Parity:
- FedAvgDynamicLayer (/root/reference/fl4health/strategies/fedavg_dynamic_layer.py:17):
  clients send arbitrary layer subsets; each layer is averaged over the
  clients that sent it.
- FedAvgSparseCooTensor (strategies/fedavg_sparse_coo_tensor.py:18): same at
  element granularity with COO-packed tensors.

TPU shape: payloads are full-shaped with 0/1 masks (LayerMaskPacket /
SparseMaskPacket), so "average over senders" is a masked sum divided by the
per-leaf (or per-element) sender count. Layers nobody sent keep the previous
global value.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.core.types import Params, PyTree
from fl4health_tpu.exchange.packer import LayerMaskPacket, SparseMaskPacket
from fl4health_tpu.strategies.base import FitResults, Strategy


@struct.dataclass
class MaskedAvgState:
    """``updated`` records which leaves/elements the LAST aggregation
    actually refreshed; the client payload carries it so pulls replace only
    refreshed entries and keep everything else client-local — the
    local-retention contract of partial exchange (the reference ships only
    the aggregated layer subset back, fedavg_dynamic_layer.py)."""

    params: Params
    updated: PyTree


class FedAvgDynamicLayer(Strategy):
    """Per-leaf sender-averaged aggregation; weighted by sample counts among
    senders (the reference uses weighted averaging within the sender set)."""

    def __init__(self, weighted_aggregation: bool = True):
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> MaskedAvgState:
        # nothing aggregated yet: round-1 pulls keep client-local weights
        # (identical to the server broadcast at init by construction)
        return MaskedAvgState(
            params=params,
            updated=jax.tree_util.tree_map(
                lambda _: jnp.zeros((), jnp.float32), params
            ),
        )

    def client_payload(self, server_state: MaskedAvgState, round_idx):
        return LayerMaskPacket(
            params=server_state.params, leaf_mask=server_state.updated
        )

    def aggregate(self, server_state: MaskedAvgState, results: FitResults, round_idx):
        packets: LayerMaskPacket = results.packets
        counts = (
            results.sample_counts if self.weighted_aggregation
            else jnp.ones_like(results.sample_counts)
        )
        cohort = results.mask * counts  # [clients]

        def agg_leaf(stacked_vals: jax.Array, stacked_sel: jax.Array, prev: jax.Array):
            # stacked_sel: [clients] scalar 0/1 per leaf
            w = cohort * stacked_sel
            total = jnp.sum(w)
            wn = jnp.where(total > 0, w / jnp.maximum(total, 1e-12), w)
            wb = wn.reshape((-1,) + (1,) * (stacked_vals.ndim - 1))
            avg = jnp.sum(stacked_vals.astype(jnp.float32) * wb, axis=0)
            return jnp.where(total > 0, avg, prev.astype(jnp.float32)).astype(prev.dtype)

        new_params = jax.tree_util.tree_map(
            agg_leaf, packets.params, packets.leaf_mask, server_state.params
        )
        updated = jax.tree_util.tree_map(
            lambda sel: (jnp.sum(cohort * sel) > 0).astype(jnp.float32),
            packets.leaf_mask,
        )
        return MaskedAvgState(params=new_params, updated=updated)


class FedAvgSparse(Strategy):
    """Element-granular sender-averaged aggregation (sparse COO semantics)."""

    def __init__(self, weighted_aggregation: bool = True):
        self.weighted_aggregation = weighted_aggregation

    def init(self, params: Params) -> MaskedAvgState:
        # masks are f32 in EVERY round (aggregate returns f32) — a params-
        # dtype round-1 mask would change the jit signature and recompile
        return MaskedAvgState(
            params=params,
            updated=jax.tree_util.tree_map(
                lambda prm: jnp.zeros(prm.shape, jnp.float32), params
            ),
        )

    def client_payload(self, server_state: MaskedAvgState, round_idx):
        return SparseMaskPacket(
            params=server_state.params, element_mask=server_state.updated
        )

    def aggregate(self, server_state: MaskedAvgState, results: FitResults, round_idx):
        packets: SparseMaskPacket = results.packets
        counts = (
            results.sample_counts if self.weighted_aggregation
            else jnp.ones_like(results.sample_counts)
        )
        cohort = results.mask * counts

        def agg_leaf(stacked_vals: jax.Array, stacked_mask: jax.Array, prev: jax.Array):
            wb = cohort.reshape((-1,) + (1,) * (stacked_vals.ndim - 1))
            w = stacked_mask.astype(jnp.float32) * wb  # [clients, ...]
            total = jnp.sum(w, axis=0)  # per element
            s = jnp.sum(stacked_vals.astype(jnp.float32) * w, axis=0)
            avg = s / jnp.maximum(total, 1e-12)
            return jnp.where(total > 0, avg, prev.astype(jnp.float32)).astype(prev.dtype)

        new_params = jax.tree_util.tree_map(
            agg_leaf, packets.params, packets.element_mask, server_state.params
        )
        def elem_updated(stacked_mask):
            wb = cohort.reshape((-1,) + (1,) * (stacked_mask.ndim - 1))
            return (jnp.sum(stacked_mask.astype(jnp.float32) * wb, axis=0) > 0
                    ).astype(jnp.float32)

        updated = jax.tree_util.tree_map(elem_updated, packets.element_mask)
        return MaskedAvgState(params=new_params, updated=updated)

"""Cohort-slot virtualization — rounds compile and run in O(sampled cohort).

ROADMAP item 1's registry half (FedJAX's stated regime, arXiv:2108.02117:
"thousands of simulated clients per round sampled from a registry of
millions"): the dense client axis made every round program, train bank,
sampling mask and per-client state leaf an ``[n_clients, ...]`` stack, so
HBM footprint and per-round FLOPs scaled with the REGISTRY, not the
participating cohort. This module decouples them:

- :class:`CohortConfig` — ``FederatedSimulation(cohort=CohortConfig(
  slots=K))`` compiles every round program against a fixed ``[K]`` slot
  axis, regardless of registry size. Same shared-compilation argument the
  sweep engine makes for hyperparameter grids (PR 11), applied to the
  client axis itself.
- :class:`ClientRegistry` — the host/CPU-resident store of per-client
  datasets and per-client persistent state rows: the full ``TrainState``
  row (params, optimizer momenta, PRNG stream, SCAFFOLD control variates
  riding in the client state) plus the strategies' per-client server rows
  (quarantine strikes, error-feedback residuals) via the
  ``Strategy.state_rows``/``scatter_state_rows`` hooks. Un-touched
  clients resolve to one shared prototype row (client-symmetric init), so
  registry memory is O(participated clients), not O(N) x model size.
- Data sources — :class:`ListDataSource` wraps the classic per-client
  ``ClientDataset`` list; :class:`IndexedPoolSource` holds ONE shared
  example pool plus per-client index views, so a million-client non-IID
  registry (``datasets/registry_presets.py`` Dirichlet presets) costs the
  pool once plus N index arrays — never N densified shards.

Per round r the simulation samples cohort ids on the host
(``ClientManager.sample_indices``), the :class:`ClientRegistry` gathers
those K clients' batches/state into ``[K, ...]`` slot tensors
(double-buffered through ``RoundPrefetcher`` so data staging for round
r+1 overlaps round r's device work, ``device_put`` sharded when a
``MeshConfig`` is active), the SAME compiled ``[K]``-shaped fit/eval
programs dispatch, and the updated rows scatter back off the consumer's
existing fused device->host transfer.

Determinism contract: a client's batch plan is seeded by its REGISTRY id
(``[*base_entropy, 1000 + round, registry_id]``) and its PRNG row by
``fold_in(init_rng, registry_id + 1)`` — exactly the dense path's streams
— so ``slots == n_clients`` under full participation reproduces the
dense trajectory bit-for-bit (pinned by tests/server/test_cohort_slots.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.engine import Batch


@dataclasses.dataclass(frozen=True)
class CohortConfig:
    """Cohort-slot execution request for :class:`FederatedSimulation`.

    ``slots``: the fixed slot count K every round program compiles
    against. A sampling draw larger than K raises
    ``CohortOverflowError``; smaller draws pad with zero-weight slots.
    ``slots == registry size`` under full participation is pinned
    bit-identical to the dense path."""

    slots: int

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError(
                f"CohortConfig.slots must be >= 1; got {self.slots}"
            )


# ---------------------------------------------------------------------------
# data sources


class RegistryDataSource:
    """Host-resident per-client data behind a :class:`ClientRegistry`.

    The contract is index-addressed and lazy: ``client_train(i)`` /
    ``client_val(i)`` return host (numpy) ``(x, y)`` pytrees for ONE
    client on demand, and the size vectors are available without
    materializing any data — the registry sizes its fixed slot shapes
    from them. Every client must share one per-example shape/dtype (the
    cohort shares one compiled program)."""

    n_clients: int = 0

    def train_sizes(self) -> np.ndarray:
        raise NotImplementedError

    def val_sizes(self) -> np.ndarray:
        raise NotImplementedError

    def client_train(self, i: int) -> tuple[Any, Any]:
        raise NotImplementedError

    def client_val(self, i: int) -> tuple[Any, Any]:
        raise NotImplementedError


class ListDataSource(RegistryDataSource):
    """The classic per-client ``ClientDataset`` list as a registry source
    (the small-N compatibility path; large-N registries should use
    :class:`IndexedPoolSource` so shards are views, not copies)."""

    def __init__(self, datasets: Sequence[Any]):
        if not datasets:
            raise ValueError("registry needs at least one client dataset")
        self._datasets = list(datasets)
        self.n_clients = len(self._datasets)
        for i, d in enumerate(self._datasets):
            if getattr(d, "x_test", None) is not None or getattr(
                d, "y_test", None
            ) is not None:
                raise ValueError(
                    f"client {i} has a test split: cohort-slot execution "
                    "evaluates the sampled cohort's val split only (a "
                    "registry-wide test pass would be O(N) per round — "
                    "run it separately on the final global model)"
                )
            for split in ("train", "val"):
                xs, ys = getattr(d, f"x_{split}"), getattr(d, f"y_{split}")
                nx, ny = engine.data_rows(xs), engine.data_rows(ys)
                if nx != ny:
                    raise ValueError(
                        f"client {i}: x_{split} has {nx} rows but "
                        f"y_{split} has {ny}; features and labels must "
                        "pair one-to-one"
                    )

    def train_sizes(self) -> np.ndarray:
        return np.asarray([d.n_train for d in self._datasets], np.int64)

    def val_sizes(self) -> np.ndarray:
        return np.asarray(
            [engine.data_rows(d.x_val) for d in self._datasets], np.int64
        )

    def client_train(self, i: int) -> tuple[Any, Any]:
        d = self._datasets[i]
        return d.x_train, d.y_train

    def client_val(self, i: int) -> tuple[Any, Any]:
        d = self._datasets[i]
        return d.x_val, d.y_val


class IndexedPoolSource(RegistryDataSource):
    """One shared example pool + per-client index views.

    ``train_pool``/``val_pool`` are ``(x, y)`` host pytrees sharing axis
    0; ``train_indices[i]``/``val_indices[i]`` are each client's row ids
    into the corresponding pool. Memory is O(pool + sum(index arrays)) —
    a million-client Dirichlet partition over CIFAR costs the images once.
    ``client_train`` materializes one client's shard as a fancy-indexed
    view copy only when that client is actually sampled."""

    def __init__(self, train_pool: tuple[Any, Any],
                 val_pool: tuple[Any, Any],
                 train_indices: Sequence[np.ndarray],
                 val_indices: Sequence[np.ndarray]):
        if len(train_indices) != len(val_indices):
            raise ValueError(
                f"train_indices ({len(train_indices)} clients) and "
                f"val_indices ({len(val_indices)} clients) disagree"
            )
        if not train_indices:
            raise ValueError("registry needs at least one client")
        self._train_pool = train_pool
        self._val_pool = val_pool
        self._train_idx = [np.asarray(ix, np.int64) for ix in train_indices]
        self._val_idx = [np.asarray(ix, np.int64) for ix in val_indices]
        self.n_clients = len(self._train_idx)
        for name, pool, idx_list in (
            ("train", train_pool, self._train_idx),
            ("val", val_pool, self._val_idx),
        ):
            rows = engine.data_rows(pool[0])
            hi = max((int(ix.max()) for ix in idx_list if ix.size), default=-1)
            if hi >= rows:
                raise ValueError(
                    f"{name}_indices reference row {hi} but the pool has "
                    f"only {rows} rows"
                )
            empty = [i for i, ix in enumerate(idx_list) if ix.size == 0]
            if empty:
                raise ValueError(
                    f"clients {empty[:5]}{'...' if len(empty) > 5 else ''} "
                    f"have empty {name} shards; every registry client "
                    "needs at least one example per split"
                )

    def train_sizes(self) -> np.ndarray:
        return np.asarray([ix.shape[0] for ix in self._train_idx], np.int64)

    def val_sizes(self) -> np.ndarray:
        return np.asarray([ix.shape[0] for ix in self._val_idx], np.int64)

    @staticmethod
    def _take(pool, ix):
        return jax.tree_util.tree_map(lambda a: np.asarray(a)[ix], pool)

    def client_train(self, i: int) -> tuple[Any, Any]:
        ix = self._train_idx[i]
        return (self._take(self._train_pool[0], ix),
                self._take(self._train_pool[1], ix))

    def client_val(self, i: int) -> tuple[Any, Any]:
        ix = self._val_idx[i]
        return (self._take(self._val_pool[0], ix),
                self._take(self._val_pool[1], ix))


def as_registry_source(datasets: Any) -> RegistryDataSource:
    """Normalize ``FederatedSimulation``'s ``datasets`` argument for
    cohort mode: a :class:`RegistryDataSource` passes through, anything
    iterable wraps in a :class:`ListDataSource`."""
    if isinstance(datasets, RegistryDataSource):
        return datasets
    return ListDataSource(list(datasets))


# ---------------------------------------------------------------------------
# sparse row store


class _SparseRowStore:
    """Sparse ``[N, ...]`` host row store.

    Clients that never participated resolve to caller-provided fresh rows
    (the client-symmetric prototype), so memory is O(participated
    clients) — the property that makes a million-client registry fit in
    host RAM. Rows are stored as flat leaf lists keyed by registry id."""

    def __init__(self, name: str):
        self.name = name
        self._rows: dict[int, list[np.ndarray]] = {}
        self._treedef = None

    @property
    def dirty(self) -> int:
        return len(self._rows)

    def gather(self, idx: np.ndarray, fresh_rows: Any) -> Any:
        """``fresh_rows`` is the default ``[K, ...]`` host tree for these
        ids (prototype broadcast + per-id PRNG rows); stored rows
        overwrite their slots."""
        leaves, treedef = jax.tree_util.tree_flatten(fresh_rows)
        if self._treedef is None:
            self._treedef = treedef
        out = [np.array(l) for l in leaves]  # writable copies
        for k, cid in enumerate(np.asarray(idx)):
            row = self._rows.get(int(cid))
            if row is not None:
                for j, leaf in enumerate(row):
                    out[j][k] = leaf
        return jax.tree_util.tree_unflatten(treedef, out)

    def scatter(self, idx: np.ndarray, rows: Any, valid: int) -> None:
        """Write the first ``valid`` slots' rows back under their registry
        ids (pad slots never persist). Row leaves are copied out of the
        ``[K, ...]`` stack so the store never pins a round's full fused
        transfer buffer."""
        leaves, treedef = jax.tree_util.tree_flatten(rows)
        if self._treedef is None:
            self._treedef = treedef
        ids = np.asarray(idx)
        for k in range(int(valid)):
            self._rows[int(ids[k])] = [np.array(l[k]) for l in leaves]

    # -- checkpointing (PR 12 frame format payloads) --------------------
    def export(self) -> tuple[np.ndarray, Any | None]:
        """(sorted dirty ids [D], stacked row tree [D, ...] or None when
        clean) — the registry's durable half of a cohort checkpoint."""
        if not self._rows:
            return np.zeros((0,), np.int64), None
        ids = np.asarray(sorted(self._rows), np.int64)
        stacked = [
            np.stack([self._rows[int(c)][j] for c in ids])
            for j in range(len(self._rows[int(ids[0])]))
        ]
        return ids, jax.tree_util.tree_unflatten(self._treedef, stacked)

    def stacked_template(self, proto_row: Any, d: int) -> Any:
        """Zero ``[d, ...]`` tree matching :meth:`export`'s stacked rows —
        the deserialization target for a restored frame."""
        return jax.tree_util.tree_map(
            lambda l: np.zeros((d,) + np.asarray(l).shape,
                               np.asarray(l).dtype),
            proto_row,
        )

    def load(self, ids: np.ndarray, stacked: Any | None) -> None:
        self._rows.clear()
        if stacked is None or len(ids) == 0:
            return
        leaves, treedef = jax.tree_util.tree_flatten(stacked)
        self._treedef = treedef
        for k, cid in enumerate(np.asarray(ids)):
            self._rows[int(cid)] = [np.array(l[k]) for l in leaves]


# ---------------------------------------------------------------------------
# the registry


class ClientRegistry:
    """Host-resident registry of per-client datasets + persistent state.

    Owns the fixed slot shapes (registry-wide step budgets, so the
    compiled ``[K]`` programs never recompile as cohorts change), the
    per-round host staging of slot tensors, and the sparse row stores the
    gather/scatter cycle reads and writes. Built and driven by
    :class:`~fl4health_tpu.server.simulation.FederatedSimulation` when a
    :class:`CohortConfig` is active."""

    def __init__(self, source: RegistryDataSource, batch_size: int,
                 local_steps: int | None, local_epochs: int | None):
        self.source = source
        self.n_clients = source.n_clients
        self.batch_size = batch_size
        self.local_steps = local_steps
        self.local_epochs = local_epochs
        self.train_sizes = np.asarray(source.train_sizes(), np.int64)
        self.val_sizes = np.asarray(source.val_sizes(), np.int64)
        for name, sizes in (("train", self.train_sizes),
                            ("val", self.val_sizes)):
            if sizes.shape != (self.n_clients,):
                raise ValueError(
                    f"{name}_sizes must be [n_clients]; got {sizes.shape}"
                )
            if (sizes < 1).any():
                raise ValueError(
                    f"every registry client needs >= 1 {name} example"
                )
        # registry-wide FIXED step budgets: the slot programs' scan
        # lengths must not depend on which clients a round samples
        steps_per_epoch = -(-int(self.train_sizes.max()) // batch_size)
        if local_steps is not None:
            self.train_steps = int(local_steps)
        else:
            self.train_steps = int(local_epochs) * steps_per_epoch
        self.val_steps = -(-int(self.val_sizes.max()) // batch_size)
        # state row stores (bound by the simulation after init)
        self._client_store = _SparseRowStore("client_states")
        self._strategy_store = _SparseRowStore("strategy_rows")
        self._client_proto: Any = None  # one host TrainState row
        self._strategy_proto: Any = None  # one host strategy-row tree
        self._init_rng = None
        self._has_strategy_rows = False
        # example prototypes for abstract (no-device-work) staging shapes
        x0, y0 = source.client_train(0)
        self._x_example = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape[1:],
                                           np.asarray(a).dtype), x0
        )
        self._y_example = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape[1:],
                                           np.asarray(a).dtype), y0
        )

    # -- facts -----------------------------------------------------------
    @property
    def dirty_rows(self) -> int:
        return self._client_store.dirty

    def reset_rows(self) -> None:
        """Drop every persisted per-client row (state AND strategy): all
        clients resolve to the bound client-symmetric prototypes again —
        the registry half of a rollback-to-initial
        (``FederatedSimulation._reset_to_initial``)."""
        self._client_store._rows.clear()
        self._strategy_store._rows.clear()

    def sample_x(self) -> Any:
        """Client 0's first training example (model-init probe)."""
        x0, _ = self.source.client_train(0)
        return jax.tree_util.tree_map(lambda a: np.asarray(a)[:1], x0)

    # -- state rows ------------------------------------------------------
    def bind_client_states(self, proto: Any, init_rng) -> None:
        """Install the client-symmetric prototype ``TrainState`` row (host
        copy of the constructor's proto, shared by every un-touched
        client) and the init PRNG key from which client ``i``'s stream is
        ``fold_in(init_rng, i + 1)`` — the dense constructor's exact
        derivation."""
        self._client_proto = jax.device_get(proto)
        self._init_rng = init_rng

    def bind_strategy_rows(self, rows_slot: Any) -> None:
        """Install the strategy-row prototype from a freshly-initialized
        ``[K]`` slot state's rows. Verifies the client-symmetric-init
        contract (every slot row identical) that lets the registry derive
        un-touched clients' rows from row 0."""
        leaves = jax.tree_util.tree_leaves(rows_slot)
        self._has_strategy_rows = bool(leaves)
        if not self._has_strategy_rows:
            return
        host = jax.device_get(rows_slot)
        for path, leaf in jax.tree_util.tree_flatten_with_path(host)[0]:
            arr = np.asarray(leaf)
            if arr.shape[0] > 1 and not np.all(arr == arr[0]):
                raise ValueError(
                    "state_rows must initialize every client identically "
                    f"(client-symmetric start); leaf {engine.path_str(path)}"
                    " differs across slots at init — the registry cannot "
                    "derive un-sampled clients' rows from a prototype"
                )
        self._strategy_proto = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[0], host
        )

    def _default_rng_rows(self, idx: np.ndarray):
        ids = jnp.asarray(np.asarray(idx, np.int64) + 1)
        return np.asarray(
            jax.vmap(lambda i: jax.random.fold_in(self._init_rng, i))(ids)
        )

    def gather_client_states(self, idx: np.ndarray) -> Any:
        """``[K, ...]`` host ``TrainState`` rows for the sampled ids:
        prototype broadcast + per-id PRNG streams, overwritten by stored
        rows for clients that participated before."""
        if self._client_proto is None:
            raise RuntimeError("bind_client_states was never called")
        k = len(idx)
        fresh = jax.tree_util.tree_map(
            lambda l: np.broadcast_to(
                np.asarray(l), (k,) + np.asarray(l).shape
            ),
            self._client_proto,
        )
        fresh = fresh.replace(rng=self._default_rng_rows(idx))
        return self._client_store.gather(idx, fresh)

    @property
    def has_strategy_rows(self) -> bool:
        """Whether the bound strategy carries per-client server rows
        (SCAFFOLD variates, EF residuals, ...) that ride the slot/window
        exchange. Static at bind time — the compiled cohort chunk
        specializes on it."""
        return self._has_strategy_rows

    def gather_strategy_rows(self, idx: np.ndarray) -> Any | None:
        if not self._has_strategy_rows:
            return None
        k = len(idx)
        fresh = jax.tree_util.tree_map(
            lambda l: np.broadcast_to(
                np.asarray(l), (k,) + np.asarray(l).shape
            ),
            self._strategy_proto,
        )
        return self._strategy_store.gather(idx, fresh)

    def scatter(self, idx: np.ndarray, valid: int, client_rows: Any,
                strategy_rows: Any | None) -> None:
        """Persist the round's updated rows (first ``valid`` slots) under
        their registry ids — the host half of the consumer's fused
        transfer."""
        self._client_store.scatter(idx, client_rows, valid)
        if self._has_strategy_rows and strategy_rows is not None:
            self._strategy_store.scatter(idx, strategy_rows, valid)

    # -- per-round data staging -----------------------------------------
    def train_plan(self, idx: np.ndarray, base_entropy, round_idx: int):
        """The sampled cohort's batch plan, seeded per REGISTRY id (the
        dense path's exact streams) and padded to the registry-wide step
        budget."""
        ns = [int(self.train_sizes[int(c)]) for c in idx]
        entropies = [
            [*base_entropy, 1000 + round_idx, int(c)] for c in idx
        ]
        return engine.multi_client_index_plans(
            entropies, ns, self.batch_size, n_steps=self.local_steps,
            local_epochs=self.local_epochs, pad_steps=self.train_steps,
        )

    def _gather_rows(self, getter, idx, plan_idx):
        xs, ys = [], []
        for k, c in enumerate(np.asarray(idx)):
            x, y = getter(int(c))
            take = plan_idx[k]
            xs.append(jax.tree_util.tree_map(
                lambda a: np.asarray(a)[take], x
            ))
            ys.append(jax.tree_util.tree_map(
                lambda a: np.asarray(a)[take], y
            ))
        stack = lambda rows: jax.tree_util.tree_map(  # noqa: E731
            lambda *ls: np.stack(ls), *rows
        )
        return stack(xs), stack(ys)

    def stage_round(self, idx: np.ndarray, valid: int, base_entropy,
                    round_idx: int) -> dict:
        """Assemble one round's host slot tensors: train batches
        ``[K, S, B, ...]`` (the dense ``gather_batches`` result, computed
        host-side from the registry instead of device-side from O(N)
        banks), the cohort's val batches/counts, the traced sample counts
        and the slot participation mask. Pure numpy — device placement is
        the caller's (prefetcher's) job, so staging can run on a worker
        thread and overlap device execution."""
        idx = np.asarray(idx, np.int64)
        k = len(idx)
        p_idx, p_em, p_sm = self.train_plan(idx, base_entropy, round_idx)
        bx, by = self._gather_rows(self.source.client_train, idx, p_idx)
        batches = Batch(x=bx, y=by, example_mask=p_em, step_mask=p_sm)
        # val: fixed-order full pass (the dense _val_batches rules), padded
        # to the registry-wide val step budget
        v_ns = [int(self.val_sizes[int(c)]) for c in idx]
        v_idx, v_em, v_sm = engine.multi_client_index_plans(
            [[0]] * k, v_ns, self.batch_size, shuffle=False,
            pad_steps=self.val_steps,
        )
        vx, vy = self._gather_rows(self.source.client_val, idx, v_idx)
        val_batches = Batch(x=vx, y=vy, example_mask=v_em, step_mask=v_sm)
        mask = np.zeros((k,), np.float32)
        mask[:valid] = 1.0
        sample_counts = np.zeros((k,), np.float32)
        sample_counts[:valid] = self.train_sizes[idx[:valid]]
        val_counts = np.zeros((k,), np.float32)
        val_counts[:valid] = self.val_sizes[idx[:valid]]
        staged_bytes = sum(
            a.nbytes for a in jax.tree_util.tree_leaves(
                (batches, val_batches)
            )
        )
        return {
            "idx": idx, "valid": int(valid), "mask": mask,
            "sample_counts": sample_counts, "batches": batches,
            "val_batches": val_batches, "val_counts": val_counts,
            "staged_bytes": staged_bytes,
        }

    # -- chunked staging (R rounds per dispatch over the registry) -------
    def chunk_window(self, idx_list: Sequence[np.ndarray],
                     valid_list: Sequence[int], slots: int,
                     n_rounds: int) -> tuple[np.ndarray, int]:
        """The chunk's device-staged registry window: the sorted-unique
        union of every round's VALID sampled ids, padded to the fixed
        width ``W = min(N, n_rounds * slots)`` with the sentinel id ``N``.

        Sorted-ascending real ids first means ``searchsorted(window, id)``
        resolves every real id (and every pad slot, which repeats a real
        id) to a real window row in-graph; sentinel rows exist only to
        keep the window shape a function of (N, K, R) — they are never
        gathered (no cohort id maps to them) and never scattered (the
        in-graph scatter drops pad destinations)."""
        chosen = [
            np.asarray(idx, np.int64)[: int(v)]
            for idx, v in zip(idx_list, valid_list)
        ]
        real = (np.unique(np.concatenate(chosen)) if any(
            c.size for c in chosen
        ) else np.zeros((0,), np.int64))
        w = min(self.n_clients, int(n_rounds) * int(slots))
        if real.size > w:  # cannot happen: union of R draws of <= K ids
            raise ValueError(
                f"chunk window overflow: {real.size} unique ids > {w}"
            )
        out = np.full((w,), self.n_clients, np.int64)
        out[: real.size] = real
        return out, int(real.size)

    def gather_window(self, window_ids: np.ndarray) -> tuple[Any, Any | None]:
        """``[W, ...]`` host row trees for a chunk window (client
        ``TrainState`` rows + strategy rows or None). Sentinel entries
        resolve to fresh prototype rows — present for shape stability,
        never addressed by the compiled chunk."""
        return (self.gather_client_states(window_ids),
                self.gather_strategy_rows(window_ids))

    def stage_chunk(self, draws: Sequence[tuple[np.ndarray, int]],
                    base_entropy, start_round: int) -> dict:
        """Stack R rounds' ``stage_round`` tensors along a leading round
        axis (``batches [R, K, S, B, ...]``, ``mask [R, K]``, ...) for one
        chunked dispatch. Pure numpy like ``stage_round`` — safe on the
        prefetcher's worker thread."""
        rounds = [
            self.stage_round(idx, valid, base_entropy, start_round + i)
            for i, (idx, valid) in enumerate(draws)
        ]
        stack_trees = lambda key: jax.tree_util.tree_map(  # noqa: E731
            lambda *ls: np.stack(ls), *[r[key] for r in rounds]
        )
        return {
            "idx": np.stack([r["idx"] for r in rounds]),
            "valid": np.asarray([r["valid"] for r in rounds], np.int32),
            "mask": np.stack([r["mask"] for r in rounds]),
            "sample_counts": np.stack([r["sample_counts"] for r in rounds]),
            "val_counts": np.stack([r["val_counts"] for r in rounds]),
            "batches": stack_trees("batches"),
            "val_batches": stack_trees("val_batches"),
            "staged_bytes": sum(r["staged_bytes"] for r in rounds),
        }

    # -- abstract shapes (introspection: no staging, no device work) -----
    def _abstract_batch(self, steps: int, k: int, x_ex, y_ex) -> Batch:
        b = self.batch_size
        sds = lambda ex: jax.tree_util.tree_map(  # noqa: E731
            lambda s: jax.ShapeDtypeStruct((k, steps, b) + s.shape, s.dtype),
            ex,
        )
        return Batch(
            x=sds(x_ex), y=sds(y_ex),
            example_mask=jax.ShapeDtypeStruct((k, steps, b), np.float32),
            step_mask=jax.ShapeDtypeStruct((k, steps), np.float32),
        )

    def abstract_round_args(self, slots: int) -> dict:
        """ShapeDtypeStructs of one round's slot inputs — what the
        ``ProgramIntrospector`` lowers the slot programs against. By
        construction these shapes mention only (K, step budgets, batch,
        example shape) — never the registry size — which is the O(K)
        compiled-footprint claim the introspection tests pin."""
        f32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
        return {
            "batches": self._abstract_batch(
                self.train_steps, slots, self._x_example, self._y_example
            ),
            "val_batches": self._abstract_batch(
                self.val_steps, slots, self._x_example, self._y_example
            ),
            "mask": f32(slots),
            "sample_counts": f32(slots),
            "val_counts": f32(slots),
        }

    def abstract_chunk_args(self, slots: int, n_rounds: int) -> dict:
        """Stacked ``[R, ...]`` ShapeDtypeStructs of one chunked
        dispatch's per-round inputs plus the window-id shape — what the
        introspector lowers the cohort chunk scan against. Like
        :meth:`abstract_round_args`, nothing here mentions the registry
        size beyond the ``min(N, R*K)`` window cap: at ``N >= R*K`` the
        chunk program's cost/footprint is a function of (K, R, budgets)
        only."""
        aa = self.abstract_round_args(slots)
        k = int(n_rounds)
        stack = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda s: jax.ShapeDtypeStruct((k,) + s.shape, s.dtype), tree
        )
        w = min(self.n_clients, k * int(slots))
        return {
            "batches": stack(aa["batches"]),
            "val_batches": stack(aa["val_batches"]),
            "mask": stack(aa["mask"]),
            "sample_counts": stack(aa["sample_counts"]),
            "val_counts": stack(aa["val_counts"]),
            "window_ids": jax.ShapeDtypeStruct((w,), np.int32),
        }

    # -- checkpointing ---------------------------------------------------
    def export_rows(self) -> dict:
        """Durable registry payload: dirty ids + stacked row trees for
        both stores (PR 12 frame format trees; ids/counts land in the
        frame header via the checkpointer)."""
        c_ids, c_rows = self._client_store.export()
        s_ids, s_rows = self._strategy_store.export()
        return {"client_ids": c_ids, "client_rows": c_rows,
                "strategy_ids": s_ids, "strategy_rows": s_rows}

    def row_templates(self, n_client: int, n_strategy: int) -> dict:
        """Deserialization targets matching :meth:`export_rows` for the
        stored dirty counts."""
        out = {}
        if n_client:
            out["client_rows"] = self._client_store.stacked_template(
                self._client_proto, n_client
            )
        if n_strategy and self._has_strategy_rows:
            out["strategy_rows"] = self._strategy_store.stacked_template(
                self._strategy_proto, n_strategy
            )
        return out

    def load_rows(self, client_ids, client_rows, strategy_ids,
                  strategy_rows) -> None:
        self._client_store.load(np.asarray(client_ids, np.int64),
                                client_rows)
        if self._has_strategy_rows:
            self._strategy_store.load(
                np.asarray(strategy_ids, np.int64), strategy_rows
            )


class _SlotManagerView:
    """A slot-count view of the real client manager, used to re-bind
    wrapper strategies so their per-client server rows initialize at
    ``[slots]`` (the compiled program's shape) while the REAL manager —
    over the full registry — keeps doing the sampling. Delegates every
    other attribute (``fraction``, ``min_clients``) so setup-time
    validation (DP fraction checks) sees the true scheme."""

    def __init__(self, real_manager: Any, slots: int):
        self._real = real_manager
        self.n_clients = slots

    def __getattr__(self, name):
        return getattr(self._real, name)

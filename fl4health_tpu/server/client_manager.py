"""Client sampling managers — participation masks from PRNG keys.

Parity: /root/reference/fl4health/client_managers/ —
BaseFractionSamplingManager (base_sampling_manager.py:8),
PoissonSamplingClientManager (poisson_sampling_manager.py:11, per-client
Bernoulli, may return empty), FixedSamplingByFractionClientManager
(fixed_without_replacement_manager.py:11), FixedSamplingClientManager
(fixed_sampling_client_manager.py:6, caches its sample for FedDG-GA).

TPU-native design: a manager maps (rng, round) -> [n_clients] 0/1 mask; shapes
stay static so sampling composes with jit. "Empty cohort allowed" is a flag,
not an exception path.

Cohort-slot execution (``server/registry.py``) adds an index-plan view:
``sample_indices(rng, round, slots) -> ([slots] int32, valid)`` — the
ascending registry ids of the sampled clients, padded to a fixed slot
count — so a round over a million-client registry never materializes an
``[n_clients]`` mask on device. For FullParticipation / Poisson /
FixedSampling the two views are pinned coherent (``sample_indices``'
first ``valid`` entries are exactly ``np.nonzero(sample(rng, round))[0]``
under the same rng); ``FixedFractionManager`` trades that realization
coherence for an O(n)-cheap draw (see its ``sample_indices`` docstring) —
same distribution, same determinism, different subset.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from fl4health_tpu.core.types import PRNGKey


class CohortOverflowError(ValueError):
    """A sampling draw selected more clients than the configured cohort
    slots can hold (``CohortConfig(slots=K)``); raise rather than silently
    truncating the cohort — dropping sampled clients would bias both the
    trajectory and any DP accounting tied to the sampling fraction."""


def _fraction_floor(fraction: float, n: int) -> int:
    """``floor(fraction * n)`` with an epsilon guard: inexact binary
    products like ``0.7 * 10 == 6.999999999999999`` must floor to 7, not 6
    — without the guard the realized cohort silently undershoots the
    configured fraction on exactly the "clean" fractions users pick."""
    return int(math.floor(fraction * n + 1e-9))


def _pack_ids_in_graph(ids_sorted: jax.Array, valid: jax.Array,
                       slots: int) -> jax.Array:
    """In-graph mirror of ``_pack_indices``' padding rule: keep the first
    ``valid`` ascending ids, pad the rest with the first valid id (empty
    draws pad with 0). Traced ``valid`` means overflow cannot raise here —
    the host-side mirror (which stages every round's data) is the raising
    authority, and the chunk puller asserts both draws agree."""
    ids_sorted = ids_sorted.astype(jnp.int32)
    first = jnp.where(valid > 0, ids_sorted[0], 0).astype(jnp.int32)
    if ids_sorted.shape[0] < slots:
        ids_sorted = jnp.concatenate([
            ids_sorted,
            jnp.zeros((slots - ids_sorted.shape[0],), jnp.int32),
        ])
    keep = jnp.arange(slots, dtype=jnp.int32) < valid
    return jnp.where(keep, ids_sorted[:slots], first)


def _pack_indices(chosen: np.ndarray, slots: int,
                  scheme: str) -> tuple[np.ndarray, int]:
    """Pack a drawn id set into the fixed ``[slots]`` plan: ascending ids
    first, the remainder padded with the first valid id (slot 0's data is
    real-shaped; the pad slots carry participation weight 0). Empty draws
    pad with id 0."""
    chosen = np.asarray(chosen)
    valid = int(chosen.shape[0])
    if valid > slots:
        raise CohortOverflowError(
            f"{scheme} drew {valid} clients but the cohort has only "
            f"{slots} slots; raise CohortConfig(slots=...) above the "
            "scheme's worst-case draw (or lower its fraction)"
        )
    out = np.zeros((slots,), np.int32)
    out[:valid] = np.sort(chosen).astype(np.int32)
    if 0 < valid < slots:
        out[valid:] = out[0]
    return out, valid


class ClientManager:
    """Subclasses expose ``fraction`` — the configured per-round sampling
    fraction q — when the scheme has one; DP consumers (accountants, the
    DP-FedAvgM coefficient scaling) read it at setup so the q they account
    for is the q actually sampled."""

    def __init__(self, n_clients: int):
        self.n_clients = n_clients

    def sample(self, rng: PRNGKey, round_idx: int) -> jax.Array:
        raise NotImplementedError

    def sample_indices(self, rng: PRNGKey, round_idx: int,
                       slots: int) -> tuple[np.ndarray, int]:
        """Cohort-slot index plan: ``([slots] int32 registry ids, valid)``.

        Contract (pinned by tests/server/test_client_manager_properties.py):
        the first ``valid`` entries are exactly
        ``np.nonzero(sample(rng, round_idx))[0]`` — the same draw, viewed
        as ascending ids instead of a dense mask — and padding repeats the
        first valid id. Overflowing ``slots`` raises
        :class:`CohortOverflowError`. Subclasses override with vectorized
        draws; this default derives the plan from the dense mask so exotic
        managers stay coherent by construction."""
        mask = np.asarray(jax.device_get(self.sample(rng, round_idx)))
        return _pack_indices(
            np.nonzero(mask > 0)[0], slots, type(self).__name__
        )

    # In-graph cohort draw (the chunked-cohort scan's sampling primitive).
    # Managers that can express their draw as a pure jit-traceable function
    # of (rng, round) override ``draw_cohort(rng, round_idx, slots) ->
    # ([slots] int32 ascending ids, int32 valid)`` pinned BIT-IDENTICAL to
    # ``sample_indices`` under the same (rng, round, slots). The base class
    # deliberately does not define it: stateful or exotic managers without
    # a pure draw demote cohort runs to the pipelined path (the simulation
    # checks ``getattr(manager, "draw_cohort", None)``).

    def sample_all(self) -> jax.Array:
        return jnp.ones((self.n_clients,), jnp.float32)


class FullParticipationManager(ClientManager):
    """sample_all semantics — every client every round."""

    fraction = 1.0

    def sample(self, rng, round_idx):
        return self.sample_all()

    def sample_indices(self, rng, round_idx, slots):
        return _pack_indices(
            np.arange(self.n_clients, dtype=np.int32), slots,
            type(self).__name__,
        )

    def draw_cohort(self, rng, round_idx, slots):
        # deterministic and rng-free like the host view; overflow is a
        # STATIC fact here (n and slots are both trace-time constants)
        if self.n_clients > slots:
            raise CohortOverflowError(
                f"FullParticipationManager needs slots >= n_clients "
                f"({self.n_clients}); got slots={slots}"
            )
        sl = jnp.arange(slots, dtype=jnp.int32)
        ids = jnp.where(sl < self.n_clients, sl, 0)
        return ids, jnp.asarray(self.n_clients, jnp.int32)


class FixedFractionManager(ClientManager):
    """Sample floor(fraction * n) clients uniformly without replacement,
    re-drawn each round (FixedSamplingByFractionClientManager)."""

    def __init__(self, n_clients: int, fraction: float, min_clients: int = 1):
        super().__init__(n_clients)
        if min_clients > n_clients:
            raise ValueError(
                f"min_clients={min_clients} exceeds n_clients={n_clients}"
            )
        # the CONFIGURED q (what a DP accountant composes with); the realized
        # count k may round/floor away from q*n (and never exceeds n).
        # Epsilon-safe floor: int() truncation floored 0.7*10 -> 6.
        self.fraction = fraction
        self.min_clients = min_clients
        self.k = min(
            n_clients, max(min_clients, _fraction_floor(fraction, n_clients))
        )

    def sample(self, rng, round_idx):
        rng = jax.random.fold_in(rng, round_idx)
        perm = jax.random.permutation(rng, self.n_clients)
        mask = jnp.zeros((self.n_clients,), jnp.float32)
        return mask.at[perm[: self.k]].set(1.0)

    def sample_indices(self, rng, round_idx, slots):
        # The index view draws the k clients with the SMALLEST uniform
        # values — the classic without-replacement construction,
        # distribution-identical to the dense mask's permutation draw but
        # O(n) uniform bits + one argpartition instead of XLA's full
        # random sort (55 ms -> ~1 ms at n=100k, the difference between a
        # hidden and an exposed staging cost). The tradeoff, pinned by
        # tests: FixedFractionManager's index view is its OWN
        # deterministic stream — same (rng, round) always yields the same
        # cohort, but not the same SUBSET the dense permutation mask
        # realizes (the dense draw cannot change: cohort=None trajectories
        # are pinned bit-identical across releases).
        rng = jax.random.fold_in(rng, round_idx)
        u = np.asarray(jax.random.uniform(rng, (self.n_clients,)))
        if self.k >= self.n_clients:
            chosen = np.arange(self.n_clients)
        else:
            chosen = np.argpartition(u, self.k)[: self.k]
        return _pack_indices(chosen, slots, type(self).__name__)

    def draw_cohort(self, rng, round_idx, slots):
        # in-graph mirror of the index view: the k clients with the
        # SMALLEST uniforms, from the SAME per-client uniform bits (jax
        # PRNG output is jit-invariant), so ids match sample_indices'
        # argpartition set exactly — the k-smallest set of distinct floats
        # is unique. k is static, so overflow raises at trace time.
        if self.k > slots:
            raise CohortOverflowError(
                f"FixedFractionManager draws k={self.k} clients but the "
                f"cohort has only {slots} slots"
            )
        rng = jax.random.fold_in(rng, round_idx)
        if self.k >= self.n_clients:
            chosen = jnp.arange(self.n_clients, dtype=jnp.int32)
        else:
            u = jax.random.uniform(rng, (self.n_clients,))
            chosen = jnp.sort(jnp.argsort(u)[: self.k]).astype(jnp.int32)
        return (
            _pack_ids_in_graph(chosen, jnp.asarray(self.k, jnp.int32), slots),
            jnp.asarray(self.k, jnp.int32),
        )


class PoissonSamplingManager(ClientManager):
    """Independent Bernoulli(fraction) per client — matches the DP accounting
    assumptions; cohort can legitimately be empty.

    ``min_clients`` (default 0 — the legacy, accounting-faithful behavior)
    optionally tops the cohort up to a floor: the clients with the smallest
    uniform draws are forced in, so the top-up is deterministic under the
    same rng and every Bernoulli success is always kept. A non-zero floor
    breaks the pure-Poisson assumption DP accountants compose with —
    useful for robustness experiments, not for accounting."""

    def __init__(self, n_clients: int, fraction: float, min_clients: int = 0):
        super().__init__(n_clients)
        if not 0 <= min_clients <= n_clients:
            raise ValueError(
                f"min_clients must be in [0, {n_clients}]; got {min_clients}"
            )
        self.fraction = fraction
        self.min_clients = min_clients

    def sample(self, rng, round_idx):
        rng = jax.random.fold_in(rng, round_idx)
        u = jax.random.uniform(rng, (self.n_clients,))
        mask = u < self.fraction
        if self.min_clients > 0:
            # force the min_clients smallest draws in: a superset of the
            # Bernoulli successes (u < fraction implies smallest-ranked),
            # one sort, static shapes
            threshold = jnp.sort(u)[self.min_clients - 1]
            mask = mask | (u <= threshold)
        return mask.astype(jnp.float32)

    def sample_indices(self, rng, round_idx, slots):
        # the SAME per-client uniform draw as the dense mask (one
        # vectorized op); only the selected ids leave the host
        rng = jax.random.fold_in(rng, round_idx)
        u = np.asarray(jax.random.uniform(rng, (self.n_clients,)))
        mask = u < self.fraction
        if self.min_clients > 0:
            threshold = np.sort(u)[self.min_clients - 1]
            mask = mask | (u <= threshold)
        return _pack_indices(
            np.nonzero(mask)[0], slots, type(self).__name__
        )

    def draw_cohort(self, rng, round_idx, slots):
        # the bucket-shaped Poisson-under-padding draw: same per-client
        # uniform bits as the host views, selected ids sorted to the front
        # via a sentinel-keyed sort. ``valid`` is data-dependent, so an
        # overflowing draw clamps here instead of raising — the host
        # mirror staging the same round's data raises CohortOverflowError
        # first, and the chunk puller's draw-parity assert backstops it.
        n = self.n_clients
        rng = jax.random.fold_in(rng, round_idx)
        u = jax.random.uniform(rng, (n,))
        mask = u < self.fraction
        if self.min_clients > 0:
            threshold = jnp.sort(u)[self.min_clients - 1]
            mask = mask | (u <= threshold)
        key = jnp.where(mask, jnp.arange(n, dtype=jnp.int32), n)
        ids_sorted = jnp.sort(key)
        valid = jnp.minimum(
            jnp.sum(mask).astype(jnp.int32), jnp.asarray(slots, jnp.int32)
        )
        return _pack_ids_in_graph(ids_sorted, valid, slots), valid


class FixedSamplingManager(ClientManager):
    """Draw once, reuse every round (FedDG-GA's reproducibility requirement,
    fixed_sampling_client_manager.py:6)."""

    def __init__(self, n_clients: int, fraction: float = 1.0):
        super().__init__(n_clients)
        self.fraction = fraction
        # epsilon-safe floor (see _fraction_floor): int() truncation
        # undershot clean fractions like 0.7*10
        self.k = max(1, _fraction_floor(fraction, n_clients))
        self._cached: jax.Array | None = None

    def sample(self, rng, round_idx):
        if self._cached is None:
            perm = jax.random.permutation(rng, self.n_clients)
            mask = jnp.zeros((self.n_clients,), jnp.float32)
            self._cached = mask.at[perm[: self.k]].set(1.0)
        return self._cached

    def sample_indices(self, rng, round_idx, slots):
        # coherence with the cached-draw semantics: the FIRST call (either
        # view) fixes the sample; both views then report the same ids
        if self._cached is None:
            self.sample(rng, round_idx)
        mask = np.asarray(self._cached)
        return _pack_indices(
            np.nonzero(mask > 0)[0], slots, type(self).__name__
        )

    def reset_sample(self):
        self._cached = None

"""Client sampling managers — participation masks from PRNG keys.

Parity: /root/reference/fl4health/client_managers/ —
BaseFractionSamplingManager (base_sampling_manager.py:8),
PoissonSamplingClientManager (poisson_sampling_manager.py:11, per-client
Bernoulli, may return empty), FixedSamplingByFractionClientManager
(fixed_without_replacement_manager.py:11), FixedSamplingClientManager
(fixed_sampling_client_manager.py:6, caches its sample for FedDG-GA).

TPU-native design: a manager maps (rng, round) -> [n_clients] 0/1 mask; shapes
stay static so sampling composes with jit. "Empty cohort allowed" is a flag,
not an exception path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fl4health_tpu.core.types import PRNGKey


class ClientManager:
    """Subclasses expose ``fraction`` — the configured per-round sampling
    fraction q — when the scheme has one; DP consumers (accountants, the
    DP-FedAvgM coefficient scaling) read it at setup so the q they account
    for is the q actually sampled."""

    def __init__(self, n_clients: int):
        self.n_clients = n_clients

    def sample(self, rng: PRNGKey, round_idx: int) -> jax.Array:
        raise NotImplementedError

    def sample_all(self) -> jax.Array:
        return jnp.ones((self.n_clients,), jnp.float32)


class FullParticipationManager(ClientManager):
    """sample_all semantics — every client every round."""

    fraction = 1.0

    def sample(self, rng, round_idx):
        return self.sample_all()


class FixedFractionManager(ClientManager):
    """Sample floor(fraction * n) clients uniformly without replacement,
    re-drawn each round (FixedSamplingByFractionClientManager)."""

    def __init__(self, n_clients: int, fraction: float, min_clients: int = 1):
        super().__init__(n_clients)
        if min_clients > n_clients:
            raise ValueError(
                f"min_clients={min_clients} exceeds n_clients={n_clients}"
            )
        # the CONFIGURED q (what a DP accountant composes with); the realized
        # count k may round/floor away from q*n (and never exceeds n)
        self.fraction = fraction
        self.min_clients = min_clients
        self.k = min(n_clients, max(min_clients, int(fraction * n_clients)))

    def sample(self, rng, round_idx):
        rng = jax.random.fold_in(rng, round_idx)
        perm = jax.random.permutation(rng, self.n_clients)
        mask = jnp.zeros((self.n_clients,), jnp.float32)
        return mask.at[perm[: self.k]].set(1.0)


class PoissonSamplingManager(ClientManager):
    """Independent Bernoulli(fraction) per client — matches the DP accounting
    assumptions; cohort can legitimately be empty.

    ``min_clients`` (default 0 — the legacy, accounting-faithful behavior)
    optionally tops the cohort up to a floor: the clients with the smallest
    uniform draws are forced in, so the top-up is deterministic under the
    same rng and every Bernoulli success is always kept. A non-zero floor
    breaks the pure-Poisson assumption DP accountants compose with —
    useful for robustness experiments, not for accounting."""

    def __init__(self, n_clients: int, fraction: float, min_clients: int = 0):
        super().__init__(n_clients)
        if not 0 <= min_clients <= n_clients:
            raise ValueError(
                f"min_clients must be in [0, {n_clients}]; got {min_clients}"
            )
        self.fraction = fraction
        self.min_clients = min_clients

    def sample(self, rng, round_idx):
        rng = jax.random.fold_in(rng, round_idx)
        u = jax.random.uniform(rng, (self.n_clients,))
        mask = u < self.fraction
        if self.min_clients > 0:
            # force the min_clients smallest draws in: a superset of the
            # Bernoulli successes (u < fraction implies smallest-ranked),
            # one sort, static shapes
            threshold = jnp.sort(u)[self.min_clients - 1]
            mask = mask | (u <= threshold)
        return mask.astype(jnp.float32)


class FixedSamplingManager(ClientManager):
    """Draw once, reuse every round (FedDG-GA's reproducibility requirement,
    fixed_sampling_client_manager.py:6)."""

    def __init__(self, n_clients: int, fraction: float = 1.0):
        super().__init__(n_clients)
        self.fraction = fraction
        self.k = max(1, int(fraction * n_clients))
        self._cached: jax.Array | None = None

    def sample(self, rng, round_idx):
        if self._cached is None:
            perm = jax.random.permutation(rng, self.n_clients)
            mask = jnp.zeros((self.n_clients,), jnp.float32)
            self._cached = mask.at[perm[: self.k]].set(1.0)
        return self._cached

    def reset_sample(self):
        self._cached = None

"""Async round pipeline — overlap host work with device execution.

Round-5 VERDICT measured ~1.5 s of host Python per round against ~0.1 s of
device busy time: the TPU sat idle while the driver loop did failure
screening, checkpointing, record construction and reporter I/O between
dispatches. FedJAX (arXiv:2108.02117) wins FL-simulation throughput by
keeping the accelerator saturated across the round loop; these two helpers
are the host half of that design for ``FederatedSimulation.fit``:

- :class:`RoundConsumer` — a bounded single-worker queue that executes each
  round's host-side epilogue (failure policy, checkpoint decisions,
  ``RoundRecord`` construction, reporter fan-out, in-graph telemetry
  recording + the ``HealthWatchdog`` screen) in a background thread
  while the device already runs the next round. FIFO ordering is guaranteed
  (one worker), ``flush()`` is a completion barrier, and the first exception
  raised by round *r*'s epilogue (e.g. ``ClientFailuresError`` or the
  watchdog's ``TrainingHealthError``) is re-raised into the producer at the
  next ``submit``/``flush``. The round's ``RoundTelemetry`` pytree rides the
  consumer's single fused device->host transfer — enabling telemetry adds
  zero producer-side syncs.

- :class:`RoundPrefetcher` — builds round *r+1*'s host-side index plan
  (pure numpy) and stages its gathered batches on device while round *r*
  executes. If ``set_train_data`` swapped the data stacks after staging
  (a ``train_data_provider`` refresh), the staged gather is discarded and
  re-issued against the fresh stacks — the *plan* (index math) is still
  reused, so only the cheap device gather is re-paid.

Neither helper touches device buffers that donation could invalidate: the
consumer receives *result* arrays (fresh outputs, never donated back into a
later round) or device-side snapshot copies; the prefetcher reads only the
immutable per-round plan inputs and the data stacks it re-validates by
identity.

Buffered-async runs (``server/async_schedule.py``) reuse both helpers with
shifted indices: buffer-fill event *e* restarts its consumed clients on
data plan ``e+1``, so the async producer schedules/takes plan index
``e+1`` while event *e* executes (the prologue takes plan 1). The plan
index IS the prefetcher's contract — it never assumes indices are round
numbers, only that ``take(i)`` follows ``schedule(i)`` — which is what
lets one prefetcher serve both cadences.
"""

from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any

from fl4health_tpu.core.workqueue import SingleWorkerQueue


class RoundConsumer(SingleWorkerQueue):
    """Single-worker FIFO executor for per-round host epilogues.

    ``maxsize`` bounds how many rounds of host work may be pending — the
    producer blocks on ``submit`` once the device is that far ahead, so host
    memory (result trees, checkpoint snapshots) stays bounded. Queue,
    ordering, flush-barrier and exception contracts come from
    :class:`~fl4health_tpu.core.workqueue.SingleWorkerQueue`.
    """

    def __init__(self, maxsize: int = 2, name: str = "fl-round-consumer"):
        super().__init__(maxsize=maxsize, name=name)
        # newest round whose epilogue FINISHED (not merely was submitted) —
        # the flight recorder's verdict quotes this so a postmortem can
        # distinguish "round r recorded" from "round r+1 died in flight"
        self.last_completed_round: int | None = None

    def submit_round(self, round_idx: int, job) -> None:
        """Submit one round's host epilogue, tracking its completion in
        ``last_completed_round`` once the job ran (worker thread, FIFO —
        the value is monotone)."""

        def _job():
            job()
            self.last_completed_round = int(round_idx)

        self.submit(_job)


class RoundPrefetcher:
    """Stage round *r+1*'s batches while round *r* executes.

    ``schedule(r)`` computes the host index plan (numpy) and dispatches the
    device gather in a worker thread; ``take(r)`` returns the staged batches,
    falling back to synchronous construction on a miss. Staleness rule: if
    the simulation's train stacks were swapped (``set_train_data``) between
    staging and ``take``, the plan is re-gathered against the fresh stacks —
    correctness over reuse.

    Under a device mesh the staged batch stack is ``device_put`` onto the
    builder's clients-axis sharding as part of staging — the clients-axis
    split of round *r+1*'s data overlaps round *r*'s execution instead of
    riding the dispatch as an implicit reshard. Without a mesh, staging is
    exactly the pre-mesh behavior.
    """

    def __init__(self, sim: Any):
        self._sim = sim
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="fl-round-prefetch"
        )
        self._pending: tuple[int, Future] | None = None

    def _place(self, batches):
        # one placement rule everywhere: the builder's put + the builder's
        # own clients sharding (no-op when unsharded), so staging policy
        # can't drift from the other device_put sites.
        #
        # Thread-safety note: this device_put runs on the worker thread
        # while the main thread dispatches the current round's program.
        # That is safe where eager multi-device COMPUTATIONS are not —
        # an eager sharded gather here deadlocks against the concurrent
        # dispatch (rendezvous-synchronized executable launches from two
        # threads; see the train-bank comment in simulation.__init__) —
        # because device_put issues independent per-device transfers, not
        # a collective program. Pinned green on the 8-device virtual mesh
        # that reproduces the gather deadlock; if a real multi-chip
        # backend ever hangs here, fall back to placing in take() on the
        # caller's thread at the cost of the staging overlap.
        builder = self._sim._program_builder
        return builder.put(batches, builder.client_sharding())

    def schedule(self, round_idx: int) -> None:
        sim = self._sim
        if getattr(sim, "_cohort_active", False):
            # cohort-slot staging: sample the round's cohort ids, gather
            # its [K, ...] slot tensors from the host registry and
            # device_put them (sharded under a mesh) — all of it a pure
            # function of (rng, round, registry data), so it runs here
            # while the previous round executes. Per-client STATE is
            # deliberately absent (it depends on the previous round's
            # registry scatter — the producer gathers it after its gate).
            self._pending = (
                round_idx,
                self._pool.submit(sim._stage_cohort_round, round_idx),
            )
            return
        # capture the stacks NOW: take() compares by identity to detect a
        # mid-flight set_train_data swap
        x_stack, y_stack = sim._x_train_stack, sim._y_train_stack

        def build():
            from fl4health_tpu.clients import engine

            plan = sim._round_plan(round_idx)
            batches = self._place(
                engine.gather_batches(x_stack, y_stack, *plan)
            )
            return (x_stack, y_stack), plan, batches

        self._pending = (round_idx, self._pool.submit(build))

    def schedule_chunk(self, start_round: int, k: int) -> None:
        """Cohort chunked route: stage chunk ``[start_round,
        start_round+k)``'s sampled draws, stacked slot tensors and window
        ids on the worker thread while the previous chunk's device work
        runs — the double-buffered half of the in-graph window exchange.
        Window STATE rows are deliberately absent: they have a
        read-after-write dependency on the previous chunk's registry
        scatter, so the driver gathers them on its own thread after it."""
        sim = self._sim
        self._pending = (
            ("chunk", start_round),
            self._pool.submit(sim._stage_cohort_chunk, start_round, k),
        )

    def take_chunk(self, start_round: int, k: int):
        """Staged chunk tensors from :meth:`schedule_chunk`; synchronous
        staging on a miss (first chunk, or a resume realigned the
        boundaries)."""
        sim = self._sim
        pending, self._pending = self._pending, None
        if pending is not None and pending[0] == ("chunk", start_round):
            return pending[1].result()
        return sim._stage_cohort_chunk(start_round, k)

    def take(self, round_idx: int):
        sim = self._sim
        pending, self._pending = self._pending, None
        if getattr(sim, "_cohort_active", False):
            if pending is not None and pending[0] == round_idx:
                return pending[1].result()
            return sim._stage_cohort_round(round_idx)
        if pending is None or pending[0] != round_idx:
            return self._place(sim._round_batches(round_idx))
        (x_stack, y_stack), plan, batches = pending[1].result()
        if x_stack is sim._x_train_stack and y_stack is sim._y_train_stack:
            return batches
        # data refreshed after staging: same plan, fresh gather
        from fl4health_tpu.clients import engine

        return self._place(engine.gather_batches(
            sim._x_train_stack, sim._y_train_stack, *plan
        ))

    def close(self) -> None:
        self._pending = None
        self._pool.shutdown(wait=False, cancel_futures=True)

"""Buffered-async scheduling — round cadence set by arrival rate, not the tail.

PR 5's quorum/circuit-breaker work still ran SYNCHRONOUS rounds: wall time
per round is ``max_c T_c``, the compute time of the slowest surviving
client — exactly the tail cost CLIP (arXiv:2510.16694) identifies as
dominant in secure FL deployments, and the barrier FedBuff (Nguyen et al.,
arXiv:2106.06639) removes. This module is the host half of the repo's
FedBuff-style mode: clients draw deterministic, seeded compute times on a
VIRTUAL clock, the server aggregates as soon as a buffer of ``K`` updates
has arrived, and stale updates are staleness-discounted against the server
version they trained from.

The critical design decision: the async schedule is resolved to a STATIC
EVENT PLAN here, at dispatch time. Arrival order, staleness and cadence
are a pure function of ``(AsyncConfig.seed, FaultPlan, cohort, K)`` — a
priority-queue simulation over the virtual clock, no wall-clock sleeps, no
threads. The resulting ``[events, clients]`` arrival/staleness arrays feed
the compiled async round programs (``server/simulation.py``) as plain jit
inputs, so the whole buffered-async run still executes as compiled round
programs — an in-graph scan over buffer-fill events on the chunked path,
one dispatch per event on the pipelined path — and the same plan replays
bit-identically on both.

Process semantics (one client = one row of the stacked cohort):

- At virtual t=0 every client pulls server version 0 and starts training;
  client ``c``'s attempt on data-plan ``p`` takes
  ``base_compute_s * jitter(seed, c, p) * slow_factor(fault_plan, c, p)``
  virtual seconds (``kind="slow"`` faults, resilience/faults.py).
- Finished updates queue in the server buffer; when the ``K``-th arrives
  the server aggregates those ``K`` (event ``e``, producing version
  ``e``), each discounted by ``1/(1+staleness)^exponent`` where staleness
  counts server versions since that client pulled.
- Consumed clients immediately pull the fresh version and restart; clients
  still training run straight through the event (no barrier).

With ``K = cohort`` and no slow faults every event consumes the whole
cohort at staleness 0 — the plan degenerates to the synchronous schedule,
which is how the simulation pins ``async == sync`` bit-identically.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq

import numpy as np

__all__ = [
    "AsyncConfig",
    "AsyncEventPlan",
    "RegistryEventPlan",
    "build_event_plan",
    "build_registry_event_plan",
    "plan_fingerprint",
    "plan_prefix_fingerprints",
    "staleness_discount",
    "sync_round_times",
]


@dataclasses.dataclass(frozen=True)
class AsyncConfig:
    """Static recipe for the buffered-async mode.

    buffer_size:        K — updates the server buffers before aggregating.
    staleness_exponent: discount ``1/(1+s)^exponent`` (0.5 = the FedBuff
                        paper's ``1/sqrt(1+s)``; 0.0 disables discounting).
    max_staleness:      updates staler than this aggregate with weight 0
                        (still counted/arrived — their client restarts);
                        None = no cap.
    base_compute_s:     nominal virtual compute time of one local-training
                        attempt (the unit every cadence number is in).
    compute_jitter:     per-(client, attempt) multiplicative jitter drawn
                        uniformly from ``[1-j, 1+j]`` — breaks arrival
                        ties so buffer fills are not degenerate lockstep;
                        0.0 keeps every honest client identical.
    seed:               stream for the jitter draws (independent of the
                        FaultPlan seed).
    """

    buffer_size: int
    staleness_exponent: float = 0.5
    max_staleness: int | None = None
    base_compute_s: float = 1.0
    compute_jitter: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.buffer_size < 1:
            raise ValueError(
                f"buffer_size must be >= 1; got {self.buffer_size}"
            )
        if self.staleness_exponent < 0:
            raise ValueError("staleness_exponent must be >= 0")
        if self.max_staleness is not None and self.max_staleness < 0:
            raise ValueError("max_staleness must be >= 0 (or None)")
        if not self.base_compute_s > 0:
            raise ValueError("base_compute_s must be > 0")
        if not 0.0 <= self.compute_jitter < 1.0:
            raise ValueError("compute_jitter must be in [0, 1)")

    def describe(self) -> dict:
        """JSON-able identity for the run manifest's config hash."""
        return {
            "buffer_size": self.buffer_size,
            "staleness_exponent": self.staleness_exponent,
            "max_staleness": self.max_staleness,
            "base_compute_s": self.base_compute_s,
            "compute_jitter": self.compute_jitter,
            "seed": self.seed,
        }


@dataclasses.dataclass(frozen=True)
class AsyncEventPlan:
    """The resolved static schedule of one buffered-async run.

    arrivals:    [E, C] float32 — 1.0 where client c's update is consumed
                 at event e (exactly ``buffer_size`` ones per row).
    staleness:   [E, C] float32 — server versions elapsed since the
                 arriving client pulled (0 where not arriving).
    event_times: [E] float64 — virtual wall time of each aggregation; the
                 successive differences ARE the async round cadence.
    """

    arrivals: np.ndarray
    staleness: np.ndarray
    event_times: np.ndarray

    @property
    def n_events(self) -> int:
        return int(self.arrivals.shape[0])

    @property
    def n_clients(self) -> int:
        return int(self.arrivals.shape[1])

    def cadences(self) -> np.ndarray:
        """[E] virtual seconds between consecutive aggregations (event 0
        measured from t=0)."""
        return np.diff(self.event_times, prepend=0.0)

    def summarize_event(self, e: int) -> dict:
        """Host facts about one event for the ``round`` JSONL record."""
        arr = self.arrivals[e] > 0
        stal = self.staleness[e][arr]
        return {
            "async_buffer": int(arr.sum()),
            "staleness_mean": float(stal.mean()) if stal.size else 0.0,
            "staleness_max": float(stal.max()) if stal.size else 0.0,
            "async_virtual_time_s": float(self.event_times[e]),
            "async_cadence_vs": float(self.cadences()[e]),
        }


@dataclasses.dataclass(frozen=True)
class RegistryEventPlan(AsyncEventPlan):
    """An :class:`AsyncEventPlan` whose ``C`` axis is COHORT SLOTS over a
    client registry rather than a fixed dense cohort (server/registry.py).

    The virtual-clock process is identical — slots draw compute times,
    fill the buffer, restart on consume — but each slot is OCCUPIED by a
    registry client, and a consumed slot hands its seat to a fresh client
    drawn deterministically from the currently-unseated pool. ``slot_ids``
    row ``e`` is the occupancy the restart wave of event ``e`` trains
    under (row 0 = the initial occupancy the prologue trains under), so
    the host stages event ``e``'s restart batches for ``slot_ids[e]`` and
    scatters the evicted occupants' rows back to the registry.

    With ``slots == registry_size`` the unseated pool is empty, occupancy
    is the identity forever, and the plan degenerates to the plain
    :class:`AsyncEventPlan` over the full registry — which is how the
    async-over-registry vs sync parity smoke pins the composition.

    slot_ids: [E+1, K] int64 — registry id seated in each slot per wave.
    """

    slot_ids: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 0), np.int64)
    )


def plan_prefix_fingerprints(plan: AsyncEventPlan) -> list[str]:
    """Per-event prefix digests of a static event plan: entry ``e-1`` is a
    short hash over events ``1..e``'s arrivals, staleness and virtual
    times. A checkpoint written after event ``e`` stores entry ``e-1``, so
    a resume can verify it is splicing state into the SAME arrival
    schedule (AsyncConfig seed / FaultPlan / cohort / buffer_size all feed
    the plan, so any drift changes the digest). Incremental sha256 — one
    pass over the plan for all E prefixes."""
    h = hashlib.sha256()
    out: list[str] = []
    arrivals = np.ascontiguousarray(plan.arrivals, np.float32)
    staleness = np.ascontiguousarray(plan.staleness, np.float32)
    times = np.ascontiguousarray(plan.event_times, np.float64)
    slot_ids = getattr(plan, "slot_ids", None)
    if slot_ids is not None and slot_ids.size:
        slot_ids = np.ascontiguousarray(slot_ids, np.int64)
    else:
        slot_ids = None
    for e in range(plan.n_events):
        h.update(arrivals[e].tobytes())
        h.update(staleness[e].tobytes())
        h.update(times[e].tobytes())
        if slot_ids is not None:
            # registry plans fold the post-event occupancy too: a resume
            # must splice into the same SEATING, not just the same cadence
            h.update(slot_ids[e + 1].tobytes())
        out.append(h.copy().hexdigest()[:16])
    return out


def plan_fingerprint(plan: AsyncEventPlan, n_events: int) -> str:
    """The prefix digest over the first ``n_events`` events (see
    :func:`plan_prefix_fingerprints`); empty-prefix digest for 0."""
    if n_events < 0 or n_events > plan.n_events:
        raise ValueError(
            f"n_events must be in [0, {plan.n_events}]; got {n_events}"
        )
    if n_events == 0:
        return hashlib.sha256().hexdigest()[:16]
    return plan_prefix_fingerprints(plan)[n_events - 1]


def staleness_discount(staleness, exponent=0.5,
                       max_staleness: int | None = None):
    """Aggregation weight for an update ``staleness`` versions old:
    ``1/(1+s)^exponent``, hard-zeroed past ``max_staleness``. Works on
    numpy arrays and traced jax arrays alike (pure arithmetic), and
    ``exponent`` itself may be a traced f32 scalar — the async round
    programs feed it as a program INPUT so an exponent sweep never
    recompiles (fl4health_tpu/sweep/ hoisting)."""
    if isinstance(exponent, (int, float)):
        exponent = float(exponent)
    w = (1.0 + staleness) ** (-exponent)
    if max_staleness is not None:
        w = w * (staleness <= max_staleness)
    return w


def _attempt_times(config: AsyncConfig, n_clients: int, n_plans: int,
                   fault_plan=None) -> np.ndarray:
    """[n_plans, C] virtual compute time of each (data-plan, client)
    training attempt — base x jitter x slow-fault factor. Plan indices are
    1-based (plan p is row p-1), matching the simulation's round plans."""
    times = np.full((n_plans, n_clients), float(config.base_compute_s))
    if config.compute_jitter > 0:
        j = config.compute_jitter
        for p in range(1, n_plans + 1):
            # seeded per (seed, plan), one [C] vector per plan:
            # deterministic across runs/platforms (PCG64) and O(plans)
            # generator constructions — a per-(client, plan) generator
            # would cost seconds of host time at thousands of clients
            rng = np.random.default_rng([config.seed, p])
            times[p - 1] *= rng.uniform(1.0 - j, 1.0 + j, size=n_clients)
    if fault_plan is not None and getattr(fault_plan, "slow_faults", ()):
        for p in range(1, n_plans + 1):
            times[p - 1] *= fault_plan.compute_time_factors(p, n_clients)
    return times


def build_event_plan(
    config: AsyncConfig,
    n_events: int,
    n_clients: int,
    fault_plan=None,
) -> AsyncEventPlan:
    """Simulate the buffered-async process on the virtual clock and return
    the static event plan the compiled round programs consume.

    Priority-queue over (finish_time, client_id) — ties resolve by client
    id, so the plan is exactly reproducible. Clients consumed at event
    ``e`` restart at the event's time on data plan ``e+1`` (the plan their
    NEXT update trains on), which is what makes the ``K = cohort`` plan
    collapse to the synchronous round schedule."""
    if n_events < 1:
        raise ValueError(f"n_events must be >= 1; got {n_events}")
    if n_clients < 1:
        raise ValueError(f"n_clients must be >= 1; got {n_clients}")
    k = config.buffer_size
    if k > n_clients:
        raise ValueError(
            f"buffer_size={k} exceeds the cohort ({n_clients} clients): "
            "the buffer could never fill"
        )
    # plan indices in play: the prologue trains on plan 1; a restart at
    # event e trains on plan e+1 — so at most n_events+1 plans are drawn
    times = _attempt_times(config, n_clients, n_events + 1, fault_plan)

    arrivals = np.zeros((n_events, n_clients), np.float32)
    staleness = np.zeros((n_events, n_clients), np.float32)
    event_times = np.zeros((n_events,), np.float64)
    pulled = np.zeros((n_clients,), np.int64)  # server version each holds
    heap: list[tuple[float, int]] = [
        (times[0, c], c) for c in range(n_clients)
    ]
    heapq.heapify(heap)
    for e in range(n_events):
        batch = [heapq.heappop(heap) for _ in range(k)]
        t_event = max(t for t, _ in batch)
        event_times[e] = t_event
        for _, c in batch:
            arrivals[e, c] = 1.0
            staleness[e, c] = float(e - pulled[c])
            pulled[c] = e + 1
            heapq.heappush(heap, (t_event + times[e + 1, c], c))
    return AsyncEventPlan(
        arrivals=arrivals, staleness=staleness, event_times=event_times
    )


def build_registry_event_plan(
    config: AsyncConfig,
    n_events: int,
    slots: int,
    registry_size: int,
    fault_plan=None,
) -> RegistryEventPlan:
    """Resolve the buffered-async process over a client REGISTRY: the
    slot-level schedule is exactly :func:`build_event_plan` (same seeds,
    same heap, same cadence — a slot is the unit that draws compute time
    and fills the buffer), plus a deterministic occupancy ledger mapping
    each slot to the registry client seated in it per restart wave.

    Seating rule: slots start occupied by registry ids ``0..K-1``; when a
    slot's update is consumed at event ``e`` it hands the seat to the
    lowest-index draw from the unseated pool (seeded per event by
    ``default_rng([seed, 104729, e])``, without replacement across that
    event's consumed slots, in ascending slot order). When the pool is
    empty (``slots == registry_size``) every occupant keeps its seat and
    the plan degenerates to the dense one. Staleness bookkeeping is
    per-SLOT: the new occupant pulls the fresh server version at the swap,
    so discounting semantics are unchanged."""
    if slots > registry_size:
        raise ValueError(
            f"cohort slots ({slots}) exceed the registry "
            f"({registry_size} clients): every seat needs an occupant"
        )
    base = build_event_plan(config, n_events, slots, fault_plan)
    slot_ids = np.zeros((n_events + 1, slots), np.int64)
    occ = np.arange(slots, dtype=np.int64)
    seated = np.zeros((registry_size,), bool)
    seated[occ] = True
    slot_ids[0] = occ
    for e in range(n_events):
        consumed = np.nonzero(base.arrivals[e] > 0)[0]
        pool = np.nonzero(~seated)[0]
        if pool.size:
            rng = np.random.default_rng([config.seed, 104729, e])
            take = min(pool.size, consumed.size)
            drawn = rng.choice(pool, size=take, replace=False)
            for s, new_id in zip(consumed[:take], drawn):
                seated[occ[s]] = False
                seated[new_id] = True
                occ[s] = new_id
        slot_ids[e + 1] = occ
    return RegistryEventPlan(
        arrivals=base.arrivals, staleness=base.staleness,
        event_times=base.event_times, slot_ids=slot_ids,
    )


def sync_round_times(
    config: AsyncConfig,
    n_rounds: int,
    n_clients: int,
    fault_plan=None,
) -> np.ndarray:
    """[n_rounds] virtual wall time of each SYNCHRONOUS round under the
    same compute-time model — ``max_c T_c(round)``, the barrier cost. The
    bench's sync-vs-async cadence comparison reads both sides from one
    model, so the headline ratio is apples-to-apples by construction."""
    if n_rounds < 1:
        raise ValueError(f"n_rounds must be >= 1; got {n_rounds}")
    times = _attempt_times(config, n_clients, n_rounds, fault_plan)
    return times.max(axis=1)

"""NnunetServer — plans negotiation + federated segmentation orchestration.

Parity surface (/root/reference/fl4health/servers/nnunet_server.py:54
``NnunetServer``): ``update_before_fit`` (:156) polls ONE random client via
``get_properties`` when the config carries no ``nnunet_plans``, stores the
returned plans bytes + channel counts, redistributes the plans through the
per-round config, and builds the global model from the plans so it can be
checkpointed (:133 ``initialize_server_model``).

TPU-native design: the handshake is the in-process polling protocol
(server/servers.py poll_clients); plans travel as JSON bytes (never pickle);
the "global model" is the flax module + its param pytree, built once and
handed to the FederatedSimulation factory.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping, Sequence

from fl4health_tpu.models.unet import unet_from_plans
from fl4health_tpu.server.servers import poll_clients
from fl4health_tpu.server.simulation import FederatedSimulation

logger = logging.getLogger(__name__)


class NnunetServer:
    """Negotiates plans, then runs the federated segmentation job.

    ``property_providers`` are the clients' ``get_properties`` handlers (one
    per client — clients.nnunet.make_nnunet_properties_provider).
    ``sim_builder(plans, num_input_channels, num_segmentation_heads)`` builds
    the FederatedSimulation once the architecture is known; deferring
    construction mirrors the reference's lazy model finalization.
    """

    def __init__(
        self,
        config: dict[str, Any],
        property_providers: Sequence[Callable[[Mapping[str, Any]], Mapping[str, Any]]],
        sim_builder: Callable[[dict[str, Any], int, int], FederatedSimulation],
        seed: int = 0,
    ):
        self.config = dict(config)
        self.property_providers = list(property_providers)
        self.sim_builder = sim_builder
        self.seed = seed
        self.plans: dict[str, Any] | None = None
        self.num_input_channels: int | None = None
        self.num_segmentation_heads: int | None = None
        self.global_model = None
        self.sim: FederatedSimulation | None = None

    # ------------------------------------------------------------------
    def update_before_fit(self) -> None:
        """The pre-round-1 handshake (nnunet_server.py:156-233)."""
        from fl4health_tpu.nnunet.plans import plans_from_bytes

        plans_bytes = self.config.get("nnunet_plans")
        if plans_bytes is None:
            logger.info(
                "[PRE-INIT] no nnunet_plans in config — requesting properties "
                "from one random client via get_properties"
            )
            # Sample one client (the reference samples via the client
            # manager; a seeded host RNG is the in-process equivalent).
            import numpy as np

            idx = int(
                np.random.default_rng(self.seed).integers(len(self.property_providers))
            )
            props = poll_clients(
                [self.property_providers[idx]], dict(self.config)
            )[0]
            plans_bytes = props["nnunet_plans"]
            self.num_input_channels = int(props["num_input_channels"])
            self.num_segmentation_heads = int(props["num_segmentation_heads"])
            logger.info("Received plans from client %d", idx)
        else:
            # Plans supplied by config; channel counts must come with them or
            # from a poll (the reference polls whenever checkpointing needs a
            # constructible model — here the sim always needs one).
            if "num_input_channels" in self.config and "num_segmentation_heads" in self.config:
                self.num_input_channels = int(self.config["num_input_channels"])
                self.num_segmentation_heads = int(self.config["num_segmentation_heads"])
            else:
                props = poll_clients(
                    [self.property_providers[0]], dict(self.config)
                )[0]
                self.num_input_channels = int(props["num_input_channels"])
                self.num_segmentation_heads = int(props["num_segmentation_heads"])

        self.plans = plans_from_bytes(plans_bytes)
        # Redistribute: subsequent rounds' client config carries the plans
        # (nnunet_server.py:233 sets the config for later configure_fit).
        self.config["nnunet_plans"] = plans_bytes
        # initialize_server_model (:133): a constructible global architecture.
        self.global_model = unet_from_plans(
            self.plans, self.num_input_channels, self.num_segmentation_heads
        )

    # ------------------------------------------------------------------
    def fit(self, n_rounds: int):
        if self.plans is None:
            self.update_before_fit()
        assert self.plans is not None
        assert self.num_input_channels is not None
        assert self.num_segmentation_heads is not None
        self.sim = self.sim_builder(
            self.plans, self.num_input_channels, self.num_segmentation_heads
        )
        return self.sim.fit(n_rounds)

    @property
    def global_params(self):
        assert self.sim is not None, "fit() has not run"
        return self.sim.global_params

"""FederatedSimulation — the round loop (FlServer.fit equivalent), SPMD-style.

Reference control flow (/root/reference/fl4health/servers/base_server.py:232
FlServer.fit -> fit_round :278 -> strategy.configure_fit -> gRPC fan-out ->
strategy.aggregate_fit -> evaluate_round :357): one server process and N
client processes exchanging serialized NumPy arrays.

TPU-native re-design: the N simulated clients are one client-stacked
``TrainState`` (leading [clients] axis on every leaf, shardable over a
``clients`` mesh axis). One round compiles to two programs:

    fit_round  = pull(payload) -> vmap(local_train scan) -> push -> aggregate
    eval_round = pull(global)  -> vmap(local_eval scan)  -> metric aggregation

The Python loop over rounds only moves host-side concerns: batch construction,
sampling, reporting, checkpointing — matching the reference's split of
responsibilities without any per-round serialize/deserialize.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import logging
import sys
import threading
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fl4health_tpu.checkpointing.async_writer import AsyncCheckpointWriter
from fl4health_tpu.checkpointing.checkpointer import CheckpointMode
from fl4health_tpu.clients import engine
from fl4health_tpu.observability import Observability
from fl4health_tpu.observability import device_specs
from fl4health_tpu.observability import stages as stage_attr
from fl4health_tpu.observability import telemetry as telem
from fl4health_tpu.observability.flightrec import trap_sigterm
from fl4health_tpu.observability.manifest import config_hash, run_manifest
from fl4health_tpu.observability.telemetry import RoundTelemetry
from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.exchange.exchanger import FullExchanger
from fl4health_tpu.metrics.aggregation import aggregate_metrics
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.parallel.program import (
    CLIENTS_AXIS,
    MeshConfig,
    RoundProgramBuilder,
)
from fl4health_tpu.server.client_manager import ClientManager, FullParticipationManager
from fl4health_tpu.server.pipeline import RoundConsumer, RoundPrefetcher
from fl4health_tpu.server.registry import (
    ClientRegistry,
    CohortConfig,
    _SlotManagerView,
    as_registry_source,
)
from fl4health_tpu.strategies.base import FitResults, Strategy

# Execution modes fit() can run in (reported through observability and every
# reporter's fit_start payload):
# - "pipelined_per_round": one fit + one eval dispatch per round, with the
#   host epilogue (failure policy, checkpointing, records, reporting) running
#   in a background RoundConsumer and the next round's batches prefetched —
#   host work overlaps device execution.
# - "chunked_scan": ALL rounds compile into one on-device lax.scan dispatch
#   (fit + eval per round inside the scan); per-round host work collapses to
#   a single fused device->host pull at the end.
EXEC_PIPELINED = "pipelined_per_round"
EXEC_CHUNKED = "chunked_scan"


def _donate_argnums(*argnums: int) -> tuple[int, ...]:
    """Buffer donation, gated OFF the CPU backend.

    Verified in this environment (jax 0.4.37, XLA:CPU, persistent
    compilation cache enabled by tests/conftest.py): an executable compiled
    WITH input-output aliasing computes correct results on the compile run
    but WRONG numerics after being reloaded from the persistent cache
    (A/B: the same program without donate_argnums round-trips exactly).
    Donation on CPU saves nothing we need — the in-place client-stack
    update is a device-memory lever — so CPU runs plain and TPU/GPU get
    the donation. Re-evaluate when the jaxlib cache serializes aliasing
    correctly. (One implementation: RoundProgramBuilder.donate — the
    sharded programs route through the same gate.)"""
    return RoundProgramBuilder.donate(*argnums)


def _dedupe_donated(*trees):
    """Break buffer aliasing inside trees about to be DONATED.

    XLA rejects donating the same buffer twice (``f(donate(a), donate(a))``)
    and Python-level state construction can legitimately alias — e.g. a
    strategy ``init`` storing the initial params in two fields. Compiled
    round OUTPUTS never alias (each output gets its own buffer), so one
    dedupe at fit entry keeps every subsequent donated dispatch safe.
    Returns the trees with later duplicates replaced by copies."""
    seen: set = set()

    def fix(x):
        if not isinstance(x, jax.Array):
            return x
        try:
            key = x.unsafe_buffer_pointer()
        except Exception:  # sharded/committed arrays: object identity
            key = id(x)
        if key in seen:
            return jnp.copy(x)
        seen.add(key)
        return x

    return jax.tree_util.tree_map(fix, trees)


@dataclasses.dataclass
class ClientDataset:
    """Host-side per-client data (the DataLoader boundary).

    ``x_*`` may be a plain array or a PYTREE of arrays sharing axis 0 (dict
    inputs — the reference's DictionaryDataset role); the engine's stacked
    gather handles either, and the model's ``__call__`` receives whatever
    structure was provided."""

    x_train: Any
    y_train: Any
    x_val: Any
    y_val: Any
    x_test: Any = None
    y_test: Any = None

    @property
    def n_train(self) -> int:
        return engine.data_rows(self.x_train)


class ClientFailuresError(RuntimeError):
    """Raised when accept_failures=False and client failures occur
    (base_server.py:443-451).

    Structured for the postmortem verdict: ``clients`` (failing client
    indices, slot positions under cohort execution), ``round`` and
    ``registry_clients`` (cohort rounds only — the slots mapped to
    registry ids) are attached by the round epilogue before the raise
    unwinds ``fit()``."""

    def __init__(self, message: str, clients: Sequence[int] = ()):
        super().__init__(message)
        self.clients = [int(c) for c in clients]
        self.round: int | None = None
        self.registry_clients: list[int] | None = None


@dataclasses.dataclass
class FailurePolicy:
    """accept_failures semantics (base_server.py:104,316-318): with
    ``accept_failures=False`` any failed client terminates the run. The SPMD
    failure signal is a non-finite backward loss in a participating client's
    row of the stacked results (a crashed gRPC peer has no in-process
    equivalent; a NaN-poisoned shard is the analogous failure mode)."""

    accept_failures: bool = True

    def check(self, per_client_losses, mask) -> list[int]:
        key = "backward" if "backward" in per_client_losses else None
        if key is None:
            return []
        # pure numpy: the pipelined loop runs this on already-host data in a
        # background thread — the screen must not dispatch device work
        row = np.asarray(per_client_losses[key])
        bad = np.logical_and(~np.isfinite(row), np.asarray(mask) > 0)
        failed = [int(i) for i in np.nonzero(bad)[0]]
        for cid in failed:
            logging.getLogger(__name__).error(
                "Client %d failed (non-finite training loss).", cid
            )
        if failed and not self.accept_failures:
            raise ClientFailuresError(
                f"The server encountered failures from clients {failed} and "
                "accept_failures is set to False",
                clients=failed,
            )
        return failed


@dataclasses.dataclass
class RoundRecord:
    round: int
    fit_losses: dict
    fit_metrics: dict
    eval_losses: dict
    eval_metrics: dict
    fit_elapsed_s: float
    eval_elapsed_s: float


@dataclasses.dataclass
class _RoundWork:
    """Everything the RoundConsumer needs to finish one round on the host.

    ``device_results`` holds fresh (never-donated) device arrays — round
    results plus any ``_pre_agg_params``/``_post_agg_params``/
    ``_state_trees`` device-side snapshot copies — and the consumer performs
    the round's single fused device->host transfer of all of it."""

    round: int
    device_results: dict
    fit_elapsed_s: float
    eval_elapsed_s: float
    device_wait_s: float
    compiles_before: float
    compile_s_before: float
    compiles_after: float | None
    compile_s_after: float | None
    # buffered-async runs only: host facts of the consumed buffer-fill
    # event (staleness stats, virtual cadence) from the static plan —
    # merged into the round record/metrics by the consumer
    async_info: dict | None = None
    # async checkpoint extras: the plan-prefix fingerprint + virtual clock
    # stored with the event's state snapshot (None on sync rounds)
    resume_meta: dict | None = None
    # cohort-slot rounds only: the round's sampled registry ids, valid
    # count, staging wall and the scatter-completion event the producer
    # gates the next state gather on (None on dense rounds)
    cohort_meta: dict | None = None
    # pre-built cohort summary for rounds whose registry exchange the
    # PRODUCER already performed (async-over-registry events) — the
    # consumer then has no ``_registry_rows`` to scatter but still reports
    # the cohort facts
    cohort_info: dict | None = None


class FederatedSimulation:
    """Couples logic + optimizer + strategy + data into a runnable FL job."""

    def __init__(
        self,
        logic: ClientLogic,
        tx: optax.GradientTransformation,
        strategy: Strategy,
        datasets: Sequence[ClientDataset],
        batch_size: int,
        metrics: MetricManager,
        local_epochs: int | None = None,
        local_steps: int | None = None,
        exchanger=None,
        client_manager: ClientManager | None = None,
        seed: int = 42,
        extra_loss_keys: tuple[str, ...] = (),
        eval_loss_keys: tuple[str, ...] = (),
        reporters: Sequence[Any] = (),
        model_checkpointers: Sequence[tuple[Any, Any]] = (),
        state_checkpointer: Any = None,
        early_stopping: engine.EarlyStoppingConfig | None = None,
        flash_early_stopping: Any = None,
        failure_policy: FailurePolicy | None = None,
        profile_dir: str | None = None,
        train_data_provider: Any = None,
        observability: Observability | None = None,
        execution_mode: str = "auto",
        pipeline_depth: int = 2,
        fault_plan: Any = None,
        compression: Any = None,
        mesh: MeshConfig | None = None,
        precision: Any = None,
        async_config: Any = None,
        cohort: CohortConfig | None = None,
        recovery: Any = None,
    ):
        if (local_epochs is None) == (local_steps is None):
            raise ValueError("specify exactly one of local_epochs / local_steps "
                             "(reference: utils/config.py epochs-xor-steps check)")
        if execution_mode not in ("auto", "pipelined", "chunked"):
            raise ValueError(
                f"execution_mode must be 'auto', 'pipelined' or 'chunked'; "
                f"got {execution_mode!r}"
            )
        # Cohort-slot execution (server/registry.py CohortConfig): rounds
        # compile and run against a fixed [slots] axis while the client
        # population lives in a host-resident ClientRegistry — HBM and
        # per-round FLOPs scale with the SAMPLED cohort, not the registry.
        # None (the default) keeps the dense [n_clients] path bit-identical
        # to pre-cohort builds on both execution modes.
        if cohort is not None and not isinstance(cohort, CohortConfig):
            raise TypeError(
                "cohort must be a CohortConfig (or None); got "
                f"{type(cohort).__name__} — pass server.registry.CohortConfig"
            )
        self.cohort_config = cohort
        self._cohort_active = cohort is not None
        self.registry: ClientRegistry | None = None
        if self._cohort_active:
            source = as_registry_source(datasets)
            self.registry = ClientRegistry(
                source, batch_size, local_steps, local_epochs
            )
            self.registry_size = source.n_clients
            # every compiled shape below is SLOT-shaped; the registry keeps
            # the O(N) facts (sizes, rows, data) host-side
            self.datasets = []
            self.n_clients = cohort.slots
        else:
            self.registry_size = None
            self.datasets = list(datasets)
            self.n_clients = len(self.datasets)
        self.logic = logic
        self.tx = tx
        self.strategy = strategy
        self.batch_size = batch_size
        self.metrics = metrics
        self._extra_loss_keys = tuple(extra_loss_keys)
        self._eval_loss_keys = tuple(eval_loss_keys)
        self.local_epochs = local_epochs
        self.local_steps = local_steps
        self.exchanger = exchanger or FullExchanger()
        # Compressed exchange (compression/: CompressionConfig): the lossy
        # client->server channel compiles INTO the round programs via a
        # CompressingStrategy wrapper, so chunked mode keeps one dispatch
        # per N rounds and both execution modes draw identical stochastic
        # codes. None (or a config with no lossy stage) wraps nothing —
        # trajectories stay bit-identical to an uncompressed build.
        self.compression = compression
        if compression is not None:
            from fl4health_tpu.compression.config import CompressionConfig

            if not isinstance(compression, CompressionConfig):
                raise TypeError(
                    "compression must be a CompressionConfig (or None); got "
                    f"{type(compression).__name__} — a duck-typed config "
                    "would silently train uncompressed"
                )
        self._compression_active = bool(
            compression is not None and compression.enabled
        )
        self._wire_bytes_cache: int | None = None
        if self._compression_active:
            from fl4health_tpu.exchange.exchanger import FixedLayerExchanger

            if (getattr(self.exchanger, "wants_packet_payload", False)
                    or isinstance(self.exchanger, FixedLayerExchanger)):
                # FixedLayerExchanger (FedBN et al.) zeroes non-exchanged
                # leaves in push(), so each would read as a huge fake
                # -reference delta dominating the top-k and poisoning the
                # EF residual — reject it like the packet-shaped partials
                raise ValueError(
                    "compression composes with full-model exchange only: "
                    f"{type(self.exchanger).__name__} ships partial "
                    "payloads whose zeroed/masked entries would read as "
                    "real deltas (it is already a compression scheme)"
                )
            from fl4health_tpu.compression.strategy import CompressingStrategy

            strategy = self.strategy = CompressingStrategy(
                strategy, compression
            )
        # Buffered-async federation (server/async_schedule.py AsyncConfig):
        # the FedBuff-style mode where the server aggregates as soon as a
        # buffer of K updates arrives, staleness-discounting stale ones.
        # The schedule resolves to a STATIC event plan at fit() time, so
        # async runs still execute as compiled round programs on both
        # execution paths. None (the default) builds the exact synchronous
        # programs — trajectories bit-identical to pre-async builds.
        self.async_config = async_config
        if async_config is not None:
            from fl4health_tpu.server.async_schedule import AsyncConfig

            if not isinstance(async_config, AsyncConfig):
                raise TypeError(
                    "async_config must be an AsyncConfig (or None); got "
                    f"{type(async_config).__name__} — a duck-typed config "
                    "would silently train synchronously"
                )
            if self._cohort_active:
                # FedBuff over the registry: K slots hold seated registry
                # clients, so the buffer fills from the SLOTS, and the
                # static seating plan needs an occupant per seat
                if async_config.buffer_size > self.cohort_config.slots:
                    raise ValueError(
                        f"async_config.buffer_size="
                        f"{async_config.buffer_size} exceeds the cohort "
                        f"slots ({self.cohort_config.slots}): the buffer "
                        "fills from the seated slots, so it could never "
                        "fill"
                    )
            elif async_config.buffer_size > len(datasets):
                raise ValueError(
                    f"async_config.buffer_size={async_config.buffer_size} "
                    f"exceeds the cohort ({len(datasets)} clients): the "
                    "buffer could never fill"
                )
            from fl4health_tpu.strategies.fedbuff import FedBuff

            if isinstance(strategy, FedBuff):
                # A pre-wrapped FedBuff must AGREE with the AsyncConfig:
                # the manifest hashes the config's staleness parameters,
                # so a wrapper silently discounting with different ones
                # would misattribute the experiment.
                if (strategy.staleness_exponent
                        != float(async_config.staleness_exponent)
                        or strategy.max_staleness
                        != async_config.max_staleness):
                    raise ValueError(
                        "the provided FedBuff wrapper's staleness "
                        f"parameters (exponent={strategy.staleness_exponent}"
                        f", max_staleness={strategy.max_staleness}) differ "
                        "from async_config's "
                        f"(exponent={async_config.staleness_exponent}, "
                        f"max_staleness={async_config.max_staleness}) — "
                        "the manifest records the config's values, so "
                        "they must match (simplest: pass the bare inner "
                        "strategy and let async_config do the wrapping)"
                    )
            else:
                # FedBuff must be the OUTERMOST wrapper: the async round
                # programs call its async_aggregation_mask hook, and inner
                # wrappers (compression/quarantine) see the discounted
                # fractional mask exactly like a sampled one
                strategy = self.strategy = FedBuff(
                    strategy,
                    staleness_exponent=async_config.staleness_exponent,
                    max_staleness=async_config.max_staleness,
                )
        self._async_active = async_config is not None
        # Self-healing recovery (resilience/supervisor.py): recovery=
        # RecoveryPolicy(...) routes fit() through a RecoverySupervisor
        # that turns the structured abnormal-end taxonomy (watchdog halt,
        # client failures, quorum loss, corrupt checkpoints) into
        # rollback-quarantine-resume per a declarative escalation ladder.
        # None (the default) keeps fit() exactly the unsupervised loop —
        # and an armed-but-never-engaged policy is pinned bit-identical
        # too (the supervisor's hooks are no-ops until it engages).
        self.recovery_policy = recovery
        if recovery is not None:
            from fl4health_tpu.resilience.supervisor import RecoveryPolicy

            if not isinstance(recovery, RecoveryPolicy):
                raise TypeError(
                    "recovery must be a RecoveryPolicy (or None); got "
                    f"{type(recovery).__name__} — pass "
                    "resilience.supervisor.RecoveryPolicy"
                )
        self._recovery_supervisor = None
        # Device-mesh placement (parallel/program.py): mesh=None keeps the
        # single-chip programs (and trajectories) bit-identical; a
        # MeshConfig shards the [C, ...] client axes over the "clients"
        # mesh axis in every compiled round program, replicates (or
        # ZeRO-1-shards) the server state, and stages per-round data with
        # sharded device_put — massive cohorts across data-parallel chips.
        if mesh is not None and not isinstance(mesh, MeshConfig):
            raise TypeError(
                "mesh must be a MeshConfig (or None); got "
                f"{type(mesh).__name__} — pass parallel.program.MeshConfig"
            )
        self.mesh_config = mesh
        self._program_builder = RoundProgramBuilder(
            mesh, n_clients=self.n_clients
        )
        # Engine-level mixed precision (precision/: PrecisionConfig): the
        # compute-dtype cast and fp16 loss scaling compile INTO the round
        # programs at model-apply time — every client algorithm trains
        # bf16/fp16 against the f32 master weights this simulation carries,
        # and everything pinned on those masters (DP clip->noise, telemetry
        # norms, compression deltas, robust aggregation, ZeRO-1 server
        # shards) stays f32. None (or an inactive f32 config) builds the
        # exact pre-precision programs — trajectories bit-identical on both
        # execution modes (tests/precision/).
        if precision is not None:
            from fl4health_tpu.precision import PrecisionConfig

            if not isinstance(precision, PrecisionConfig):
                raise TypeError(
                    "precision must be a PrecisionConfig (or None); got "
                    f"{type(precision).__name__} — a duck-typed config "
                    "would silently train in f32"
                )
        self.precision = precision
        self._precision_active = bool(
            precision is not None and precision.active
        )
        self._precision_scaling = bool(
            precision is not None and precision.scaling_active
        )
        if self._cohort_active:
            # the manager samples over the REGISTRY; the compiled programs
            # are slot-shaped
            self.client_manager = client_manager or FullParticipationManager(
                self.registry_size
            )
            if self.client_manager.n_clients != self.registry_size:
                raise ValueError(
                    f"client_manager covers {self.client_manager.n_clients} "
                    f"clients but the registry holds {self.registry_size}; "
                    "the sampling manager must be built over the registry"
                )
            if (isinstance(self.client_manager, FullParticipationManager)
                    and self.cohort_config.slots < self.registry_size
                    and not self._async_active):
                # (buffered-async over the registry seats K of N clients
                # per the occupancy plan — full participation there means
                # "every SEATED slot", so slots < N is the normal shape)
                raise ValueError(
                    f"full participation needs slots >= registry size "
                    f"({self.registry_size}); got slots="
                    f"{self.cohort_config.slots} — pass a sampling manager "
                    "(FixedFractionManager/PoissonSamplingManager) whose "
                    "worst-case draw fits the slots"
                )
        else:
            self.client_manager = client_manager or FullParticipationManager(
                self.n_clients
            )
        # setup-time strategy <-> sampling-scheme validation (e.g. the DP
        # strategies derive/check fraction_fit against the manager's sampling
        # fraction — a mismatch silently mis-scales the DP noise).
        bind = getattr(strategy, "bind_client_manager", None)
        if bind is not None:
            bind(self.client_manager)
        if self._cohort_active and bind is not None:
            # re-bind a SLOT-COUNT view so wrapper strategies size their
            # per-client server rows [slots] — the compiled shape; the
            # registry persists the O(N) rows host-side. The view delegates
            # fraction/min_clients, so the validation above still saw the
            # true scheme.
            bind(_SlotManagerView(self.client_manager,
                                  self.cohort_config.slots))
        self.reporters = list(reporters)
        # (CheckpointMode, ParamsCheckpointer) pairs — PRE_AGGREGATION fires on
        # the client-stacked post-fit params, POST_AGGREGATION on the
        # aggregated global model (client_module.py:23-28 semantics).
        self.model_checkpointers = list(model_checkpointers)
        self.state_checkpointer = state_checkpointer
        self.early_stopping = early_stopping
        self.flash_early_stopping = flash_early_stopping
        if flash_early_stopping is not None:
            # Flash is epoch-defined (flash_client.py:71-95 rejects step-wise)
            if local_epochs is None:
                raise ValueError("flash_early_stopping requires local_epochs")
            if early_stopping is not None:
                raise ValueError("flash_early_stopping and early_stopping are exclusive")
            if flash_early_stopping.n_epochs != local_epochs:
                raise ValueError(
                    f"flash_early_stopping.n_epochs={flash_early_stopping.n_epochs} "
                    f"must equal local_epochs={local_epochs}: the gamma rule is "
                    "defined per true local epoch"
                )
        self.failure_policy = failure_policy or FailurePolicy()
        # SURVEY §5: the reference records only coarse wall-clock timings;
        # a real device-level trace is the strictly-better TPU-native story.
        # When set, fit() wraps the round loop in jax.profiler.trace and the
        # trace directory can be opened in TensorBoard/XProf.
        self.profile_dir = profile_dir
        # Round-level observability (observability/__init__.py): spans per
        # round phase, compile/byte counters, opt-in per-round XProf capture.
        # Defaults to a disabled handle whose every hook is a shared no-op,
        # so the un-instrumented hot loop stays exactly as fast (and adds no
        # device syncs — the fence is a pass-through when disabled).
        self.observability = observability or Observability(enabled=False)
        self._payload_bytes_cache: tuple[int, int] | None = None
        # Optional per-round host data refresh: callable(round_idx) ->
        # (x_list, y_list) | None. Called at the top of each fit() round;
        # shapes must match the originals so the compiled round program
        # stays valid (no recompile). The nnU-Net pipeline uses this for
        # fresh patch extraction per round (nnunet.data.make_patch_resampler);
        # fit_chunk bakes its data at dispatch time and bypasses it.
        self.train_data_provider = train_data_provider
        if self._async_active:
            # The async event programs are FUSED (aggregate -> eval ->
            # retrain in one dispatch), so hooks that need the host mid-
            # round cannot compose; and participation is DERIVED from the
            # arrival schedule, so a sampling manager would be silently
            # ignored. Reject loudly instead.
            if not isinstance(self.client_manager, FullParticipationManager):
                raise ValueError(
                    "async_config derives participation from the buffer's "
                    "arrival schedule; a sampling client manager "
                    f"({type(self.client_manager).__name__}) is not "
                    "composable with buffered-async mode"
                )
            overrides = getattr(
                self.strategy, "overrides_update_after_eval", None
            )
            if overrides is None:
                overrides = (type(self.strategy).update_after_eval
                             is not Strategy.update_after_eval)
            if overrides:
                raise ValueError(
                    "async_config is not composable with strategies that "
                    "consume per-round eval results on the host "
                    "(update_after_eval override): the async event "
                    "program fuses aggregate+eval+retrain in one dispatch"
                )
            if self.train_data_provider is not None:
                raise ValueError(
                    "async_config is not composable with "
                    "train_data_provider: the async event programs bake "
                    "their data at dispatch time"
                )
            if self.model_checkpointers:
                raise ValueError(
                    "async_config is not composable with per-round model "
                    "checkpointing: there is no synchronous post-fit/"
                    "pre-aggregation moment inside a fused buffer-fill "
                    "event (state checkpointing — resume — composes; use "
                    "state_checkpointer)"
                )
            if self._cohort_active and self.mesh_config is not None:
                raise ValueError(
                    "async_config + cohort=CohortConfig(...) does not yet "
                    "compose with mesh: the per-event occupancy swap "
                    "restages seated rows host-side, which would fight the "
                    "mesh's sharded staging; run the composition unsharded "
                    "or drop one of the two"
                )
            if self._cohort_active and self.state_checkpointer is not None:
                raise ValueError(
                    "async_config + cohort=CohortConfig(...) does not yet "
                    "compose with state checkpointing: a resume would need "
                    "a frame persisting BOTH the pending update buffer and "
                    "the registry's dirty rows + seating cursor, and no "
                    "such combined frame format exists yet"
                )
            sc = self.state_checkpointer
            if sc is not None and not (
                hasattr(sc, "save_async_snapshot")
                and hasattr(sc, "load_async_simulation")
            ):
                raise ValueError(
                    "async state checkpointing needs a checkpointer that "
                    "can snapshot the pending update buffer and the event "
                    "cursor (save_async_snapshot/load_async_simulation — "
                    f"SimulationStateCheckpointer); {type(sc).__name__} "
                    "cannot, so an interrupted async run could not resume "
                    "mid-plan"
                )
        if self._cohort_active:
            # cohort-slot composition rules: the slot round evaluates the
            # SAMPLED cohort, so hooks that consume whole-population
            # per-round eval on the host cannot compose; per-round host
            # data refresh would invalidate the registry's staging.
            overrides = getattr(
                self.strategy, "overrides_update_after_eval", None
            )
            if overrides is None:
                overrides = (type(self.strategy).update_after_eval
                             is not Strategy.update_after_eval)
            if overrides:
                raise ValueError(
                    "cohort=CohortConfig(...) is not composable with "
                    "strategies that consume per-round eval results on the "
                    "host (update_after_eval override): slot eval covers "
                    "the sampled cohort, not the population"
                )
            if self.train_data_provider is not None:
                raise ValueError(
                    "cohort=CohortConfig(...) is not composable with "
                    "train_data_provider: per-round data lives in the "
                    "registry source — refresh it there"
                )
            sc = self.state_checkpointer
            if sc is not None and not (
                hasattr(sc, "save_cohort_snapshot")
                and hasattr(sc, "load_cohort_simulation")
            ):
                raise ValueError(
                    "cohort state checkpointing needs a checkpointer that "
                    "persists the registry's dirty rows (save_cohort_"
                    "snapshot/load_cohort_simulation — "
                    f"SimulationStateCheckpointer); {type(sc).__name__} "
                    "cannot, so an interrupted cohort run could not resume"
                )
        # fit() dispatch strategy: "auto" routes through the on-device
        # multi-round chunked scan whenever the configuration permits (see
        # _chunk_ineligibility) and falls back to the pipelined per-round
        # path otherwise; "pipelined"/"chunked" force one path (forcing
        # "chunked" on an ineligible config raises at fit()).
        self.execution_mode = execution_mode
        # How many rounds of host epilogue work may be in flight behind the
        # device on the pipelined path (bounded RoundConsumer queue).
        self.pipeline_depth = pipeline_depth
        # Deterministic chaos layer (resilience/faults.py FaultPlan): client
        # dropout multiplies the participation mask and update corruption
        # transforms the packet stack INSIDE the round programs, so the same
        # plan injects the same faults on both execution modes and a faulted
        # run never recompiles. None (or an empty plan) leaves the round
        # closures untouched — trajectories stay bit-identical.
        self._fault_plan = fault_plan
        # host mirror of the in-graph quarantine mask (strategy-driven), for
        # entered/released transition accounting in the per-round metrics
        self._last_quarantine: list[int] | None = None
        # cohort-slot runs: persistent registry-wide quarantine view
        # (sampled rounds only refresh the sampled ids' standing)
        self._cohort_quarantine: set | None = None
        self._active_execution_mode = EXEC_PIPELINED
        self._consumer: RoundConsumer | None = None
        self._prefetcher: RoundPrefetcher | None = None
        # cohort-slot ordering handle: the consumer sets this event once it
        # has scattered round r's rows into the registry, and the producer
        # waits on it before gathering round r+1's state (read-after-write
        # through the host registry; data staging is NOT gated on it)
        self._registry_scatter_event = None
        self._ckpt_writer: AsyncCheckpointWriter | None = None
        self._fit_n_rounds = 0
        # facts of the restore a fit() performed (manifest `resume`
        # descriptor); None on fresh runs
        self._resume_info: dict | None = None
        # per-event prefix digests of the async plan (computed when async
        # checkpointing is active; event e's snapshot stores entry e-1)
        self._async_prefix_fps: list[str] | None = None
        # Measured per-round program FLOPs from build-time introspection
        # (observability/introspect.py); None until a fit() captures it.
        # Feeds the measured-MFU numbers in _record_round_metrics.
        self._round_program_flops: float | None = None
        # per-client scheduled local-step counts (from the fixed round
        # plan), computed lazily for the per-chip steps/s round metric
        self._steps_per_client_cache: np.ndarray | None = None
        self.rng = jax.random.PRNGKey(seed)
        self._device_kind = getattr(jax.devices()[0], "device_kind", None)
        if self._cohort_active:
            # slot programs take sample_counts as a TRACED input (the PR 11
            # hook) — the cohort's true counts are staged per round; this
            # baked placeholder is never dispatched
            self.sample_counts = jnp.zeros((self.n_clients,), jnp.float32)
        else:
            self.sample_counts = jnp.asarray(
                [d.n_train for d in self.datasets], jnp.float32
            )
        self.history: list[RoundRecord] = []

        # x/y row counts must agree within each client and split: n_train is
        # derived from x, so a short y would silently pair tail examples with
        # zero-padded labels after stacking.
        for i, d in enumerate(self.datasets):
            if d.y_test is not None and d.x_test is None:
                # mirror of the x-without-y case below: silently ignoring the
                # labels would skip a test evaluation the user asked for
                raise ValueError(f"client {i}: y_test set but x_test is None")
        have_test = [d.x_test is not None for d in self.datasets]
        if any(have_test) and not all(have_test):
            missing = [i for i, h in enumerate(have_test) if not h]
            raise ValueError(
                f"clients {missing} have no test split while others do; "
                "provide x_test/y_test for every client or none."
            )
        self._has_test_split = all(have_test) and len(have_test) > 0
        for i, d in enumerate(self.datasets):
            splits = [(d.x_train, d.y_train, "train"), (d.x_val, d.y_val, "val")]
            if self._has_test_split:
                if d.y_test is None:
                    raise ValueError(f"client {i}: x_test set but y_test is None")
                splits.append((d.x_test, d.y_test, "test"))
            for xs, ys, split in splits:
                nx, ny = engine.data_rows(xs), engine.data_rows(ys)
                if nx != ny:
                    raise ValueError(
                        f"client {i}: x_{split} has {nx} rows but y_{split} "
                        f"has {ny}; each client's features and labels must "
                        "pair one-to-one."
                    )

        # Pre-stacked per-client data (one-time, device-resident) feeding the
        # per-round single-gather batch construction (engine.gather_batches).
        # The banks deliberately stay UNSHARDED here: the pipelined
        # prefetcher's worker thread gathers batches from them eagerly, and
        # an eager multi-device gather racing the main thread's round
        # dispatch deadlocks (two threads enqueueing multi-device launches
        # in different per-device orders). The chunked dispatches — the only
        # programs that take the banks as jit inputs — stage a sharded copy
        # once via _sharded_train_banks() instead.
        if self._cohort_active:
            # no O(N) device banks in cohort mode: per-round slot batches
            # are assembled host-side from the registry and staged through
            # the prefetcher (data never exceeds O(slots) on device)
            self._x_train_stack = self._y_train_stack = None
            self._x_val_stack = self._y_val_stack = None
            self._sharded_banks_cache: tuple | None = None
        else:
            self._x_train_stack = engine.pad_and_stack_data([d.x_train for d in self.datasets], "x_train")
            self._y_train_stack = engine.pad_and_stack_data([d.y_train for d in self.datasets], "y_train")
            self._sharded_banks_cache = None
            self._x_val_stack = engine.pad_and_stack_data([d.x_val for d in self.datasets], "x_val")
            self._y_val_stack = engine.pad_and_stack_data([d.y_val for d in self.datasets], "y_val")
        self._base_entropy = engine._entropy_from_key(self.rng)
        self._val_cache: tuple[Batch, jax.Array] | None = None
        self._test_cache: tuple[Batch, jax.Array] | None = None

        # --- init client + server state -----------------------------------
        self._init_states(_wire_zero1=True)

        self._build_compiled()

    # ------------------------------------------------------------------
    def _init_states(self, _wire_zero1: bool = False) -> None:
        """(Re)initialize the client-stacked ``TrainState`` and the server
        state from ``self.rng`` — exactly the constructor's derivation,
        factored out so the sweep engine (``fl4health_tpu/sweep/``) can
        re-seed a template simulation per grid cell without rebuilding its
        closures/compiled programs::

            sim.rng = jax.random.PRNGKey(seed)
            sim._base_entropy = engine._entropy_from_key(sim.rng)
            sim._init_states()

        reproduces bit-identically the states a fresh construction with
        that seed would build. ``_wire_zero1`` runs the one-time ZeRO-1
        server-optimizer wiring and is only passed by ``__init__``."""
        init_rng = jax.random.fold_in(self.rng, 0)
        if self._cohort_active:
            sample_x = jax.tree_util.tree_map(
                jnp.asarray, self.registry.sample_x()
            )
        else:
            sample_x = jax.tree_util.tree_map(
                lambda a: a[:1], self.datasets[0].x_train
            )
        proto = engine.create_train_state(
            self.logic, self.tx, init_rng, sample_x, precision=self.precision
        )
        if (_wire_zero1 and self._program_builder.mesh is not None
                and self.mesh_config.zero1):
            # ZeRO-1 server optimizer (parallel/zero.py) over the SAME mesh
            # the round programs dispatch on — each replica owns 1/N of the
            # server momenta; the construction-time parity probe therefore
            # validates the deployed sharding, not a throwaway mesh.
            self._wire_zero1_server_optimizer(proto.params)
        per_client = []
        for i in range(self.n_clients):
            # All clients share the server's initial params (the reference's
            # round-1 initialize_all_model_weights broadcast covers the FULL
            # model, basic_client.py:205 — including personal subtrees that
            # never cross the wire afterwards); only the PRNG stream differs.
            st = proto.replace(rng=jax.random.fold_in(init_rng, i + 1))
            per_client.append(st)
        self.client_states: TrainState = ptu.stack_clients(per_client)
        # self.strategy, not a local: zero1 wiring may have rebuilt the
        # chain around a ZeRO-sharded server optimizer
        self.server_state = self.strategy.init(proto.params)
        if self._cohort_active:
            # bind the registry's prototype rows: client i's TrainState row
            # derives from (proto, fold_in(init_rng, i+1)) — the dense
            # constructor's exact per-client derivation — and the
            # strategy's per-client server rows from the slot init's row 0
            # (client-symmetric start, verified by bind_strategy_rows)
            self.registry.bind_client_states(proto, init_rng)
            self.registry.bind_strategy_rows(
                self.strategy.state_rows(self.server_state)
            )

    # ------------------------------------------------------------------
    def set_train_data(self, xs: Sequence[Any], ys: Sequence[Any]) -> None:
        """Swap every client's training arrays in place — the host half of
        per-round data refresh (e.g. fresh nnU-Net patch banks). Shapes and
        dtypes must match the originals: the compiled round program is traced
        against the stacked layout and must not be invalidated."""
        if self._cohort_active:
            raise ValueError(
                "set_train_data swaps the dense device banks; a cohort-slot "
                "simulation has none — refresh the registry's data source "
                "instead (the next round's staging reads it)"
            )
        def coerce(d):
            # Preserve pre-pytree behavior for array-likes (lists of rows
            # coerce to ONE array); only Mapping inputs are treated as
            # multi-input pytrees.
            from collections.abc import Mapping

            if isinstance(d, Mapping):
                return jax.tree_util.tree_map(jnp.asarray, d)
            return jnp.asarray(d)

        new_x = engine.pad_and_stack_data([coerce(x) for x in xs], "x_train")
        new_y = engine.pad_and_stack_data([coerce(y) for y in ys], "y_train")
        for name, new, old in (("x_train", new_x, self._x_train_stack),
                               ("y_train", new_y, self._y_train_stack)):
            if (jax.tree_util.tree_structure(new)
                    != jax.tree_util.tree_structure(old)):
                raise ValueError(
                    f"set_train_data: {name} pytree structure changed "
                    "(per-round refresh may not change the data layout)"
                )
            for (pa, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(new)[0],
                jax.tree_util.tree_flatten_with_path(old)[0],
            ):
                if a.shape != b.shape or a.dtype != b.dtype:
                    raise ValueError(
                        f"set_train_data: {name}{engine.path_str(pa)} stack "
                        f"{a.shape}/{a.dtype} must match the original "
                        f"{b.shape}/{b.dtype} (per-round refresh may not "
                        "change the data layout)"
                    )
        self._x_train_stack = new_x
        self._y_train_stack = new_y
        # the swapped banks invalidate any staged sharded copy (identity
        # check in _sharded_train_banks)
        self._sharded_banks_cache = None

    # ------------------------------------------------------------------
    def _wire_zero1_server_optimizer(self, params_template) -> None:
        """Wire ``parallel/zero.py`` into the server optimizer
        (``MeshConfig(zero1=True)``): the innermost strategy must be
        FedOpt-family (it OWNS a server optax transform); its ``tx`` is
        wrapped so the flat server-momenta vector is partitioned over the
        clients (replica) axis — Xu et al.'s cross-replica sharding of the
        weight update. The one-step sharded-vs-unsharded parity probe runs
        against THIS mesh (the one ``fit()`` dispatches on).

        The caller's strategy object is never mutated: the wrapper chain is
        rebuilt around shallow copies and ``self.strategy`` reassigned, so a
        strategy instance reused by another simulation (the natural
        sharded-vs-unsharded comparison) keeps its plain ``tx``."""
        import copy

        from fl4health_tpu.parallel.zero import (
            Zero2ShardedOptimizer,
            ZeroShardedOptimizer,
            _validate_elementwise,
            zero_sharded_optimizer,
        )
        from fl4health_tpu.strategies.fedopt import FedOpt

        chain = [self.strategy]
        while hasattr(chain[-1], "inner"):
            chain.append(chain[-1].inner)
        inner = chain[-1]
        if not isinstance(inner, FedOpt):
            raise ValueError(
                "MeshConfig(zero1=True) shards a SERVER optimizer: the "
                "(innermost) strategy must be FedOpt-family (fed_adam/"
                "fed_yogi/fed_adagrad/fed_avg_m/FedOpt); got "
                f"{type(inner).__name__}, which has no server optax "
                "transform to shard"
            )
        mesh = self._program_builder.mesh
        if isinstance(inner.tx, (ZeroShardedOptimizer, Zero2ShardedOptimizer)):
            # Already sharded by the caller: the probe must still reflect
            # the DEPLOYED mesh — a wrapper validated on a different mesh
            # certifies nothing about this run's sharding.
            if inner.tx.mesh != mesh or inner.tx.axis_name != CLIENTS_AXIS:
                raise ValueError(
                    "the server optimizer was ZeRO-sharded against a "
                    f"different mesh/axis ({inner.tx.axis_name!r} on "
                    f"{dict(inner.tx.mesh.shape)}) than the round programs "
                    f"dispatch on ({CLIENTS_AXIS!r} on {dict(mesh.shape)}); "
                    "let MeshConfig(zero1=True) do the wiring (pass the "
                    "plain optax transform) so validation reflects the "
                    "deployed sharding"
                )
            if self.mesh_config.validate_zero1:
                n_local = (inner.tx.n_shards
                           if isinstance(inner.tx, Zero2ShardedOptimizer)
                           else None)
                _validate_elementwise(
                    inner.tx, inner.tx.tx, params_template, n_local=n_local
                )
            return
        new_inner = copy.copy(inner)
        new_inner.tx = zero_sharded_optimizer(
            inner.tx, mesh, params_template, axis_name=CLIENTS_AXIS,
            validate=self.mesh_config.validate_zero1,
        )
        rebuilt = new_inner
        for wrapper in reversed(chain[:-1]):
            wrapper = copy.copy(wrapper)
            wrapper.inner = rebuilt
            rebuilt = wrapper
        self.strategy = rebuilt

    # ------------------------------------------------------------------
    def _build_compiled(self):
        # In-graph telemetry (observability/telemetry.py) is a compile-time
        # property of the round programs: the plain 5-output fit_round /
        # eval_round keep their signature for every external caller
        # (servers.py warm starts, bench, direct-test drivers, fit_chunk),
        # and telemetry-enabled fit() dispatches the *_t variants whose
        # extra output is the RoundTelemetry pytree.
        self._telemetry_enabled = self.observability.telemetry_enabled
        self._fit_round_fn, self._eval_round_fn = self._build_round_fns(False)
        # Every compiled round program is constructed by the
        # RoundProgramBuilder (parallel/program.py) — placement policy in
        # one place. mesh=None: b.jit IS jax.jit(fn, donate_argnums=...),
        # the pre-mesh program. With a mesh, the [C, ...] inputs/outputs
        # get NamedSharding(P("clients")) and the server state replicates
        # (or ZeRO-1-shards) via in_shardings/out_shardings.
        b = self._program_builder
        cs = b.client_sharding()
        rep = b.replicated()
        if b.mesh is not None:
            sh_clients = b.client_state_shardings(self.client_states)
            sh_server = b.server_state_shardings(
                self.strategy, self.server_state
            )
            # fit_round(server_state, client_states, batches, mask,
            #           round_idx, val_batches)
            self._fit_in_sh = (sh_server, sh_clients, cs, cs, rep, cs)
            if self._cohort_active:
                # cohort dispatches pass the per-round sample_counts as a
                # 7th (traced) argument — a [K] per-slot vector, clients
                # axis like the mask
                self._fit_in_sh = self._fit_in_sh + (cs,)
            self._fit_out_sh = (sh_server, sh_clients, None, None, None)
            # eval_round(server_state, client_states, batches, eval_counts)
            self._eval_in_sh = (sh_server, sh_clients, cs, cs)
            self._eval_out_sh = (sh_clients, None, None, None, None)
        else:
            sh_clients = sh_server = None
            self._fit_in_sh = self._fit_out_sh = None
            self._eval_in_sh = self._eval_out_sh = None
        self._sh_client_states = sh_clients
        self._sh_server_state = sh_server
        # Donation (mirroring fit_chunk's donate_argnums=(0,1), per
        # arXiv:2004.13336's reuse-the-replica-buffers rule): the full
        # client-weight stack and server state are updated IN PLACE each
        # round instead of copied — halves the steady-state footprint of the
        # big-cohort configs and removes an alloc+copy from the hot path.
        # CONTRACT for every caller: treat the passed-in states as INVALID
        # after the call — always replace them with the returned ones.
        # (Donation is gated off the CPU backend — see _donate_argnums —
        # but call sites must stay donation-safe for the TPU path; the
        # sharded builds route through the SAME gating.) eval donates only
        # the client stack: its server_state flows on to
        # update_after_eval/test-eval on the caller side.
        self._fit_round = b.jit(
            self._fit_round_fn, donate=(0, 1),
            in_shardings=self._fit_in_sh, out_shardings=self._fit_out_sh,
        )
        self._eval_round = b.jit(
            self._eval_round_fn, donate=(1,),
            in_shardings=self._eval_in_sh, out_shardings=self._eval_out_sh,
        )
        self._fit_round_fn_t = self._eval_round_fn_t = None
        self._fit_round_t = self._eval_round_t = None
        if self._telemetry_enabled:
            self._fit_round_fn_t, self._eval_round_fn_t = (
                self._build_round_fns(True)
            )
            # telemetry variants append ONE output (RoundTelemetry / the
            # per-client non-finite eval count) — unconstrained placement
            fit_out_t = (self._fit_out_sh + (None,)
                         if self._fit_out_sh is not None else None)
            eval_out_t = (self._eval_out_sh + (None,)
                          if self._eval_out_sh is not None else None)
            self._fit_round_t = b.jit(
                self._fit_round_fn_t, donate=(0, 1),
                in_shardings=self._fit_in_sh, out_shardings=fit_out_t,
            )
            self._eval_round_t = b.jit(
                self._eval_round_fn_t, donate=(1,),
                in_shardings=self._eval_in_sh, out_shardings=eval_out_t,
            )
        self._chunked_fit = None  # compiled lazily by make_chunked_fit
        self._chunked_fit_eval = None  # compiled lazily (fit()'s chunked route)
        # cohort chunked-scan program (in-graph draw + window exchange),
        # compiled lazily by _make_cohort_chunk — cohort runs only
        self._cohort_chunk_jit = None
        # Buffered-async programs (compiled lazily by _make_async_programs /
        # _make_async_chunked — only ever built when async_config is set,
        # so a synchronous simulation compiles exactly the pre-async set)
        self._async_prologue_jit = None
        self._async_event_jit = None
        self._async_chunked_jit = None
        self._async_plan = None  # the run's static event plan (host numpy)
        self._async_pending = None  # in-flight update buffer (device tree)

    def _build_client_fns(self, collect_telemetry: bool):
        """Build the client-level (client_fit, client_eval) closures —
        pull -> local train -> push, and pull -> eval. ONE definition
        shared by the synchronous round programs (:meth:`_build_round_fns`)
        and the buffered-async event programs (:meth:`_build_async_fns`),
        so async and sync rounds run bit-identical client math by
        construction."""
        logic, tx, strategy, exchanger = self.logic, self.tx, self.strategy, self.exchanger
        loss_keys = ("backward", *self._extra_keys())
        if collect_telemetry:
            # logic-declared telemetry channels (e.g. the DP clip fraction)
            # enter the loss meter only on the telemetry build — the plain
            # programs stay exactly as before
            loss_keys += tuple(
                k for k in getattr(logic, "telemetry_loss_keys", ())
                if k not in loss_keys
            )
        if self.early_stopping is not None:
            es_train = engine.make_local_train_with_early_stopping(
                logic, tx, self.metrics, self.early_stopping, loss_keys,
                collect_telemetry=collect_telemetry,
                precision=self.precision,
            )
            train = None
        elif self.flash_early_stopping is not None:
            from fl4health_tpu.clients.flash import make_flash_local_train

            # flash's gamma-rule train has no telemetry accumulator: engine
            # stats come back NaN (update_norm/divergence/nonfinite still
            # measure — they are computed outside the train scan)
            es_train = make_flash_local_train(
                logic, tx, self.metrics, self.flash_early_stopping, loss_keys,
                precision=self.precision,
            )
            train = None
        else:
            es_train = None
            train = engine.make_local_train(
                logic, tx, self.metrics, loss_keys,
                collect_telemetry=collect_telemetry,
                precision=self.precision,
            )
        evaluate = engine.make_local_eval(logic, self.metrics, ("checkpoint", *self._eval_keys()))

        evaluate_after_fit = getattr(strategy, "evaluate_after_fit", False)

        wants_packet = getattr(exchanger, "wants_packet_payload", False)
        scaling_active = self._precision_scaling

        def client_fit(state: TrainState, payload, batches: Batch, participate,
                       val_batches: Batch):
            orig = state
            payload_params = payload.params if hasattr(payload, "params") else payload
            pull_src = payload if wants_packet else payload_params
            pulled = exchanger.pull(pull_src, state.params)
            state = state.replace(params=pulled)
            ctx = logic.init_round_context(state, payload)
            if es_train is not None:
                outs = es_train(state, ctx, batches, val_batches)
            else:
                outs = train(state, ctx, batches)
            if len(outs) == 5:
                new_state, losses, metrics, n_steps, engine_telem = outs
            else:
                new_state, losses, metrics, n_steps = outs
                engine_telem = (
                    telem.nan_engine_telemetry() if collect_telemetry else None
                )
            if evaluate_after_fit:
                # pre-aggregation local validation (FedDG-GA's
                # evaluate_after_fit=True requirement, feddg_ga.py:205-210)
                post_fit_losses, _ = evaluate(new_state, ctx, val_batches)
                losses = {**losses, "val_checkpoint_post_fit": post_fit_losses["checkpoint"]}
            client_telem = None
            if collect_telemetry:
                # update norm against the pulled globals, on the TRAINED
                # state (pre participation-masking: a non-participant's row
                # is garbage-by-construction and the watchdog filters by
                # mask, exactly like the loss rows)
                client_telem = {
                    **engine_telem,
                    "update_norm": telem.global_norm_diff(
                        new_state.params, pulled
                    ),
                }
            # non-participants neither pull nor train (their packet row is
            # garbage but aggregation hard-zeroes masked rows)
            new_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(participate > 0, n, o), new_state, orig
            )
            if collect_telemetry and scaling_active:
                # cumulative skipped-optimizer-step count from the carried
                # scaler state, AFTER participation masking (a
                # non-participant reports its carried value, not garbage)
                client_telem["loss_scale_skips"] = new_state.loss_scale[
                    "skipped"
                ]
            pushed = exchanger.push(new_state.params, pulled)
            packet = logic.pack(new_state, pushed, losses)
            if collect_telemetry:
                return new_state, packet, losses, metrics, client_telem
            return new_state, packet, losses, metrics

        def client_eval(state: TrainState, payload, batches: Batch):
            payload_params = payload.params if hasattr(payload, "params") else payload
            pull_src = payload if wants_packet else payload_params
            pulled = exchanger.pull(pull_src, state.params)
            st = state.replace(params=pulled)
            ctx = logic.init_round_context(st, payload)
            losses, metrics = evaluate(st, ctx, batches)
            return st, losses, metrics

        return client_fit, client_eval

    def _build_round_fns(self, collect_telemetry: bool):
        """Build (fit_round, eval_round) closures. With ``collect_telemetry``
        each appends one extra output — fit_round a :class:`RoundTelemetry`
        pytree, eval_round the per-client non-finite eval-loss count — all
        derived from values the program already computes, so the training
        math (and thus the loss trajectory) is bit-identical either way.

        ``fit_round`` carries one OPTIONAL trailing ``sample_counts``
        parameter: every historical caller omits it (the closure bakes
        ``self.sample_counts`` exactly as before), while the sweep engine's
        cell programs (``fl4health_tpu/sweep/``) pass it as a TRACED input
        so cells whose data partitions (and thus per-client train-set
        sizes) differ still share one compiled program."""
        client_fit, client_eval = self._build_client_fns(collect_telemetry)
        strategy = self.strategy
        baked_sample_counts = self.sample_counts

        # Chaos layer (resilience/faults.py): compiled into the round
        # program so the same seeded plan injects identical faults on both
        # execution modes. With no plan (or an empty one) neither branch
        # traces — the closure is exactly the pre-resilience program.
        fault_plan = self._fault_plan
        inject_dropout = (fault_plan is not None
                          and bool(getattr(fault_plan, "dropout_faults", ())))
        inject_corruption = (
            fault_plan is not None
            and bool(getattr(fault_plan, "corruption_faults", ()))
        )
        n_clients = self.n_clients

        def fit_round(server_state, client_states, batches, mask, round_idx,
                      val_batches, sample_counts=None):
            if sample_counts is None:
                sample_counts = baked_sample_counts
            payload = strategy.client_payload(server_state, round_idx)
            if inject_dropout:
                # a dropped client is exactly an unsampled one: mask math,
                # never a shape change
                mask = mask * fault_plan.participation_factor(
                    round_idx, n_clients
                )
            vmapped = jax.vmap(client_fit, in_axes=(0, None, 0, 0, 0))(
                client_states, payload, batches, mask, val_batches
            )
            if collect_telemetry:
                new_states, packets, losses, metrics, client_telem = vmapped
            else:
                new_states, packets, losses, metrics = vmapped
            if inject_corruption:
                # corrupt the WIRE update (what aggregation consumes), not
                # the client's local state — byzantine clients train
                # honestly and lie upstream, the standard attack model
                payload_params = (payload.params if hasattr(payload, "params")
                                  else payload)
                packets = fault_plan.corrupt_packets(
                    packets, payload_params, round_idx, n_clients
                )
            # Failed clients (non-finite loss) are excluded from aggregation,
            # matching the reference where failures never enter results
            # (strategies/basic_fedavg.py:254-256 skips on failures; here the
            # per-client row is masked out so the aggregate stays clean).
            finite = jnp.isfinite(losses.get("backward", jnp.zeros_like(mask)))
            agg_mask = mask * finite.astype(mask.dtype)
            results = FitResults(
                packets=packets,
                sample_counts=sample_counts,
                train_losses=losses,
                train_metrics=metrics,
                mask=agg_mask,
            )
            with stage_attr.stage("server_update"):
                new_server_state = strategy.aggregate(
                    server_state, results, round_idx
                )
            w = results.mask * sample_counts
            agg_losses = {
                # where() not multiply: an excluded client's NaN loss must not
                # poison the weighted mean (NaN * 0 == NaN).
                k: jnp.sum(jnp.where(results.mask > 0, v, 0.0) * w)
                / jnp.maximum(jnp.sum(w), 1.0)
                for k, v in losses.items()
            }
            agg_metrics = aggregate_metrics(metrics, sample_counts, results.mask)
            if not collect_telemetry:
                return new_server_state, new_states, agg_losses, agg_metrics, losses
            nan_row = jnp.full_like(
                jnp.asarray(losses["backward"], jnp.float32), jnp.nan
            )
            round_telemetry = RoundTelemetry(
                train_loss=jnp.asarray(losses["backward"], jnp.float32),
                train_loss_min=client_telem["train_loss_min"],
                train_loss_max=client_telem["train_loss_max"],
                grad_norm_mean=client_telem["grad_norm_mean"],
                grad_norm_max=client_telem["grad_norm_max"],
                update_norm=client_telem["update_norm"],
                clip_fraction=losses.get("clip_fraction", nan_row),
                nonfinite_params=telem.per_client_nonfinite(new_states.params),
                nonfinite_loss=telem.nonfinite_in_losses(losses),
                divergence=telem.per_client_divergence(
                    new_states.params,
                    strategy.divergence_reference(new_server_state),
                ),
                nonfinite_eval_loss=jnp.zeros_like(nan_row),
                # fp16 scaler visibility: cumulative skipped-step count per
                # client; None (an empty pytree node) without loss scaling,
                # so legacy telemetry records keep their exact shape
                loss_scale_skips=client_telem.get("loss_scale_skips"),
            )
            return (new_server_state, new_states, agg_losses, agg_metrics,
                    losses, round_telemetry)

        def eval_round(server_state, client_states, batches, eval_counts):
            gp = strategy.client_payload(server_state, jnp.zeros((), jnp.int32))
            new_states, losses, metrics = jax.vmap(client_eval, in_axes=(0, None, 0))(
                client_states, gp, batches
            )
            agg_losses = {
                k: jnp.sum(v * eval_counts) / jnp.maximum(jnp.sum(eval_counts), 1.0)
                for k, v in losses.items()
            }
            agg_metrics = aggregate_metrics(metrics, eval_counts)
            if collect_telemetry:
                return (new_states, agg_losses, agg_metrics, losses, metrics,
                        telem.nonfinite_in_losses(losses))
            return new_states, agg_losses, agg_metrics, losses, metrics

        return fit_round, eval_round

    def _extra_keys(self):
        # explicit constructor keys win; else the logic's declared keys
        if self._extra_loss_keys:
            return self._extra_loss_keys
        return getattr(self.logic, "extra_loss_keys", ())

    def _eval_keys(self):
        if self._eval_loss_keys:
            return self._eval_loss_keys
        return getattr(self.logic, "eval_loss_keys", ())

    # ------------------------------------------------------------------
    def _round_plan(self, round_idx: int):
        """Host-side index plan (numpy idx/example_mask/step_mask) for one
        round — the same plan whether gathered per round (``fit``) or stacked
        for the on-device multi-round scan (``fit_chunk``)."""
        entropies = [
            [*self._base_entropy, 1000 + round_idx, i] for i in range(self.n_clients)
        ]
        return engine.multi_client_index_plans(
            entropies,
            [d.n_train for d in self.datasets],
            self.batch_size,
            n_steps=self.local_steps,
            local_epochs=self.local_epochs,
        )

    def _sharded_train_banks(self):
        """The [C, ...] train banks staged onto the clients axis, cached
        until ``set_train_data`` swaps them. The chunked programs take the
        banks as jit inputs with ``in_shardings`` pinned to P("clients"),
        so passing the unsharded construction-time banks would reshard the
        FULL per-client data bank — a cross-device copy of every client's
        whole dataset — on every chunk dispatch. Without a mesh this
        returns the banks untouched. (The banks themselves must stay
        unsharded for the pipelined prefetcher — see the construction-time
        comment.)"""
        sh = self._program_builder.client_sharding()
        if sh is None:
            return self._x_train_stack, self._y_train_stack
        cached = self._sharded_banks_cache
        if (cached is not None and cached[0] is self._x_train_stack
                and cached[1] is self._y_train_stack):
            return cached[2], cached[3]
        xs = self._program_builder.put(self._x_train_stack, sh)
        ys = self._program_builder.put(self._y_train_stack, sh)
        self._sharded_banks_cache = (
            self._x_train_stack, self._y_train_stack, xs, ys
        )
        return xs, ys

    def _round_batches(self, round_idx: int) -> Batch:
        idx, em, sm = self._round_plan(round_idx)
        return engine.gather_batches(
            self._x_train_stack, self._y_train_stack, idx, em, sm
        )

    # ------------------------------------------------------------------
    def make_chunked_fit(self):
        """Compile a multi-round scan: ONE dispatch executes k federated
        rounds entirely on device, gathering each round's batches inside the
        scan from the resident data stacks. Each round's math is exactly
        ``_fit_round``'s on the same host index plans and the same per-round
        participation masks, so the trajectory matches the per-round path
        bit-for-bit — including sampled partial participation
        (tests/server/test_chunked_fit.py).

        NOT a drop-in for ``fit`` beyond that: the per-round failure-policy
        check / checkpointing / reporting — host-sync work — do not run
        inside the scan. Participation DOES match ``fit``: per-round masks
        are drawn host-side with the same PRNG stream and scanned over.

        The returned callable DONATES its first two arguments (server_state,
        client_states): on TPU the passed-in buffers are invalidated — always
        replace them with the outputs, as ``fit_chunk`` does. (CPU ignores
        donation, so misuse is only visible on device backends.)

        This is the SURVEY §7 "keep entire rounds (or multi-round chunks)
        on-device" lever: over a tunneled/remote TPU each dispatch costs a
        host round trip, and amortizing it across k rounds removes the
        per-round dispatch latency from the hot loop. Used by ``fit_chunk``
        and the bench.
        """
        if self._chunked_fit is not None:
            return self._chunked_fit
        fit_round = self._fit_round_fn

        def chunk(server_state, client_states, x_stack, y_stack, idx, em, sm,
                  masks, start_round, val_batches):
            def body(carry, per_round):
                server_state, client_states, r = carry
                idx_r, em_r, sm_r, mask_r = per_round
                batches = engine.gather_batches(x_stack, y_stack, idx_r, em_r, sm_r)
                server_state, client_states, losses, metrics, _ = fit_round(
                    server_state, client_states, batches, mask_r, r, val_batches
                )
                return (server_state, client_states, r + 1), (losses, metrics)

            (server_state, client_states, _), (losses, metrics) = jax.lax.scan(
                body, (server_state, client_states, start_round),
                (idx, em, sm, masks),
            )
            return server_state, client_states, losses, metrics

        # Donate the carried states: the caller always replaces them with the
        # scan's outputs, so XLA can update the (large, client-stacked)
        # buffers in place instead of allocating a second copy — on a 16GB
        # chip that halves the peak footprint of the big-cohort configs.
        # (No-op on CPU; data stacks are NOT donated.)
        b = self._program_builder
        in_sh = out_sh = None
        if b.mesh is not None:
            cs = b.client_sharding()
            scs = b.stacked_client_sharding()
            in_sh = (self._sh_server_state, self._sh_client_states, cs, cs,
                     scs, scs, scs, scs, b.replicated(), cs)
            out_sh = (self._sh_server_state, self._sh_client_states,
                      None, None)
        self._chunked_fit = b.jit(
            chunk, donate=(0, 1), in_shardings=in_sh, out_shardings=out_sh
        )
        return self._chunked_fit

    def fit_chunk(self, start_round: int, k: int, mask=None):
        """Run rounds [start_round, start_round+k) in one compiled dispatch.
        Returns per-round stacked (losses, metrics) dicts; updates the
        simulation state in place.

        Incompatible with ``train_data_provider``: the chunk bakes its data
        stacks at dispatch time, so per-round host refresh cannot happen
        inside it — raising beats silently training k rounds on a frozen
        bank.

        Participation matches ``fit``: each round's mask is drawn from the
        same PRNG stream (fold_in(rng, 2000+round)) via the client manager.
        Pass ``mask`` ([clients] or [k, clients]) to pin it instead."""
        if self.train_data_provider is not None:
            raise ValueError(
                "fit_chunk cannot honor train_data_provider (per-round data "
                "refresh happens on the host, between dispatches); use "
                "fit(), or chunk with the provider disabled if a frozen "
                "bank is acceptable"
            )
        chunked = self.make_chunked_fit()
        plans = [self._round_plan(start_round + i) for i in range(k)]
        idx = jnp.asarray(np.stack([p[0] for p in plans]))
        em = jnp.asarray(np.stack([p[1] for p in plans]))
        sm = jnp.asarray(np.stack([p[2] for p in plans]))
        if mask is None:
            masks = jnp.stack([
                self.client_manager.sample(
                    jax.random.fold_in(self.rng, 2000 + start_round + i),
                    start_round + i,
                )
                for i in range(k)
            ])
        else:
            mask = jnp.asarray(mask)
            if mask.shape not in ((k, self.n_clients), (self.n_clients,)):
                raise ValueError(
                    f"fit_chunk mask must have shape ({k}, {self.n_clients}) "
                    f"or ({self.n_clients},); got {mask.shape}"
                )
            masks = mask if mask.ndim == 2 else jnp.broadcast_to(
                mask, (k,) + mask.shape
            )
        val_batches, _ = self._val_batches()
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        x_bank, y_bank = self._sharded_train_banks()
        self.server_state, self.client_states, losses, metrics = chunked(
            self.server_state, self.client_states,
            x_bank, y_bank, idx, em, sm, masks,
            jnp.asarray(start_round, jnp.int32), val_batches,
        )
        return losses, metrics

    def _make_chunked_fit_with_eval(self):
        """Compile fit()'s chunked route: a multi-round scan whose body runs
        the SAME fit_round + eval_round (+ optional test eval) sequence as
        one pipelined round — so a chunked fit() produces the same
        RoundRecord trajectory as the per-round path, in ONE dispatch for
        the whole run. Donates the carried states like make_chunked_fit.

        With telemetry enabled the scan body runs the telemetry round
        variants and stacks each round's :class:`RoundTelemetry` into the
        outputs — per-round training-health metrics ride the run's single
        fused device->host pull."""
        if self._chunked_fit_eval is not None:
            return self._chunked_fit_eval
        telemetry_on = self._telemetry_enabled
        fit_round = self._fit_round_fn_t if telemetry_on else self._fit_round_fn
        eval_round = self._eval_round_fn_t if telemetry_on else self._eval_round_fn
        quarantine_fn = (getattr(self.strategy, "quarantine_mask", None)
                         if self.observability.enabled else None)

        def chunk(server_state, client_states, x_stack, y_stack, idx, em, sm,
                  masks, start_round, val_batches, val_counts,
                  test_batches=None, test_counts=None):
            def body(carry, per_round):
                server_state, client_states, r = carry
                idx_r, em_r, sm_r, mask_r = per_round
                batches = engine.gather_batches(x_stack, y_stack, idx_r, em_r, sm_r)
                fit_outs = fit_round(
                    server_state, client_states, batches, mask_r, r,
                    val_batches,
                )
                round_telemetry = None
                if telemetry_on:
                    (server_state, client_states, fit_losses, fit_metrics,
                     per_fit, round_telemetry) = fit_outs
                else:
                    (server_state, client_states, fit_losses, fit_metrics,
                     per_fit) = fit_outs
                # mirror _run_round: post-aggregation eval refreshes the
                # client stack with the pulled global params
                ev_outs = eval_round(
                    server_state, client_states, val_batches, val_counts
                )
                if telemetry_on:
                    (client_states, ev_losses, ev_metrics, _pl, _pm,
                     ev_nonfinite) = ev_outs
                    round_telemetry = round_telemetry.replace(
                        nonfinite_eval_loss=ev_nonfinite
                    )
                else:
                    client_states, ev_losses, ev_metrics, _pl, _pm = ev_outs
                out = {
                    "fit_losses": fit_losses,
                    "fit_metrics": fit_metrics,
                    "per_client_fit_losses": per_fit,
                    "eval_losses": ev_losses,
                    "eval_metrics": ev_metrics,
                }
                if round_telemetry is not None:
                    out["telemetry"] = round_telemetry
                if quarantine_fn is not None:
                    # per-round in-graph quarantine mask stacks with the
                    # scan outputs — same fused pull, per-round visibility
                    out["quarantine"] = quarantine_fn(server_state)
                if test_batches is not None:
                    t_outs = eval_round(
                        server_state, client_states, test_batches, test_counts
                    )
                    out["test_losses"] = t_outs[1]
                    out["test_metrics"] = t_outs[2]
                return (server_state, client_states, r + 1), out

            (server_state, client_states, _), outs = jax.lax.scan(
                body, (server_state, client_states, start_round),
                (idx, em, sm, masks),
            )
            return server_state, client_states, outs

        b = self._program_builder
        in_sh = out_sh = None
        if b.mesh is not None:
            cs = b.client_sharding()
            scs = b.stacked_client_sharding()
            in_sh = (self._sh_server_state, self._sh_client_states, cs, cs,
                     scs, scs, scs, scs, b.replicated(), cs, cs)
            if self._test_batches() is not None:
                # arity must match the dispatch: test args ride along
                in_sh = in_sh + (cs, cs)
            out_sh = (self._sh_server_state, self._sh_client_states, None)
        self._chunked_fit_eval = b.jit(
            chunk, donate=(0, 1), in_shardings=in_sh, out_shardings=out_sh
        )
        return self._chunked_fit_eval

    # -- buffered-async programs (server/async_schedule.py) -------------
    def _build_async_fns(self, collect_telemetry: bool):
        """Build the (async_prologue, async_event) closures of the
        buffered-async mode (FedBuff-style, arXiv:2106.06639).

        One buffer-fill EVENT replaces one synchronous round:

            consume  — the K arrived updates (a row of the static event
                       plan) aggregate under the staleness-discounted
                       fractional mask (``FedBuff.async_aggregation_mask``);
            eval     — the post-aggregation global evaluates exactly like
                       a synchronous round's eval;
            restart  — the consumed clients pull the fresh global and run
                       their next local training, whose packet sits in the
                       carried ``pending`` buffer until a later event
                       consumes it.

        The prologue is event 0's missing half: every client trains from
        the initial global on data plan 1, filling ``pending``. Ordering
        (aggregate -> eval -> restart-on-post-eval-states) deliberately
        mirrors the synchronous round sequence, which is what makes the
        ``K = cohort, no stragglers`` plan bit-identical to sync fit() —
        same client math (shared ``_build_client_fns`` closures), same
        aggregation arithmetic, same round indices."""
        client_fit, _ = self._build_client_fns(collect_telemetry)
        _, eval_round = self._build_round_fns(collect_telemetry)
        strategy = self.strategy
        fault_plan = self._fault_plan
        inject_dropout = (fault_plan is not None
                          and bool(getattr(fault_plan, "dropout_faults", ())))
        inject_corruption = (
            fault_plan is not None
            and bool(getattr(fault_plan, "corruption_faults", ()))
        )
        n_clients = self.n_clients
        sample_counts = self.sample_counts
        # over the registry, a slot's sample count is a property of its
        # OCCUPANT — and aggregation consumes packets trained under a
        # possibly-evicted occupant, so the counts must ride the pending
        # buffer with the packet instead of being a closure constant
        cohort_active = self._cohort_active
        async_mask = getattr(strategy, "async_aggregation_mask", None)
        if async_mask is not None:
            import inspect

            # duck-typed hooks with the pre-hoisting 2-arg signature keep
            # working: only pass the traced exponent where it is accepted.
            # The exponent is passed POSITIONALLY, so only positional-
            # capable parameters count (**kwargs can never absorb it).
            _params = inspect.signature(async_mask).parameters.values()
            _positional = sum(
                1 for p in _params
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            )
            _takes_exponent = (_positional >= 3 or any(
                p.kind == p.VAR_POSITIONAL for p in _params
            ))
            if not _takes_exponent:
                raw_mask = async_mask
                async_mask = lambda arr, stal, _exp: raw_mask(arr, stal)  # noqa: E731
            elif not hasattr(strategy, "staleness_exponent"):
                # an exponent-taking hook on a strategy WITHOUT the
                # attribute would receive the 0.0 dispatch fallback —
                # (1+s)^0 = 1, silently no discounting; fail loudly
                raise ValueError(
                    f"{type(strategy).__name__}.async_aggregation_mask "
                    "accepts an exponent argument but the strategy exposes "
                    "no 'staleness_exponent' attribute for the async round "
                    "programs to feed it from; expose the attribute (as "
                    "FedBuff does), or drop the parameter to use internal "
                    "defaults"
                )
        quarantine_fn = (getattr(strategy, "quarantine_mask", None)
                         if self.observability.enabled else None)

        def train_wave(server_state, client_states, batches, train_mask,
                       round_idx, val_batches, wave_counts=None):
            """One training wave on data plan ``round_idx``: pull the
            current payload, locally train the masked clients, corrupt the
            wire packets with the SAME seeded round draws the sync path
            uses. Returns (new client stack, this wave's pending pieces).
            ``wave_counts`` (registry occupancy only) pins the per-slot
            sample counts the wave trained under into the pending buffer."""
            payload = strategy.client_payload(server_state, round_idx)
            vmapped = jax.vmap(client_fit, in_axes=(0, None, 0, 0, 0))(
                client_states, payload, batches, train_mask, val_batches
            )
            if collect_telemetry:
                new_states, packets, losses, metrics, client_telem = vmapped
            else:
                new_states, packets, losses, metrics = vmapped
                client_telem = None
            if inject_corruption:
                payload_params = (payload.params
                                  if hasattr(payload, "params") else payload)
                packets = fault_plan.corrupt_packets(
                    packets, payload_params, round_idx, n_clients
                )
            pending = {"packets": packets, "losses": losses,
                       "metrics": metrics}
            if cohort_active:
                pending["sample_counts"] = (
                    sample_counts if wave_counts is None else wave_counts
                )
            if collect_telemetry:
                pending["telem"] = client_telem
            return new_states, pending

        def merge_pending(old, new, arrivals):
            """Per-leaf arrival-masked merge: an arrived client's slot
            takes its fresh wave output; everyone else's in-flight update
            stays buffered untouched."""
            def sel(n, o):
                a = arrivals.reshape((-1,) + (1,) * (n.ndim - 1))
                return jnp.where(a > 0, n, o)

            return jax.tree_util.tree_map(sel, new, old)

        def async_prologue(server_state, client_states, batches, val_batches,
                           wave_counts=None):
            ones = jnp.ones((n_clients,), jnp.float32)
            return train_wave(
                server_state, client_states, batches, ones,
                jnp.asarray(1, jnp.int32), val_batches, wave_counts,
            )

        def async_event(server_state, client_states, pending, batches_next,
                        arrivals, staleness, event_idx, val_batches,
                        val_counts, staleness_exponent,
                        test_batches=None, test_counts=None,
                        wave_counts=None):
            # -- consume: staleness-discounted aggregation of the buffer --
            # staleness_exponent is a TRACED scalar input (fed from the
            # live strategy attribute at each dispatch), so an exponent
            # sweep/rebind reuses this compiled program — the sweep
            # engine's scalar-hoisting contract
            arr = arrivals
            if inject_dropout:
                # a dropped update is lost on the wire: it fills its buffer
                # slot but aggregates with weight 0 (the client restarts
                # normally, keeping the static plan's bookkeeping exact)
                arr = arr * fault_plan.participation_factor(
                    event_idx, n_clients
                )
            disc_mask = (async_mask(arr, staleness, staleness_exponent)
                         if async_mask is not None else arr)
            finite = jnp.isfinite(
                pending["losses"].get("backward", jnp.zeros_like(arr))
            )
            agg_mask = disc_mask * finite.astype(disc_mask.dtype)
            # the counts the buffered packets TRAINED under (they rode the
            # pending buffer on the registry path — occupancy may have
            # changed since); the dense path's closure constant otherwise
            counts = (pending["sample_counts"] if cohort_active
                      else sample_counts)
            results = FitResults(
                packets=pending["packets"],
                sample_counts=counts,
                train_losses=pending["losses"],
                train_metrics=pending["metrics"],
                mask=agg_mask,
            )
            new_server = strategy.aggregate(server_state, results, event_idx)
            w = results.mask * counts
            agg_losses = {
                k: jnp.sum(jnp.where(results.mask > 0, v, 0.0) * w)
                / jnp.maximum(jnp.sum(w), 1.0)
                for k, v in pending["losses"].items()
            }
            agg_metrics = aggregate_metrics(
                pending["metrics"], counts, results.mask
            )
            round_telemetry = None
            if collect_telemetry:
                # telemetry describes the CONSUMED updates (like the loss
                # record): engine stats ride the pending buffer from train
                # time; divergence/nonfinite measure the live stack against
                # the fresh aggregate, exactly the sync definitions
                pt = pending["telem"]
                nan_row = jnp.full_like(
                    jnp.asarray(pending["losses"]["backward"], jnp.float32),
                    jnp.nan,
                )
                round_telemetry = RoundTelemetry(
                    train_loss=jnp.asarray(
                        pending["losses"]["backward"], jnp.float32
                    ),
                    train_loss_min=pt["train_loss_min"],
                    train_loss_max=pt["train_loss_max"],
                    grad_norm_mean=pt["grad_norm_mean"],
                    grad_norm_max=pt["grad_norm_max"],
                    update_norm=pt["update_norm"],
                    clip_fraction=pending["losses"].get(
                        "clip_fraction", nan_row
                    ),
                    nonfinite_params=telem.per_client_nonfinite(
                        client_states.params
                    ),
                    nonfinite_loss=telem.nonfinite_in_losses(
                        pending["losses"]
                    ),
                    divergence=telem.per_client_divergence(
                        client_states.params,
                        strategy.divergence_reference(new_server),
                    ),
                    nonfinite_eval_loss=jnp.zeros_like(nan_row),
                    loss_scale_skips=pt.get("loss_scale_skips"),
                )
            # -- eval: the fresh global, synchronous-round semantics ------
            ev_outs = eval_round(
                new_server, client_states, val_batches, val_counts
            )
            if collect_telemetry:
                (client_states, ev_losses, ev_metrics, _pl, _pm,
                 ev_nonfinite) = ev_outs
                round_telemetry = round_telemetry.replace(
                    nonfinite_eval_loss=ev_nonfinite
                )
            else:
                client_states, ev_losses, ev_metrics, _pl, _pm = ev_outs
            out = {
                "fit_losses": agg_losses,
                "fit_metrics": agg_metrics,
                "per_client_fit_losses": pending["losses"],
                "eval_losses": ev_losses,
                "eval_metrics": ev_metrics,
            }
            if round_telemetry is not None:
                out["telemetry"] = round_telemetry
            if quarantine_fn is not None:
                out["quarantine"] = quarantine_fn(new_server)
            if test_batches is not None:
                t_outs = eval_round(
                    new_server, client_states, test_batches, test_counts
                )
                client_states = t_outs[0]
                out["test_losses"] = t_outs[1]
                out["test_metrics"] = t_outs[2]
            # -- restart: consumed clients train for a later event --------
            # data plan event_idx+1 and the matching fault draws — the
            # index stream a synchronous round event_idx+1 would use
            client_states, fresh = train_wave(
                new_server, client_states, batches_next, arrivals,
                event_idx + 1, val_batches, wave_counts,
            )
            pending = merge_pending(pending, fresh, arrivals)
            return new_server, client_states, pending, out

        return async_prologue, async_event

    def _make_async_programs(self):
        """Jit the per-event async programs (the pipelined path). The
        prologue keeps its server-state input alive (event 1 consumes it);
        the event program donates all three carried trees."""
        if self._async_event_jit is not None:
            return self._async_prologue_jit, self._async_event_jit
        prologue, event = self._build_async_fns(self._telemetry_enabled)
        b = self._program_builder
        pro_in = ev_in = ev_out = None
        if b.mesh is not None:
            cs = b.client_sharding()
            rep = b.replicated()
            sh_c, sh_s = self._sh_client_states, self._sh_server_state
            pro_in = (sh_s, sh_c, cs, cs)
            ev_in = (sh_s, sh_c, cs, cs, cs, cs, rep, cs, cs, rep)
            if self._test_batches() is not None:
                ev_in = ev_in + (cs, cs)
            ev_out = (sh_s, sh_c, cs, None)
        self._async_prologue_jit = b.jit(
            prologue, donate=(1,),
            in_shardings=pro_in,
            out_shardings=(self._sh_client_states, b.client_sharding())
            if b.mesh is not None else None,
        )
        self._async_event_jit = b.jit(
            event, donate=(0, 1, 2), in_shardings=ev_in, out_shardings=ev_out,
        )
        return self._async_prologue_jit, self._async_event_jit

    def _make_async_chunked(self):
        """Compile the async chunked route: ONE lax.scan dispatch walks the
        whole static event plan — per-event arrivals/staleness rows and
        data plans scan over the carried (server, clients, pending) trees,
        so a buffered-async run costs two dispatches total (prologue +
        scan) exactly like the synchronous chunked path costs one."""
        if self._async_chunked_jit is not None:
            return self._async_chunked_jit
        _, event = self._build_async_fns(self._telemetry_enabled)

        def chunk(server_state, client_states, pending, x_stack, y_stack,
                  idx, em, sm, arrivals, staleness, start_event,
                  val_batches, val_counts, staleness_exponent,
                  test_batches=None, test_counts=None):
            def body(carry, per_event):
                server_state, client_states, pending, e = carry
                idx_r, em_r, sm_r, arr_r, stal_r = per_event
                batches_next = engine.gather_batches(
                    x_stack, y_stack, idx_r, em_r, sm_r
                )
                server_state, client_states, pending, out = event(
                    server_state, client_states, pending, batches_next,
                    arr_r, stal_r, e, val_batches, val_counts,
                    staleness_exponent, test_batches, test_counts,
                )
                return (server_state, client_states, pending, e + 1), out

            (server_state, client_states, pending, _e), outs = jax.lax.scan(
                body,
                (server_state, client_states, pending, start_event),
                (idx, em, sm, arrivals, staleness),
            )
            # pending is RETURNED: the next chunk (checkpoint boundary)
            # carries it forward, and the boundary snapshot persists it
            return server_state, client_states, pending, outs

        b = self._program_builder
        in_sh = out_sh = None
        if b.mesh is not None:
            cs = b.client_sharding()
            scs = b.stacked_client_sharding()
            in_sh = (self._sh_server_state, self._sh_client_states, cs,
                     cs, cs, scs, scs, scs, scs, scs, b.replicated(),
                     cs, cs, b.replicated())
            if self._test_batches() is not None:
                in_sh = in_sh + (cs, cs)
            out_sh = (self._sh_server_state, self._sh_client_states, cs,
                      None)
        self._async_chunked_jit = b.jit(
            chunk, donate=(0, 1, 2), in_shardings=in_sh, out_shardings=out_sh
        )
        return self._async_chunked_jit

    def _eval_split_batches(self, x_stack, y_stack, ns) -> tuple[Batch, jax.Array]:
        """Shared val/test eval batching: fixed-order full pass + counts —
        one implementation so both splits always score under the same rules."""
        idx, em, sm = engine.multi_client_index_plans(
            [[0]] * self.n_clients, ns, self.batch_size, shuffle=False
        )
        batches = engine.gather_batches(x_stack, y_stack, idx, em, sm)
        return batches, jnp.asarray(ns, jnp.float32)

    def _val_batches(self) -> tuple[Batch, jax.Array]:
        if self._val_cache is None:
            batches, counts = self._eval_split_batches(
                self._x_val_stack, self._y_val_stack,
                [engine.data_rows(d.x_val) for d in self.datasets],
            )
            # sharded staging (no-op without a mesh): the cache is reused
            # every round, so the clients-axis split is paid once here
            # instead of on each dispatch's implicit reshard
            batches = self._program_builder.put(
                batches, self._program_builder.client_sharding()
            )
            self._val_cache = (batches, counts)
        return self._val_cache

    def _test_batches(self) -> tuple[Batch, jax.Array] | None:
        """Separate test split (basic_client.py:867 test loader; metrics ride
        with eval under a "test - " prefix, base_server.py:545
        _unpack_metrics). Present only when EVERY client provides one
        (validated in __init__)."""
        if not self._has_test_split:
            return None
        if self._test_cache is None:
            x_stack = engine.pad_and_stack_data(
                [d.x_test for d in self.datasets], "x_test"
            )
            y_stack = engine.pad_and_stack_data(
                [d.y_test for d in self.datasets], "y_test"
            )
            batches, counts = self._eval_split_batches(
                x_stack, y_stack, [engine.data_rows(d.x_test) for d in self.datasets]
            )
            batches = self._program_builder.put(
                batches, self._program_builder.client_sharding()
            )
            self._test_cache = (batches, counts)
        return self._test_cache

    # ------------------------------------------------------------------
    def _chunk_ineligibility(self) -> str | None:
        """Why fit() may NOT route through the on-device chunked scan
        (None = eligible). Anything that needs the host between rounds
        forces the pipelined per-round path."""
        if self._cohort_active:
            # cohort-slot runs chunk too (the in-graph draw + window
            # exchange replace the per-round host gather/scatter) — only
            # the combinations that genuinely need the host between
            # sampled rounds still demote:
            if self._async_active:
                return ("buffered-async over the registry swaps slot "
                        "occupants host-side per event (pipelined "
                        "per-event path)")
            if getattr(self.client_manager, "draw_cohort", None) is None:
                return (f"{type(self.client_manager).__name__} provides no "
                        "in-graph draw_cohort; the cohort draw must run on "
                        "the host every round")
            if self.recovery_policy is not None:
                return ("recovery supervision refreshes the quarantine "
                        "keep-mask against the live registry every round")
            if self.mesh_config is not None:
                return ("mesh + cohort stages each round's slot tensors "
                        "with sharded per-round device_put; the chunk's "
                        "window exchange is unsharded")
        if self.train_data_provider is not None:
            return "train_data_provider needs a host data refresh every round"
        if self.model_checkpointers:
            return "per-round model checkpointing needs per-round host access"
        # Durable state checkpointing no longer demotes the chunked path:
        # snapshot-capable checkpointers save at chunk boundaries (the run
        # dispatches in checkpoint_every-round chunks and the snapshot
        # rides the existing boundary host touch). Only the legacy
        # sim-reading API — save_simulation(sim, round) against LIVE state
        # every round — still needs the per-round loop.
        if (self.state_checkpointer is not None
                and not hasattr(self.state_checkpointer,
                                "save_simulation_snapshot")):
            return ("legacy state checkpointer (save_simulation reads live "
                    "per-round state)")
        if not self.failure_policy.accept_failures:
            return "accept_failures=False must be able to terminate mid-run"
        # Observability per se no longer demotes the chunked path: in-graph
        # telemetry rides the scan outputs and the per-round gauges/JSONL
        # records are reconstructed from the stacked pull. Only the two
        # hooks that intrinsically need per-round dispatch still force the
        # pipelined path.
        if (self.observability.enabled
                and self.observability.profile_round_idx is not None
                and self.observability.output_dir is not None):
            # without an output_dir maybe_profile() is a guaranteed no-op —
            # demoting for it would cost the fast path and capture nothing
            return ("opt-in XProf capture (profile_round_idx) wraps one "
                    "round's dispatch")
        if self.observability.enabled and self.observability.per_round_spans:
            return ("per-round span fencing requested "
                    "(Observability(per_round_spans=True))")
        # wrapper strategies (e.g. resilience.QuarantiningStrategy) override
        # update_after_eval only to delegate — they declare whether the
        # WRAPPED strategy actually consumes per-round eval on the host
        overrides = getattr(self.strategy, "overrides_update_after_eval", None)
        if overrides is None:
            overrides = (type(self.strategy).update_after_eval
                         is not Strategy.update_after_eval)
        if overrides:
            return ("strategy overrides update_after_eval (host-side "
                    "per-round eval consumption)")
        return None

    def _select_execution_mode(self, n_rounds: int) -> tuple[str, str]:
        """(mode, reason) for this fit() call. 'auto' prefers the chunked
        scan (fastest: zero per-round host work) and falls back to the
        pipelined path with the blocking reason attached."""
        if n_rounds < 1:
            # graceful no-op for every mode (the pipelined loop simply runs
            # zero rounds) — fit(0) must not raise even when chunked is forced
            return EXEC_PIPELINED, "n_rounds < 1 (no rounds to run)"
        if self.execution_mode == "pipelined":
            return EXEC_PIPELINED, "forced by execution_mode='pipelined'"
        why = self._chunk_ineligibility()
        if self.execution_mode == "chunked":
            if why:
                raise ValueError(f"execution_mode='chunked' but {why}")
            return EXEC_CHUNKED, "forced by execution_mode='chunked'"
        if why:
            return EXEC_PIPELINED, why
        if self.observability.enabled and self.observability.admin is not None:
            # the admin plane retunes at per-round host boundaries; a
            # chunked dispatch has none. Only the AUTO path demotes —
            # forcing 'chunked' with an armed plane stays legal, and the
            # endpoint rejects submits with a structured mid_chunk error.
            return EXEC_PIPELINED, (
                "admin retune endpoint armed (live scalar rebinds apply "
                "at per-round boundaries)"
            )
        return EXEC_CHUNKED, "auto: no per-round host dependencies"

    def fit(self, n_rounds: int) -> list[RoundRecord]:
        if self.recovery_policy is not None:
            # self-healing mode: the RecoverySupervisor re-enters
            # _fit_unsupervised after each recoverable abnormal end
            # (rollback via the checkpoint ring, rung mitigation, resume)
            if self._recovery_supervisor is None:
                from fl4health_tpu.resilience.supervisor import (
                    RecoverySupervisor,
                )

                self._recovery_supervisor = RecoverySupervisor(
                    self, self.recovery_policy
                )
            return self._recovery_supervisor.run(n_rounds)
        return self._fit_unsupervised(n_rounds)

    def _fit_unsupervised(self, n_rounds: int) -> list[RoundRecord]:
        """One fit attempt with no recovery wrapper — the pre-supervisor
        ``fit()`` body (also the supervisor's per-attempt entry point)."""
        if self.profile_dir is not None:
            with jax.profiler.trace(self.profile_dir):
                return self._fit_loop(n_rounds)
        return self._fit_loop(n_rounds)

    def _reset_to_initial(self) -> None:
        """Roll the live training state back to the constructor's
        seed-derived init — the recovery supervisor's rollback when no
        durable checkpoint generation predates a failure. ``self.rng`` is
        never mutated by ``fit()`` (every draw is a pure ``fold_in``), so
        ``_init_states`` reproduces the fresh states bit-identically."""
        if self._cohort_active:
            self.registry.reset_rows()
        self._init_states()
        self.history = []
        self._async_pending = None
        # from-scratch rollback: lifetime records of the abandoned
        # trajectory's rounds must not survive into the replay (they
        # would double-count participation)
        ledger = self.observability.fleet_ledger
        if ledger is not None:
            ledger.clear()

    def _apply_recovery_keep(self, mask, rnd: int):
        """Multiply a round's sampling mask by the recovery supervisor's
        quarantine keep-mask. A pure pass-through (the exact input object)
        when no supervisor is attached or nothing is quarantined, so
        armed-but-never-engaged runs stay bit-identical."""
        sup = self._recovery_supervisor
        if sup is None:
            return mask
        keep = sup.keep_mask(rnd, self.n_clients)
        if keep is None:
            return mask
        return mask * jnp.asarray(keep, jnp.float32)

    def _apply_admin_retunes(self, rnd: int) -> None:
        """Round-boundary hook (producer thread, every pipelined path):
        drain the admin plane's pending/scheduled retunes and rebind them
        on the live run — state-kind scalars through the same
        ``apply_state_scalars`` the sweep uses (a server-state leaf swap:
        zero recompiles), live-attr scalars (async staleness exponent) via
        setattr picked up by the next dispatch. A no-op without an armed
        plane, so the default path stays bit-identical."""
        obs = self.observability
        admin = obs.admin if obs.enabled else None
        if admin is None:
            return
        values = admin.drain(rnd)
        if not values:
            return
        from fl4health_tpu.sweep import hoisting

        try:
            state_vals = {
                n: v for n, v in values.items()
                if hoisting.binding(n).kind == "state"
            }
            if state_vals:
                self.server_state = hoisting.apply_state_scalars(
                    self.strategy, self.server_state, state_vals
                )
            for name, value in values.items():
                if name not in state_vals:
                    b = hoisting.binding(name)
                    setattr(b.find(self.strategy), b.attr, float(value))
        except Exception:
            # submit() validated against this strategy chain, so this is a
            # race (e.g. strategy swapped between submit and drain) — a bad
            # retune must not kill a training run
            logging.getLogger(__name__).warning(
                "admin retune %r failed to apply at round %d",
                values, rnd, exc_info=True,
            )
            return
        admin.note_applied(rnd, values)
        obs.update_manifest({"admin": admin.descriptor()})

    def _note_recovery_round(self, rnd: int) -> None:
        """Round-epilogue hook (every execution path, after the watchdog
        passed): drives the supervisor's probation window and quarantine
        releases. No-op without a supervisor."""
        sup = self._recovery_supervisor
        if sup is not None:
            sup.note_round(rnd)

    def _fit_loop(self, n_rounds: int) -> list[RoundRecord]:
        obs = self.observability
        obs.start()  # re-arm after a previous fit()'s shutdown (idempotent)
        flight = obs.flight_recorder if obs.enabled else None
        if flight is not None:
            flight.clear()  # the black box records THIS run only
        fleet = obs.fleet_ledger if obs.enabled else None
        if fleet is not None:
            # fresh fit(): the ledger starts empty; _maybe_resume below
            # restores the checkpointed as-of state when resuming, so
            # re-run rounds absorb exactly once
            fleet.clear()
        self._last_epilogue_round = None  # per-run (RoundConsumer progress)
        mode, mode_reason = self._select_execution_mode(n_rounds)
        self._active_execution_mode = mode
        self._round_program_flops = None  # re-measured per fit() (mode-shaped)
        self._last_quarantine = None  # transition accounting is per-run
        self._cohort_quarantine = None
        logging.getLogger(__name__).info(
            "fit: execution_mode=%s (%s)", mode, mode_reason
        )
        # Resume BEFORE the manifest/introspection: a restored run's
        # manifest carries its `resume` descriptor, and the chunked paths
        # size their dispatches from the remaining rounds. The async event
        # plan is derived first — the resume must fingerprint-verify the
        # consumed prefix against it.
        plan = None
        if self._async_active and n_rounds >= 1:
            from fl4health_tpu.server.async_schedule import (
                build_event_plan,
                build_registry_event_plan,
            )

            if self._cohort_active:
                # FedBuff over the registry: the slot-level schedule plus
                # the deterministic seating ledger (who occupies each slot
                # per restart wave)
                plan = build_registry_event_plan(
                    self.async_config, n_rounds, self.n_clients,
                    self.registry_size, self._fault_plan,
                )
            else:
                plan = build_event_plan(
                    self.async_config, n_rounds, self.n_clients,
                    self._fault_plan,
                )
            self._async_plan = plan
        try:
            start_round = self._maybe_resume(n_rounds, plan)
        except BaseException as resume_exc:
            # a failed restore (all generations corrupt, config mismatch)
            # still publishes its evidence and disarms the hooks this
            # fit() armed — a CheckpointCorruptError IS a postmortem
            self._dump_postmortem(resume_exc)
            obs.shutdown()
            raise
        if self._recovery_supervisor is not None:
            # post-restore hook: the supervisor re-applies its pending
            # mitigations (in-graph quarantine seeding, hoisted-scalar
            # overrides) onto the freshly restored state and keeps
            # /healthz at 503 while a recovery is mid-probation
            self._recovery_supervisor.on_resume(start_round)
        if obs.watchdog is not None and not self._telemetry_enabled:
            logging.getLogger(__name__).warning(
                "HealthWatchdog attached but in-graph telemetry is off "
                "(Observability(enabled=%s, telemetry=%s)) — no health "
                "checks will run.", obs.enabled, obs.telemetry,
            )
        if obs.enabled and obs.admin is not None:
            # arm the admin plane against THIS run: validation needs the
            # live strategy chain + execution mode (a chunked run rejects
            # submits with a structured mid_chunk error), and the manifest
            # must disclose the plane from round 0 for replayability
            obs.admin.bind_run(self.strategy, mode,
                               async_active=self._async_active)
            obs.update_manifest({"admin": obs.admin.descriptor()})
        if obs.enabled:
            obs.log_event("execution_mode", mode=mode, reason=mode_reason)
            if self._program_builder.mesh is not None:
                # one-time mesh gauges: a scraped metrics page can divide
                # any aggregate number down to per-chip without the manifest
                mesh_shape = dict(self._program_builder.mesh.shape)
                obs.registry.gauge(
                    "fl_mesh_devices",
                    help="devices backing the round-program mesh",
                ).set(float(self._program_builder.n_devices))
                obs.registry.gauge(
                    "fl_mesh_client_axis",
                    help="size of the 'clients' (data-parallel) mesh axis",
                ).set(float(self._program_builder.client_axis_size))
                obs.registry.gauge(
                    "fl_mesh_model_axis",
                    help="size of the 'model' (tensor-parallel) mesh axis",
                ).set(float(mesh_shape.get("model", 1)))
            # run manifest (served live at /manifest when http_port is set,
            # exported as manifest.json): provenance that makes a scraped
            # metrics page interpretable — versions, chip, mode, config hash
            try:
                extra = None
                if self._resume_info is not None:
                    # resumed runs disclose where they picked up — the key
                    # is absent on fresh runs so legacy manifests are stable
                    extra = {"resume": dict(self._resume_info)}
                obs.update_manifest(run_manifest(
                    execution_mode=mode,
                    execution_mode_reason=mode_reason,
                    donation=bool(_donate_argnums(0, 1)),
                    mesh=self._program_builder.descriptor(),
                    config=self._manifest_config(n_rounds),
                    extra=extra,
                ))
            except Exception:
                logging.getLogger(__name__).warning(
                    "run manifest construction failed", exc_info=True
                )
            if obs.introspection and n_rounds >= 1 and not self._async_active:
                # compiled-program introspection at BUILD time: XLA
                # cost/memory analysis, compile wall, cache attribution —
                # zero per-round cost, measured MFU for every round record.
                # (Async runs skip it: the event programs' work varies with
                # the consumed buffer, so a single per-round FLOP number
                # would be dishonest — staleness/cadence metrics carry the
                # async story instead.)
                with obs.span("introspect", cat="fit"):
                    # the chunked path dispatches checkpoint_every-round
                    # chunks when a snapshot checkpointer is attached —
                    # introspect the program shape fit() will actually run
                    self._introspect_programs(
                        mode, self._rounds_per_dispatch(n_rounds, start_round)
                    )
        if flight is not None:
            # run-level provenance for the bundle header ("run" in
            # ring.msgpack): what was executing when the box was opened
            facts: dict[str, Any] = {
                "execution_mode": mode,
                "execution_mode_reason": mode_reason,
                "n_rounds": n_rounds,
                "start_round": start_round,
                "config_hash": obs.manifest.get("config_hash"),
            }
            if self._cohort_active:
                facts["cohort_slots"] = self.n_clients
                facts["registry_size"] = self.registry_size
            if self._async_active:
                facts["async"] = True
            flight.set_run_facts(**facts)
        for r in self.reporters:
            r.report({"host_type": "server", "fit_start": time.time(),
                      "num_rounds": n_rounds, "execution_mode": mode,
                      "execution_mode_reason": mode_reason})
        self._sigterm_round = None

        def _note_sigterm() -> None:
            # runs INSIDE the signal handler: the round the run was at
            # when SIGTERM arrived — the teardown drains that follow may
            # legitimately record later rounds, but the verdict names
            # this. LOCK-FREE read: the handler can interrupt the very
            # thread holding the recorder lock (chunked-mode epilogues
            # record on the main thread) — taking it here would deadlock.
            if flight is not None:
                self._sigterm_round = flight.last_round_hint

        try:
            # SIGTERM trap (flight recorder armed only): a preemption
            # becomes a SigtermShutdown raised in the main thread, so the
            # except below publishes the black box and every finally
            # (checkpoint flush, consumer close) still runs — then the
            # process exits with the conventional 143.
            with (trap_sigterm(on_signal=_note_sigterm)
                  if flight is not None else contextlib.nullcontext()):
                if self._async_active and n_rounds >= 1:
                    self._fit_async(n_rounds, mode, plan, start_round)
                elif self._cohort_active:
                    # both routes handle n_rounds < 1 themselves (graceful
                    # no-op) — the dense pipelined fallback would touch
                    # the absent banks
                    if mode == EXEC_CHUNKED:
                        self._fit_cohort_chunked(n_rounds, start_round)
                    else:
                        self._fit_cohort(n_rounds, start_round)
                elif mode == EXEC_CHUNKED:
                    self._fit_chunked(n_rounds, start_round)
                else:
                    self._fit_pipelined(n_rounds, start_round)
        except BaseException as e:
            # ANY abnormal end — TrainingHealthError/ClientFailuresError/
            # QuorumError, an unhandled exception, a SIGTERM — publishes a
            # self-contained postmortem bundle BEFORE obs.shutdown() below
            # clears the trace/event evidence. Never masks the original
            # failure.
            self._dump_postmortem(e)
            raise
        finally:
            # shutdown (not just export) ALWAYS runs — even when a round
            # raises (ClientFailuresError): it detaches the compile monitor
            # and releases/clears the tracer this run enabled, so a retry in
            # the same process doesn't double-count compiles, and the failed
            # run's trace/metrics (the run you most want to inspect) still
            # land on disk.
            artifacts = obs.shutdown()
        for rep in self.reporters:
            if artifacts:
                rep.report({"observability_artifacts": dict(artifacts)})
            rep.report({"fit_end": time.time()})
            rep.shutdown()
        return self.history

    def _manifest_config(self, n_rounds: int) -> dict:
        """JSON-able run-config facts for the manifest's ``config_hash`` —
        the experiment identity two scrapes can be matched on."""
        config = {
            "n_clients": self.n_clients,
            "batch_size": self.batch_size,
            "local_epochs": self.local_epochs,
            "local_steps": self.local_steps,
            "n_rounds": n_rounds,
            "strategy": type(self.strategy).__name__,
            "exchanger": type(self.exchanger).__name__,
            "client_manager": type(self.client_manager).__name__,
            "execution_mode": self.execution_mode,
            "telemetry": self._telemetry_enabled,
            "compression": (self.compression.describe()
                            if self._compression_active else None),
            # precision identity: an f32 and a bf16 run of the same recipe
            # are different experiments — and the dtype the manifest names
            # is the one the fl_program_*/MFU numbers were produced under
            "precision": (self.precision.describe()
                          if self._precision_active else None),
        }
        if self._cohort_active:
            # cohort-slot identity belongs in the config hash (a slot run
            # and a dense run are different programs; resume templates are
            # sized by the slot count); key absent on dense builds so
            # legacy hashes stay stable
            config["cohort"] = {
                "slots": self.cohort_config.slots,
                "registry_size": self.registry_size,
            }
        if self._async_active:
            # async identity belongs in the config hash (a buffered-async
            # and a synchronous run of the same recipe are different
            # experiments); key absent on sync builds so legacy hashes
            # stay stable
            config["async"] = self.async_config.describe()
        if self._program_builder.mesh is not None:
            # mesh identity belongs in the config hash (a sharded and an
            # unsharded run of the same recipe are different experiments);
            # key absent on single-chip builds so legacy hashes are stable
            config["mesh"] = self._program_builder.descriptor()
        return config

    # -- crash-consistent checkpoint/resume ------------------------------
    def _resume_config_hash(self) -> str:
        """The resume-relevant experiment identity a checkpoint binds to:
        the manifest config minus the knobs that may legitimately differ
        between an interrupted run and its resume — ``n_rounds`` (resuming
        with more rounds is the point), ``execution_mode`` (trajectories
        are pinned identical across modes, so cross-mode resume is legal),
        ``telemetry`` (observability never changes the trajectory) and
        ``mesh`` (placement, not math — restored arrays are re-sharded
        onto whatever mesh the resuming run deploys)."""
        cfg = {
            k: v for k, v in self._manifest_config(0).items()
            if k not in ("n_rounds", "execution_mode", "telemetry", "mesh")
        }
        return config_hash(cfg)

    def adopt_restored_state(self, server_state, client_states,
                             pending=None) -> None:
        """Install restored (host numpy) trees as the live training state.
        Under a mesh the arrays are ``device_put`` back onto the round
        programs' ``NamedSharding``s — the same placement a fresh build
        pins via in_shardings — so the first resumed dispatch never pays an
        implicit gather-and-reshard; single-chip runs get one committed
        device transfer instead of a per-dispatch host upload."""
        b = self._program_builder
        if b.mesh is not None:
            server_state = b.put(server_state, self._sh_server_state)
            client_states = b.put(client_states, self._sh_client_states)
            if pending is not None:
                pending = b.put(pending, b.client_sharding())
        else:
            server_state = jax.device_put(server_state)
            client_states = jax.device_put(client_states)
            if pending is not None:
                pending = jax.device_put(pending)
        self.server_state = server_state
        self.client_states = client_states
        if pending is not None:
            self._async_pending = pending

    def _ckpt_every(self) -> int | None:
        """The attached snapshot checkpointer's save cadence in rounds
        (None when no snapshot-capable checkpointer is attached)."""
        sc = self.state_checkpointer
        if sc is None or not hasattr(sc, "save_simulation_snapshot"):
            return None
        return max(int(getattr(sc, "checkpoint_every", 1) or 1), 1)

    def _rounds_per_dispatch(self, n_rounds: int, start_round: int = 1) -> int:
        """Scan length of the chunked path's next dispatch: all remaining
        rounds, capped at ``checkpoint_every`` when snapshots are due at
        chunk boundaries."""
        remaining = max(n_rounds - start_round + 1, 1)
        every = self._ckpt_every()
        return remaining if every is None else min(every, remaining)

    def _checkpoint_due(self, rnd: int) -> bool:
        every = self._ckpt_every()
        if every is None:
            return False
        return rnd % every == 0 or rnd >= self._fit_n_rounds

    def _async_pending_template(self, val_batches):
        """Host-shaped template of the async ``pending`` buffer (the tree
        the prologue produces), via ``jax.eval_shape`` — no device work, no
        prologue dispatch — for deserializing a restored buffer into."""
        prologue, _ = self._build_async_fns(self._telemetry_enabled)
        batches1 = self._round_batches(1)
        _states_sds, pending_sds = jax.eval_shape(
            prologue, self.server_state, self.client_states, batches1,
            val_batches,
        )
        return jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), pending_sds
        )

    def _maybe_resume(self, n_rounds: int, plan=None) -> int:
        """Bind the checkpointer to this run (config hash + metrics hook)
        and restore the newest good generation when one exists. Returns the
        first round/event to run (1 on a fresh start). Sets
        ``self._resume_info`` for the manifest's ``resume`` descriptor."""
        self._resume_info = None
        sc = self.state_checkpointer
        if sc is None:
            return 1
        # bind the frame's config hash + fl_ckpt_* metrics hook once —
        # explicit user-set values win
        if getattr(sc, "config_hash", "absent") is None:
            sc.config_hash = self._resume_config_hash()
        if getattr(sc, "on_save", "absent") is None:
            sc.on_save = self._emit_checkpoint_stats
        if not (hasattr(sc, "exists") and sc.exists()):
            return 1
        if self._async_active:
            if n_rounds < 1:
                return 1
            val_batches, _ = self._val_batches()
            template = self._async_pending_template(val_batches)
            start = sc.load_async_simulation(self, template, plan)
        elif self._cohort_active:
            # cohort resume: slot states + the registry's dirty rows —
            # every participated client's persistent state survives
            start = sc.load_cohort_simulation(self)
        elif hasattr(sc, "load_simulation"):
            # fit_with_per_round_checkpointing resume (base_server.py:143-229)
            start = sc.load_simulation(self)
        else:
            return 1
        info = getattr(sc, "last_restore_info", None)
        self._resume_info = {
            "next_round": int(start),
            "kind": ("async" if self._async_active
                     else "cohort" if self._cohort_active else "sync"),
        }
        if info is not None:
            self._resume_info.update(
                path=info.path, generation=info.generation,
                bytes=info.nbytes,
                fallback_skipped=list(info.fallback_skipped),
            )
        obs = self.observability
        if obs.enabled:
            reg = obs.registry
            reg.counter(
                "fl_ckpt_restores_total",
                help="state-checkpoint restores (resumed runs)",
            ).inc()
            if info is not None and info.fallback_skipped:
                reg.counter(
                    "fl_ckpt_fallbacks_total",
                    help="corrupt checkpoint generations skipped by the "
                         "retention-ring fallback at restore",
                ).inc(len(info.fallback_skipped))
            obs.log_event("resume", **self._resume_info)
        logging.getLogger(__name__).info(
            "resumed from checkpoint: next %s %d",
            "event" if self._async_active else "round", start,
        )
        return start

    def _emit_checkpoint_stats(self, stats: dict) -> None:
        """``fl_ckpt_*`` metrics + one ``checkpoint`` JSONL event per
        durable save. Runs on whichever thread persisted the frame (the
        async writer under the pipelined loop) — the registry is
        thread-safe and this hook never raises into the writer."""
        obs = self.observability
        if not obs.enabled:
            return
        reg = obs.registry
        reg.counter(
            "fl_ckpt_writes_total", help="durable state-checkpoint writes",
        ).inc()
        reg.counter(
            "fl_ckpt_bytes_written_total",
            help="bytes of durable state-checkpoint frames written",
        ).inc(int(stats.get("bytes", 0)))
        reg.counter(
            "fl_ckpt_write_seconds_total",
            help="wall seconds spent serializing+writing state checkpoints "
                 "(off the round loop under the async writer)",
        ).inc(float(stats.get("write_s", 0.0)))
        reg.gauge(
            "fl_ckpt_last_write_ms",
            help="wall milliseconds of the most recent checkpoint write",
        ).set(float(stats.get("write_s", 0.0)) * 1000.0)
        reg.gauge(
            "fl_ckpt_generation",
            help="newest durable checkpoint generation in the retention ring",
        ).set(float(stats.get("generation", 0)))
        reg.log_event(
            "checkpoint",
            round=stats.get("round"),
            generation=stats.get("generation"),
            bytes=stats.get("bytes"),
            write_ms=round(float(stats.get("write_s", 0.0)) * 1000.0, 3),
            path=stats.get("path"),
            kind=stats.get("kind", "sync"),
        )
        flight = obs.flight_recorder
        if flight is not None:
            # the bundle's "what to resume from": newest durable generation
            flight.note_checkpoint(stats)

    def _dump_postmortem(self, exc: BaseException) -> None:
        """Best-effort postmortem bundle for an abnormal ``fit()`` end
        (``observability/bundle.py``): classify ``exc`` into a verdict,
        publish ``postmortem_<ts>/`` under the observability output dir,
        and flip ``/healthz`` to 503. By the time the exception reaches
        here the fit paths' ``finally`` blocks have closed the
        RoundConsumer (draining pending epilogues into the flight ring)
        and flushed the checkpoint writer — the ring is as complete as the
        process can make it. NEVER raises: the primary failure propagates
        untouched."""
        obs = self.observability
        if not obs.enabled or obs.output_dir is None:
            return
        try:
            from fl4health_tpu.observability.bundle import (
                verdict_from_exception,
            )

            verdict = verdict_from_exception(
                exc, recorder=obs.flight_recorder
            )
            if (verdict.get("kind") == "sigterm"
                    and getattr(self, "_sigterm_round", None) is not None):
                # the handler's snapshot wins over the recorder's current
                # last round: drains during unwind may have run past it
                verdict["round"] = self._sigterm_round
            if getattr(self, "_last_epilogue_round", None) is not None:
                # pipelined runs: which round's epilogue last FINISHED —
                # evidence beyond it died with the in-flight rounds
                verdict["epilogues_through_round"] = (
                    self._last_epilogue_round
                )
            path = obs.dump_bundle(verdict)
            if path:
                obs.log_event(
                    "postmortem", path=path,
                    kind=verdict.get("kind"), round=verdict.get("round"),
                )
                logging.getLogger(__name__).warning(
                    "abnormal end (%s) — postmortem bundle published at %s",
                    verdict.get("kind"), path,
                )
        except Exception:
            logging.getLogger(__name__).warning(
                "postmortem bundle dump failed (the primary exception "
                "propagates)", exc_info=True,
            )

    def _close_ckpt_writer(self, writer) -> None:
        """Close the async checkpoint writer on EVERY exit path and surface
        its stored failure without masking an in-flight exception.
        ``close()`` drains the queue before joining, so a run that halts
        (``TrainingHealthError``, ``ClientFailuresError``) still publishes
        its last completed-round checkpoint before the error propagates."""
        writer.close()
        in_flight = sys.exc_info()[1] is not None
        try:
            writer.raise_pending()
        except BaseException:
            if not in_flight:
                raise
            logging.getLogger(__name__).warning(
                "checkpoint write failed during error shutdown (the "
                "primary exception propagates)", exc_info=True,
            )

    @contextlib.contextmanager
    def _ckpt_writer_scope(self, active: bool,
                           attach_model_ckpts: bool = False):
        """THE async-checkpoint-writer lifecycle, shared by every fit path:
        yields a fresh :class:`AsyncCheckpointWriter` (or None when
        ``active`` is False), flushes it on clean exit, and on EVERY exit —
        error paths included — drains+closes it, surfaces stored write
        failures without masking an in-flight exception
        (:meth:`_close_ckpt_writer`), detaches any model checkpointers and
        resets ``self._ckpt_writer``."""
        if not active:
            yield None
            return
        writer = self._ckpt_writer = AsyncCheckpointWriter()
        attached = []
        if attach_model_ckpts:
            for _mode, ckpt in self.model_checkpointers:
                if hasattr(ckpt, "async_writer"):
                    ckpt.async_writer = writer
                    attached.append(ckpt)
        try:
            yield writer
            writer.flush()  # clean exit: every submitted write is durable
        finally:
            try:
                self._close_ckpt_writer(writer)
            finally:
                for ckpt in attached:
                    ckpt.async_writer = None
                self._ckpt_writer = None

    def _introspect_programs(self, mode: str, n_rounds: int) -> None:
        """Capture XLA cost/memory analysis for the round programs this
        ``fit()`` will dispatch (``observability/introspect.py``).

        Lowering happens against abstract ``ShapeDtypeStruct`` args, so no
        device work runs and the training trajectory cannot change; the
        compile goes through XLA's cached-compile path, so with the
        persistent compilation cache the later jit dispatch of the same
        program is a disk hit, not a second backend compile (without the
        cache this is one extra build-time compile per program — never a
        per-round cost). Failures degrade to a warning: introspection must
        not take down a run."""
        obs = self.observability
        intro = obs.introspector
        mesh_desc = self._program_builder.descriptor()
        prec_desc = (self.precision.describe() if self._precision_active
                     else None)
        try:
            if self._cohort_active:
                # slot programs lower against ABSTRACT slot shapes — by
                # construction a function of (slots, step budgets, batch,
                # example shape), never of the registry size: the
                # fl_program_* flops/peak-HBM numbers ARE the O(K) proof
                # (pinned across registry sizes by tests)
                aa = self.registry.abstract_round_args(self.n_clients)
                r = jnp.asarray(1, jnp.int32)
                t = self._telemetry_enabled
                fit_fn = self._fit_round_t if t else self._fit_round
                eval_fn = self._eval_round_t if t else self._eval_round
                fit_name = "fit_round_t" if t else "fit_round"
                eval_name = "eval_round_t" if t else "eval_round"
                intro.introspect_jit(
                    fit_name, fit_fn,
                    (self.server_state, self.client_states, aa["batches"],
                     aa["mask"], r, aa["val_batches"],
                     aa["sample_counts"]),
                    mesh=mesh_desc, precision=prec_desc,
                )
                intro.introspect_jit(
                    eval_name, eval_fn,
                    (self.server_state, self.client_states,
                     aa["val_batches"], aa["val_counts"]),
                    mesh=mesh_desc, precision=prec_desc,
                )
                self._round_program_flops = intro.round_flops(
                    (fit_name, eval_name)
                )
                if mode == EXEC_CHUNKED:
                    # the chunk scan program too: its report carries the
                    # per-dispatch facts (rounds_per_dispatch, the
                    # in-graph draw site) the O(rounds/R) claim quotes
                    kd = self._rounds_per_dispatch(n_rounds)
                    ca = self.registry.abstract_chunk_args(
                        self.n_clients, kd
                    )
                    w = ca["window_ids"].shape[0]
                    as_window = lambda t: jax.tree_util.tree_map(  # noqa: E731
                        lambda a: jax.ShapeDtypeStruct(
                            (w,) + jnp.shape(a)[1:], jnp.result_type(a)
                        ), t,
                    )
                    w_client = as_window(self.client_states)
                    w_srows = (
                        as_window(self.strategy.state_rows(
                            self.server_state
                        ))
                        if self.registry.has_strategy_rows else {}
                    )
                    intro.introspect_jit(
                        "fit_cohort_chunk", self._make_cohort_chunk(),
                        (self.server_state, self.client_states, w_client,
                         w_srows, self.rng, ca["window_ids"],
                         ca["batches"], ca["mask"], ca["sample_counts"],
                         ca["val_batches"], ca["val_counts"], r),
                        rounds_per_dispatch=kd, cohort_draw="in_graph",
                        mesh=mesh_desc, precision=prec_desc,
                    )
                intro.hbm_headroom_bytes()
                return
            val_batches, val_counts = self._val_batches()
            mask = self.client_manager.sample(
                jax.random.fold_in(self.rng, 2000 + 1), 1
            )
            r = jnp.asarray(1, jnp.int32)
            test = self._test_batches()
            if mode == EXEC_CHUNKED:
                p_idx, p_em, p_sm = self._round_plan(1)

                def stacked_sds(a):
                    a1 = jnp.asarray(a)
                    return jax.ShapeDtypeStruct((n_rounds,) + a1.shape, a1.dtype)

                args = [self.server_state, self.client_states,
                        self._x_train_stack, self._y_train_stack,
                        stacked_sds(p_idx), stacked_sds(p_em),
                        stacked_sds(p_sm),
                        jax.ShapeDtypeStruct((n_rounds,) + mask.shape,
                                             mask.dtype),
                        r, val_batches, val_counts]
                if test is not None:
                    args.extend(test)
                intro.introspect_jit(
                    "fit_chunk_eval", self._make_chunked_fit_with_eval(),
                    tuple(args), rounds_per_dispatch=n_rounds,
                    mesh=mesh_desc, precision=prec_desc,
                )
                names: tuple[str, ...] = ("fit_chunk_eval",)
            else:
                idx, em, sm = self._round_plan(1)
                batches = jax.eval_shape(
                    engine.gather_batches, self._x_train_stack,
                    self._y_train_stack, idx, em, sm,
                )
                t = self._telemetry_enabled
                fit_fn = self._fit_round_t if t else self._fit_round
                eval_fn = self._eval_round_t if t else self._eval_round
                fit_name = "fit_round_t" if t else "fit_round"
                eval_name = "eval_round_t" if t else "eval_round"
                intro.introspect_jit(
                    fit_name, fit_fn,
                    (self.server_state, self.client_states, batches, mask,
                     r, val_batches),
                    mesh=mesh_desc, precision=prec_desc,
                )
                intro.introspect_jit(
                    eval_name, eval_fn,
                    (self.server_state, self.client_states, val_batches,
                     val_counts),
                    mesh=mesh_desc, precision=prec_desc,
                )
                names = (fit_name, eval_name)
                if test is not None:
                    # same eval program, test-split shapes -> its own
                    # executable, so it gets its own report
                    test_name = eval_name + "_test"
                    intro.introspect_jit(
                        test_name, eval_fn,
                        (self.server_state, self.client_states,
                         test[0], test[1]),
                        mesh=mesh_desc, precision=prec_desc,
                    )
                    names = names + (test_name,)
            self._round_program_flops = intro.round_flops(names)
            intro.hbm_headroom_bytes()
        except Exception:
            logging.getLogger(__name__).warning(
                "compiled-program introspection failed (continuing without "
                "measured MFU)", exc_info=True,
            )

    # -- pipelined per-round path --------------------------------------
    def _fit_pipelined(self, n_rounds: int, start_round: int = 1) -> None:
        """The per-round path, pipelined: each round the producer (this
        thread) dispatches fit+eval and hands the round's results — one
        fused device tree plus any host snapshots donation would otherwise
        invalidate — to a background RoundConsumer that runs the host
        epilogue for round r while the device executes round r+1. The next
        round's batches are prefetched concurrently. ``start_round`` > 1
        continues a restored run (``_maybe_resume``)."""
        obs = self.observability
        with obs.span("setup", cat="fit"):
            val_batches, val_counts = self._val_batches()
        self._fit_n_rounds = n_rounds
        # the round program donates the states — break any Python-level
        # buffer aliasing once; round outputs stay alias-free thereafter
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        # the writer scope flushes on clean exit and, on error exits,
        # drains + surfaces write failures without masking the in-flight
        # exception — a halted run still publishes its last
        # completed-round checkpoint
        with self._ckpt_writer_scope(
            bool(self.model_checkpointers
                 or self.state_checkpointer is not None),
            attach_model_ckpts=True,
        ):
            consumer = self._consumer = RoundConsumer(
                maxsize=self.pipeline_depth
            )
            # per-round data staging is SHARDED under a mesh: the
            # prefetcher's device_put splits the gathered [C, ...] batch
            # stack over the clients axis while the previous round still
            # runs
            prefetcher = self._prefetcher = RoundPrefetcher(self)
            try:
                if start_round <= n_rounds:
                    prefetcher.schedule(start_round)
                for rnd in range(start_round, n_rounds + 1):
                    consumer.raise_pending()
                    # opt-in XProf capture of ONE round (profile_round_idx)
                    with obs.maybe_profile(rnd):
                        self._run_round(rnd, val_batches, val_counts)
                consumer.flush()  # barrier: every round's epilogue has run
            finally:
                consumer.close()
                prefetcher.close()
                # retained for the postmortem verdict: which round's host
                # epilogue last FINISHED before this run ended
                self._last_epilogue_round = consumer.last_completed_round
                self._consumer = None
                self._prefetcher = None

    def _run_round(self, rnd: int, val_batches, val_counts) -> None:
        """Producer half of one federated round: configure_fit -> fit
        dispatch -> eval dispatch, then submit the host epilogue
        (_finish_round) to the RoundConsumer. All device_get of results
        happens in the consumer (results are fresh outputs, never donated
        into a later round, so they stay valid); only checkpoint/state
        snapshots — whose buffers round r+1's donation WILL invalidate —
        are pulled here."""
        obs = self.observability
        consumer = self._consumer
        prefetcher = self._prefetcher
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            # compile accounting baseline: delta over the round = recompiles
            # (shape drift re-paying XLA compiles is THE classic round-loop bug)
            compiles_before = obs.registry.counter("jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total"
            ).value
        device_wait_s = 0.0
        t0 = time.time()
        with obs.span("round", round=rnd):
            with obs.span("configure_fit", round=rnd):
                if self.train_data_provider is not None:
                    fresh = self.train_data_provider(rnd)
                    if fresh is not None:
                        self.set_train_data(*fresh)
                # admin-plane retunes land HERE — a per-round host boundary
                # before anything reads server_state, after the provider (so
                # a submit issued synchronously from it applies this round)
                self._apply_admin_retunes(rnd)
                mask = self.client_manager.sample(
                    jax.random.fold_in(self.rng, 2000 + rnd), rnd
                )
                if obs.watchdog is not None:
                    # host-side mitigation (HealthPolicy action="mitigate"):
                    # clients the watchdog quarantined are sampled out of
                    # later rounds. None while nothing is quarantined, so
                    # the un-mitigated mask values stay untouched. With the
                    # pipeline running depth rounds ahead, a new quarantine
                    # takes effect once the producer catches up (pipelined
                    # path only — in-graph quarantine covers the chunked
                    # scan, resilience/quarantine.py).
                    keep = obs.watchdog.quarantine_keep_mask(self.n_clients)
                    if keep is not None:
                        mask = mask * jnp.asarray(keep, jnp.float32)
                # recovery-supervisor quarantine (resilience/supervisor.py):
                # suspects a past engagement named stay sampled out until
                # their release round; a pass-through when idle
                mask = self._apply_recovery_keep(mask, rnd)
                batches = (prefetcher.take(rnd) if prefetcher is not None
                           else self._round_batches(rnd))
            if prefetcher is not None and rnd < self._fit_n_rounds:
                # stage round r+1's plan+gather while round r executes
                prefetcher.schedule(rnd + 1)
            telemetry = None
            with obs.span("fit_round", round=rnd) as fit_span:
                if self._telemetry_enabled:
                    (
                        self.server_state,
                        self.client_states,
                        fit_losses,
                        fit_metrics,
                        per_client_fit_losses,
                        telemetry,
                    ) = self._fit_round_t(
                        self.server_state, self.client_states, batches, mask,
                        jnp.asarray(rnd, jnp.int32), val_batches,
                    )
                else:
                    (
                        self.server_state,
                        self.client_states,
                        fit_losses,
                        fit_metrics,
                        per_client_fit_losses,
                    ) = self._fit_round(
                        self.server_state, self.client_states, batches, mask,
                        jnp.asarray(rnd, jnp.int32), val_batches,
                    )
                # Honest device time: the dispatch above returns at enqueue;
                # fence (enabled path ONLY — disabled adds zero syncs) so the
                # span covers actual device execution, not enqueue latency.
                _, wait = obs.fence(
                    (fit_losses, fit_metrics, per_client_fit_losses)
                )
                device_wait_s += wait
                fit_span.set(device_wait_s=wait)
            need_pre = any(m == CheckpointMode.PRE_AGGREGATION
                           for m, _ in self.model_checkpointers)
            need_post = any(m == CheckpointMode.POST_AGGREGATION
                            for m, _ in self.model_checkpointers)
            # snapshot only on due rounds (checkpoint_every cadence): the
            # device-side copies + fused pull of two full state trees are
            # the entire per-round cost of durable state, so off-cadence
            # rounds skip them entirely
            snapshot_state = (
                self.state_checkpointer is not None
                and hasattr(self.state_checkpointer, "save_simulation_snapshot")
                and self._checkpoint_due(rnd)
            )
            pre_agg_params = None
            if need_pre:
                # post-fit client-stacked params (client_module.py:23-28
                # PRE_AGGREGATION semantics) — DEVICE-side copy (async, no
                # host sync) taken BEFORE eval overwrites the stack with the
                # pulled globals; the copy's fresh buffers are never donated,
                # so the consumer's fused transfer can pull them later
                with obs.span("state_snapshot", round=rnd, what="pre_agg"):
                    pre_agg_params = jax.tree_util.tree_map(
                        jnp.copy, self.client_states.params
                    )
            t1 = time.time()
            with obs.span("eval_round", round=rnd) as eval_span:
                if self._telemetry_enabled:
                    (
                        self.client_states,
                        eval_losses,
                        eval_metrics,
                        per_client_eval_losses,
                        per_client_eval_metrics,
                        ev_nonfinite,
                    ) = self._eval_round_t(
                        self.server_state, self.client_states, val_batches,
                        val_counts,
                    )
                    telemetry = telemetry.replace(
                        nonfinite_eval_loss=ev_nonfinite
                    )
                else:
                    (
                        self.client_states,
                        eval_losses,
                        eval_metrics,
                        per_client_eval_losses,
                        per_client_eval_metrics,
                    ) = self._eval_round(
                        self.server_state, self.client_states, val_batches,
                        val_counts,
                    )
                self.server_state = self.strategy.update_after_eval(
                    self.server_state, per_client_eval_losses,
                    per_client_eval_metrics, mask
                )
                _, eval_wait = obs.fence((eval_losses, eval_metrics))
                test = self._test_batches()
                test_losses = test_metrics = None
                if test is not None:
                    # Separate test loader: same aggregated model, "test - "
                    # prefixed keys alongside the val metrics
                    # (base_server.py:545). The returned stack is
                    # value-identical to the val-eval one (pull is
                    # idempotent) but must be re-assigned: the input stack
                    # was donated.
                    ev = (self._eval_round_t if self._telemetry_enabled
                          else self._eval_round)(
                        self.server_state, self.client_states, test[0], test[1]
                    )
                    self.client_states, test_losses, test_metrics = ev[:3]
                    # fence the test dispatch too — its device time belongs
                    # in device_wait_s, not misattributed to host_s
                    _, test_wait = obs.fence((test_losses, test_metrics))
                    eval_wait += test_wait
                device_wait_s += eval_wait
                eval_span.set(device_wait_s=eval_wait)
            post_agg_params = None
            state_trees = None
            if need_post or snapshot_state:
                # device-side copies only (async): the producer never blocks
                # on a transfer — the consumer's fused device_get pulls these
                # fresh (never-donated) buffers off-thread
                with obs.span("state_snapshot", round=rnd, what="post_agg"):
                    if need_post:
                        post_agg_params = jax.tree_util.tree_map(
                            jnp.copy, self.global_params
                        )
                    if snapshot_state:
                        state_trees = jax.tree_util.tree_map(
                            jnp.copy,
                            {"server_state": self.server_state,
                             "client_states": self.client_states},
                        )
            t2 = time.time()
            compiles_after = compile_s_after = None
            if obs.enabled:
                # all of round r's compiles happened at dispatch, above; read
                # the counters HERE so a pipelined consumer can't misattribute
                # round r+1's (hypothetical) recompile to round r
                compiles_after = obs.registry.counter(
                    "jax_backend_compiles_total").value
                compile_s_after = obs.registry.counter(
                    "jax_backend_compiles_seconds_total").value
            device_results = {
                "mask": mask,
                "fit_losses": fit_losses,
                "fit_metrics": fit_metrics,
                "per_client_fit_losses": per_client_fit_losses,
                "eval_losses": eval_losses,
                "eval_metrics": eval_metrics,
            }
            if telemetry is not None:
                # the RoundTelemetry pytree rides the SAME fused transfer —
                # in-graph observability adds zero extra host syncs
                device_results["telemetry"] = telemetry
            q_fn = getattr(self.strategy, "quarantine_mask", None)
            if q_fn is not None and obs.enabled:
                # in-graph quarantine visibility: device-side copy (the
                # server-state buffer will be donated into the next round)
                # riding the consumer's fused transfer; quarantine itself
                # lives in the strategy and needs no observability
                device_results["_quarantine"] = jnp.copy(
                    q_fn(self.server_state)
                )
            if test_losses is not None:
                device_results["test_losses"] = test_losses
                device_results["test_metrics"] = test_metrics
            # snapshots ride the SAME fused transfer (keys the consumer pops
            # before the results are read)
            if pre_agg_params is not None:
                device_results["_pre_agg_params"] = pre_agg_params
            if post_agg_params is not None:
                device_results["_post_agg_params"] = post_agg_params
            if state_trees is not None:
                device_results["_state_trees"] = state_trees
            work = _RoundWork(
                round=rnd,
                device_results=device_results,
                fit_elapsed_s=t1 - t0,
                eval_elapsed_s=t2 - t1,
                device_wait_s=device_wait_s,
                compiles_before=compiles_before,
                compile_s_before=compile_s_before,
                compiles_after=compiles_after,
                compile_s_after=compile_s_after,
            )
            if consumer is not None:
                consumer.submit_round(
                    rnd, functools.partial(self._finish_round, work))
                legacy_state_save = (
                    self.state_checkpointer is not None
                    and not hasattr(self.state_checkpointer,
                                    "save_simulation_snapshot")
                )
                if legacy_state_save or not self.failure_policy.accept_failures:
                    # Correctness over overlap, two cases:
                    # - legacy sim-based checkpointer API (save_simulation
                    #   only): it reads LIVE sim state + history, so the
                    #   producer must not run ahead of the save;
                    # - accept_failures=False: the failure screen runs in the
                    #   epilogue and must be able to terminate BEFORE the
                    #   next round dispatches/mutates state, exactly like the
                    #   old inline loop.
                    consumer.flush()
            else:
                # no pipeline (direct calls in tests) — run inline
                self._finish_round(work)

    def _finish_round(self, work: "_RoundWork") -> None:
        """Consumer half of one round: ONE fused device->host transfer of
        the results tree, then failure-policy screen, checkpoint decisions,
        RoundRecord construction and reporter I/O — all while the device
        executes later rounds. Runs on the RoundConsumer thread in
        submission (= round) order."""
        obs = self.observability
        rnd = work.round
        # the single fused pull this round pays (replaces ~8 scattered
        # device_get/float() syncs in the old loop)
        host = jax.device_get(work.device_results)
        mask = np.asarray(host["mask"])
        pre_agg_params = host.pop("_pre_agg_params", None)
        post_agg_params = host.pop("_post_agg_params", None)
        state_trees = host.pop("_state_trees", None)
        quarantine_mask = host.pop("_quarantine", None)
        registry_rows = host.pop("_registry_rows", None)
        cohort_info = work.cohort_info
        if registry_rows is not None:
            # cohort-slot rounds: the updated rows came down on the SAME
            # fused pull; scatter them under their registry ids, then
            # release the producer (it gates the next round's state gather
            # on this event)
            meta = work.cohort_meta
            with obs.span("registry_scatter", round=rnd,
                          valid=meta["valid"]) as sc_span:
                s0 = time.perf_counter()
                self.registry.scatter(
                    meta["idx"], meta["valid"],
                    registry_rows["client_states"],
                    registry_rows.get("strategy_rows"),
                )
                scatter_ms = (time.perf_counter() - s0) * 1e3
                sc_span.set(scatter_ms=scatter_ms)
            meta["scatter_event"].set()
            cohort_info = {
                "cohort_slots": meta["slots"],
                "cohort_valid": meta["valid"],
                "registry_size": meta["registry_size"],
                "registry_dirty_rows": self.registry.dirty_rows,
                "stage_ms": round(meta["stage_ms"], 3),
                "gather_ms": round(meta["gather_ms"], 3),
                "scatter_ms": round(scatter_ms, 3),
                "staged_bytes": meta["staged_bytes"],
                # host-barrier accounting: how many rounds this dispatch
                # amortized (1 on the per-round path) and where the cohort
                # draw ran — the O(rounds/R) claim, measured per round
                "rounds_per_dispatch": meta.get("rounds_per_dispatch", 1),
                "cohort_draw": meta.get("cohort_draw", "host"),
            }
        telemetry_obj = host.pop("telemetry", None)
        telemetry_host = (
            {k: np.asarray(v) for k, v in telemetry_obj.as_dict().items()}
            if telemetry_obj is not None else None
        )
        with obs.span("aggregate", round=rnd):
            # Failure policy screen (base_server.py:316-318): terminate
            # before checkpointing a poisoned aggregate when
            # accept_failures=False.
            host_fit_losses = host["per_client_fit_losses"]
            try:
                failed = self.failure_policy.check(host_fit_losses, mask)
            except ClientFailuresError as cf:
                # verdict facts: the policy doesn't know the round, and
                # cohort rounds fail by SLOT — map to registry ids here,
                # while the round's cohort view is still in hand
                cf.round = rnd
                if work.cohort_meta is not None:
                    ids = np.asarray(work.cohort_meta["idx"])
                    cf.registry_clients = [
                        int(ids[c]) for c in cf.clients
                        if 0 <= int(c) < len(ids)
                    ]
                raise
            fit_losses = {k: float(v) for k, v in host["fit_losses"].items()}
            fit_metrics = {k: float(v) for k, v in host["fit_metrics"].items()}
            eval_losses = {k: float(v) for k, v in host["eval_losses"].items()}
            eval_metrics = {k: float(v) for k, v in host["eval_metrics"].items()}
            if "test_losses" in host:
                eval_losses.update({
                    f"test - {k}": float(v)
                    for k, v in host["test_losses"].items()
                })
                eval_metrics.update({
                    f"test - {k}": float(v)
                    for k, v in host["test_metrics"].items()
                })
        with obs.span("checkpoint", round=rnd, mode="pre_aggregation"):
            for mode, ckpt in self.model_checkpointers:
                if mode == CheckpointMode.PRE_AGGREGATION:
                    ckpt.maybe_checkpoint(
                        pre_agg_params,
                        fit_losses.get("backward", float("nan")),
                        fit_metrics,
                    )
        with obs.span("checkpoint", round=rnd, mode="post_aggregation"):
            for mode, ckpt in self.model_checkpointers:
                if mode == CheckpointMode.POST_AGGREGATION:
                    ckpt.maybe_checkpoint(
                        post_agg_params,
                        eval_losses.get("checkpoint", float("nan")),
                        eval_metrics,
                    )
        rec = RoundRecord(
            round=rnd,
            fit_losses=fit_losses,
            fit_metrics=fit_metrics,
            eval_losses=eval_losses,
            eval_metrics=eval_metrics,
            fit_elapsed_s=work.fit_elapsed_s,
            eval_elapsed_s=work.eval_elapsed_s,
        )
        self.history.append(rec)
        # fleet-ledger absorb BEFORE the state checkpoint below: the saved
        # frame's ledger must be as-of THIS round, or a resume at rnd+1
        # would undercount rnd's participation
        fleet_info = self._fleet_absorb_round(
            rnd, mask, host_fit_losses, telemetry_host,
            registry_ids=(np.asarray(work.cohort_meta["idx"])
                          if work.cohort_meta is not None else None),
            quarantine_mask=quarantine_mask,
            failed=failed,
            async_info=work.async_info,
        )
        if self.state_checkpointer is not None:
            # per-round durable state (_save_server_state, base_server.py:420)
            fleet_doc = self._fleet_snapshot_doc()
            with obs.span("checkpoint", round=rnd, mode="state"):
                if state_trees is not None:
                    if work.resume_meta is not None:
                        # buffered-async event snapshot: the trees include
                        # the pending buffer, plus the plan-prefix
                        # fingerprint + virtual clock the resume verifies
                        self.state_checkpointer.save_async_snapshot(
                            state_trees, rnd, self.n_clients,
                            list(self.history),
                            plan_fingerprint=work.resume_meta[
                                "plan_fingerprint"],
                            virtual_time_s=work.resume_meta[
                                "virtual_time_s"],
                            writer=self._ckpt_writer,
                            fleet=fleet_doc,
                        )
                    elif work.cohort_meta is not None:
                        # cohort snapshot: slot states + the registry's
                        # dirty rows (exported AFTER this round's scatter —
                        # the consumer is FIFO, so the rows are exactly
                        # through round rnd)
                        self.state_checkpointer.save_cohort_snapshot(
                            state_trees, rnd, self.n_clients,
                            self.registry_size,
                            self.registry.export_rows(),
                            list(self.history), writer=self._ckpt_writer,
                            fleet=fleet_doc,
                        )
                    else:
                        self.state_checkpointer.save_simulation_snapshot(
                            state_trees, rnd, self.n_clients,
                            list(self.history), writer=self._ckpt_writer,
                            fleet=fleet_doc,
                        )
                elif not hasattr(self.state_checkpointer,
                                 "save_simulation_snapshot"):
                    # legacy sim-based API: reads live sim state — safe ONLY
                    # because the producer flushes this round's epilogue
                    # before dispatching the next round (see _run_round)
                    self.state_checkpointer.save_simulation(self, rnd)
                # else: snapshot-capable checkpointer, off-cadence round —
                # nothing due
        obs_summary = None
        if obs.enabled:
            obs_summary = self._record_round_metrics(
                rnd, rec, mask, host_fit_losses, failed,
                work.compiles_before, work.compile_s_before,
                work.device_wait_s,
                compiles_after=work.compiles_after,
                compile_s_after=work.compile_s_after,
                telemetry=telemetry_host,
                async_info=work.async_info,
                cohort_info=cohort_info,
                fleet_info=fleet_info,
                # cohort rounds: the [K] registry ids the slots mapped to,
                # so the flight ring (and any postmortem ranking built on
                # it) attributes evidence to REAL clients, not slots
                registry_ids=(np.asarray(work.cohort_meta["idx"])
                              if work.cohort_meta is not None else None),
            )
        if quarantine_mask is not None:
            # cohort rounds report quarantine by REGISTRY id, not slot
            ids = (np.asarray(work.cohort_meta["idx"])
                   if work.cohort_meta is not None else None)
            self._emit_quarantine_metrics(
                rnd, np.asarray(quarantine_mask), ids=ids
            )
        with obs.span("report", round=rnd):
            for rep in self.reporters:
                payload = {
                    "fit_losses": rec.fit_losses,
                    "fit_metrics": rec.fit_metrics,
                    "eval_losses": rec.eval_losses,
                    "eval_metrics": rec.eval_metrics,
                    "fit_elapsed_s": rec.fit_elapsed_s,
                    "eval_elapsed_s": rec.eval_elapsed_s,
                    "execution_mode": self._active_execution_mode,
                }
                if obs_summary is not None:
                    # same data the registry/trace hold, bridged through
                    # ReportsManager so JsonReporter/WandBReporter see it
                    payload["observability"] = dict(obs_summary)
                rep.report(payload, round=rnd)
        # watchdog LAST: the round's record/metrics/reports always land
        # before a halt check tears the run down (the raise propagates to
        # the producer via the RoundConsumer's exception channel)
        if telemetry_host is not None and obs.watchdog is not None:
            obs.watchdog.observe(
                rnd, telemetry_host, mask,
                rec.fit_losses.get("backward", float("nan")),
                obs=obs, reporters=self.reporters,
            )
        # recovery probation: a round only counts healthy once the
        # watchdog passed it (a halt above skips this)
        self._note_recovery_round(rnd)

    # -- chunked on-device path ----------------------------------------
    def _fit_chunked(self, n_rounds: int, start_round: int = 1) -> None:
        """fit()'s chunked route: the rounds execute as compiled lax.scan
        dispatches (fit + eval per round on device), then ONE fused
        device->host pull per dispatch materializes the RoundRecords.
        Per-round host overhead collapses to the record/report loop at
        each chunk boundary. Per-round participation masks come from the
        same PRNG stream as the pipelined path, so the trajectories match.

        Without a state checkpointer the whole run is ONE dispatch, as
        before. With a snapshot-capable checkpointer the run dispatches in
        ``checkpoint_every``-round chunks and each boundary's host touch
        (the fused pull that already happens there) also snapshots the
        state trees for a durable, crash-consistent save — checkpointing
        no longer costs the fast path (``state_checkpointer`` is not in
        ``_chunk_ineligibility``). The scan body is identical for every
        chunk length, so a chunked-with-checkpoints run is bit-identical
        to the single-dispatch one (pinned by tests).

        With observability enabled the per-round gauges, JSONL ``round`` /
        ``telemetry`` events and reporter observability payloads are
        reconstructed from the stacked outputs — the SAME
        ``_record_round_metrics`` the pipelined consumer runs, so nothing
        is pipelined-only. The HealthWatchdog screens each round's
        telemetry in order; a halt raises ``TrainingHealthError`` naming
        the first offending round (the chunk's device work has already
        completed, but the failure is just as loud)."""
        if start_round > n_rounds:
            return  # restored state already covers the requested rounds
        sc = self.state_checkpointer
        chunk_ckpt = (sc is not None
                      and hasattr(sc, "save_simulation_snapshot"))
        self._fit_n_rounds = n_rounds
        with self._ckpt_writer_scope(chunk_ckpt) as writer:
            s = start_round
            while s <= n_rounds:
                k = self._rounds_per_dispatch(n_rounds, s)
                self._run_sync_chunk(s, k)
                if chunk_ckpt:
                    # the snapshot rides the chunk-boundary host touch: a
                    # host pull of the fresh state outputs BEFORE the next
                    # chunk's dispatch donates them away
                    trees = jax.device_get({
                        "server_state": self.server_state,
                        "client_states": self.client_states,
                    })
                    sc.save_simulation_snapshot(
                        trees, s + k - 1, self.n_clients,
                        list(self.history), writer=writer,
                        fleet=self._fleet_snapshot_doc(),
                    )
                s += k

    def _run_sync_chunk(self, start_round: int, k: int) -> None:
        """Dispatch rounds ``[start_round, start_round+k)`` as one compiled
        scan and run their host epilogue (the pre-checkpointing
        ``_fit_chunked`` body, offset-aware)."""
        obs = self.observability
        n_rounds = k
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            compiles_before = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        t_start = time.time()
        val_batches, val_counts = self._val_batches()
        test = self._test_batches()
        chunked = self._make_chunked_fit_with_eval()
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        rounds = range(start_round, start_round + k)
        plans = [self._round_plan(r) for r in rounds]
        idx = jnp.asarray(np.stack([p[0] for p in plans]))
        em = jnp.asarray(np.stack([p[1] for p in plans]))
        sm = jnp.asarray(np.stack([p[2] for p in plans]))
        mask_stack = jnp.stack([
            # the supervisor keep-mask is a pure function of (ledger,
            # round), so computing the whole chunk's masks ahead of the
            # dispatch sees the same values the per-round path would
            self._apply_recovery_keep(
                self.client_manager.sample(
                    jax.random.fold_in(self.rng, 2000 + r), r
                ),
                r,
            )
            for r in rounds
        ])
        masks_np = np.asarray(mask_stack)
        x_bank, y_bank = self._sharded_train_banks()
        args = [self.server_state, self.client_states,
                x_bank, y_bank, idx, em, sm,
                mask_stack, jnp.asarray(start_round, jnp.int32),
                val_batches, val_counts]
        if test is not None:
            args.extend(test)
        with obs.span("fit_chunk", cat="fit", rounds=n_rounds,
                      start_round=start_round) as chunk_span:
            self.server_state, self.client_states, outs = chunked(*args)
            # fence (enabled path only): total device wait for the chunk,
            # amortized per round below
            _, device_wait_total = obs.fence(outs)
            stacked = jax.device_get(outs)  # the chunk's ONE fused host pull
            if obs.enabled:
                chunk_span.set(device_wait_s=device_wait_total)
        compiles_after = compile_s_after = None
        if obs.enabled:
            compiles_after = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_after = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        per_round_s = (time.time() - t_start) / max(n_rounds, 1)
        device_wait_round = device_wait_total / max(n_rounds, 1)
        self._chunked_epilogue(
            n_rounds, stacked, masks_np, compiles_before, compile_s_before,
            compiles_after, compile_s_after, per_round_s, device_wait_round,
            start_round=start_round,
        )

    def _chunked_epilogue(
        self, n_rounds: int, stacked: dict, masks_np: np.ndarray,
        compiles_before: float, compile_s_before: float,
        compiles_after: float | None, compile_s_after: float | None,
        per_round_s: float, device_wait_round: float,
        async_plan=None, start_round: int = 1,
        cohort_infos=None, registry_ids=None,
    ) -> None:
        """Per-round host epilogue over a chunked dispatch's stacked
        outputs: failure screen, RoundRecords, metrics/reports, watchdog —
        shared by the synchronous chunked route, the buffered-async
        chunked route (``async_plan`` adds per-event staleness/cadence
        facts to each round's metrics) and the cohort chunked route
        (``cohort_infos``: per-round cohort summary dicts;
        ``registry_ids``: [R, K] slot->registry-id map so failures,
        fleet absorption and quarantine name REAL clients).
        ``start_round`` offsets the round numbering for non-initial
        chunks (checkpoint boundaries, resume)."""
        obs = self.observability
        telemetry_stack = stacked.get("telemetry")
        quarantine_stack = stacked.get("quarantine")
        for i in range(n_rounds):
            rnd = start_round + i
            per_fit_i = {
                k: v[i] for k, v in stacked["per_client_fit_losses"].items()
            }
            ids_i = (np.asarray(registry_ids[i])
                     if registry_ids is not None else None)
            # logs per-round failures; cannot terminate (eligibility
            # guarantees accept_failures=True on this path)
            failed = self.failure_policy.check(per_fit_i, masks_np[i])
            eval_losses = {
                k: float(v[i]) for k, v in stacked["eval_losses"].items()
            }
            eval_metrics = {
                k: float(v[i]) for k, v in stacked["eval_metrics"].items()
            }
            if "test_losses" in stacked:
                eval_losses.update({
                    f"test - {k}": float(v[i])
                    for k, v in stacked["test_losses"].items()
                })
                eval_metrics.update({
                    f"test - {k}": float(v[i])
                    for k, v in stacked["test_metrics"].items()
                })
            rec = RoundRecord(
                round=rnd,
                fit_losses={
                    k: float(v[i]) for k, v in stacked["fit_losses"].items()
                },
                fit_metrics={
                    k: float(v[i]) for k, v in stacked["fit_metrics"].items()
                },
                eval_losses=eval_losses,
                eval_metrics=eval_metrics,
                # one dispatch covers the whole run: report the amortized
                # per-round wall; there is no separable eval wall on-device
                fit_elapsed_s=per_round_s,
                eval_elapsed_s=0.0,
            )
            self.history.append(rec)
            telemetry_i = None
            if telemetry_stack is not None:
                telemetry_i = {
                    k: np.asarray(v[i])
                    for k, v in telemetry_stack.as_dict().items()
                }
            async_info_i = (self._async_event_info(async_plan, rnd - 1)
                            if async_plan is not None else None)
            # fleet-ledger absorb BEFORE the chunk boundary's snapshot
            # (taken after this epilogue returns) — the frame's ledger is
            # as-of the chunk's last round, matching the pipelined path
            fleet_info = self._fleet_absorb_round(
                rnd, masks_np[i], per_fit_i, telemetry_i,
                registry_ids=ids_i,
                quarantine_mask=(np.asarray(quarantine_stack[i])
                                 if quarantine_stack is not None else None),
                failed=failed,
                async_info=async_info_i,
            )
            obs_summary = None
            if obs.enabled:
                # the single dispatch's compiles/device time attribute to
                # round 1 / amortize per round — disclosed by execution_mode
                obs_summary = self._record_round_metrics(
                    rnd, rec, masks_np[i], per_fit_i, failed,
                    compiles_before, compile_s_before, device_wait_round,
                    compiles_after=(compiles_after if i == 0
                                    else compiles_before),
                    compile_s_after=(compile_s_after if i == 0
                                     else compile_s_before),
                    telemetry=telemetry_i,
                    async_info=async_info_i,
                    cohort_info=(cohort_infos[i]
                                 if cohort_infos is not None else None),
                    fleet_info=fleet_info,
                    registry_ids=ids_i,
                )
            if quarantine_stack is not None:
                self._emit_quarantine_metrics(
                    rnd, np.asarray(quarantine_stack[i]), ids=ids_i
                )
            for rep in self.reporters:
                payload = {
                    "fit_losses": rec.fit_losses,
                    "fit_metrics": rec.fit_metrics,
                    "eval_losses": rec.eval_losses,
                    "eval_metrics": rec.eval_metrics,
                    "fit_elapsed_s": rec.fit_elapsed_s,
                    "eval_elapsed_s": rec.eval_elapsed_s,
                    "execution_mode": EXEC_CHUNKED,
                }
                if obs_summary is not None:
                    payload["observability"] = dict(obs_summary)
                rep.report(payload, round=rnd)
            if telemetry_i is not None and obs.watchdog is not None:
                obs.watchdog.observe(
                    rnd, telemetry_i, masks_np[i],
                    rec.fit_losses.get("backward", float("nan")),
                    obs=obs, reporters=self.reporters,
                )
            # recovery probation (see _finish_round): healthy rounds only
            self._note_recovery_round(rnd)

    # -- cohort-slot path (server/registry.py) --------------------------
    def _count_cohort_roundtrip(self) -> None:
        """One host round-trip against the registry — a cohort draw +
        row gather/scatter + program dispatch paid on the host. The
        pipelined path pays one per ROUND; the chunked path one per
        R-round dispatch; async-over-registry one per buffer-fill event.
        ``fl_cohort_host_roundtrips_total`` is the measured side of the
        chunked path's O(rounds/R) host-barrier claim."""
        obs = self.observability
        if obs.enabled:
            obs.registry.counter(
                "fl_cohort_host_roundtrips_total",
                help="host round-trips paid against the client registry "
                     "(one per dispatch: cohort draw + gather/scatter)",
            ).inc()

    def _stage_cohort_round(self, rnd: int) -> dict:
        """One round's slot tensors, staged: sample the cohort ids from
        the dense path's exact PRNG stream (``fold_in(rng, 2000+round)``),
        assemble the ``[K, ...]`` host tensors from the registry, and
        ``device_put`` the big ones (sharded onto the clients axis under a
        mesh). Pure function of (rng, round, registry data) — safe to run
        on the prefetcher's worker thread, overlapping device execution;
        per-client STATE is deliberately absent (it has a read-after-write
        dependency on the previous round's scatter — see
        ``_run_cohort_round``)."""
        idx, valid = self.client_manager.sample_indices(
            jax.random.fold_in(self.rng, 2000 + rnd), rnd, self.n_clients
        )
        t0 = time.perf_counter()
        # the staging-overlap span: on the prefetch worker it runs INSIDE
        # the previous round's `round` span wall — visible overlap in the
        # trace timeline
        with self.observability.span("cohort_stage", round=rnd,
                                     valid=int(valid)) as sp:
            staged = self.registry.stage_round(
                idx, valid, self._base_entropy, rnd
            )
            b = self._program_builder
            cs = b.client_sharding()
            put = ((lambda t: b.put(t, cs)) if b.mesh is not None
                   else jax.device_put)
            staged["batches"] = put(staged["batches"])
            staged["val_batches"] = put(staged["val_batches"])
            staged["mask"] = jnp.asarray(staged["mask"])
            staged["sample_counts"] = jnp.asarray(staged["sample_counts"])
            staged["val_counts"] = jnp.asarray(staged["val_counts"])
            staged["stage_ms"] = (time.perf_counter() - t0) * 1e3
            sp.set(stage_ms=round(staged["stage_ms"], 3),
                   staged_bytes=staged["staged_bytes"])
        return staged

    def _await_registry_scatter(self) -> None:
        """Block until the consumer has scattered the PREVIOUS round's
        rows into the registry (the host-side read-after-write edge of the
        gather/scatter cycle), while still surfacing consumer failures —
        a raised epilogue must not leave the producer waiting forever."""
        ev = self._registry_scatter_event
        if ev is None:
            return
        consumer = self._consumer
        while not ev.wait(0.05):
            if consumer is not None:
                consumer.raise_pending()
        self._registry_scatter_event = None

    def _fit_cohort(self, n_rounds: int, start_round: int = 1) -> None:
        """fit()'s cohort-slot route: every round dispatches the SAME
        compiled [slots]-shaped fit/eval programs regardless of registry
        size. Per round the producer takes the prefetcher's staged slot
        data (staged during the previous round's device work), gathers the
        sampled clients' persistent rows from the host registry, runs
        fit+eval, and hands the results — including the updated rows — to
        the RoundConsumer, whose single fused device->host transfer also
        feeds the registry scatter."""
        obs = self.observability
        if start_round > n_rounds:
            return
        self._fit_n_rounds = n_rounds
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        self._registry_scatter_event = None
        with self._ckpt_writer_scope(
            bool(self.model_checkpointers
                 or self.state_checkpointer is not None),
            attach_model_ckpts=True,
        ):
            consumer = self._consumer = RoundConsumer(
                maxsize=self.pipeline_depth
            )
            prefetcher = self._prefetcher = RoundPrefetcher(self)
            try:
                prefetcher.schedule(start_round)
                for rnd in range(start_round, n_rounds + 1):
                    consumer.raise_pending()
                    with obs.maybe_profile(rnd):
                        self._run_cohort_round(rnd)
                consumer.flush()
            finally:
                consumer.close()
                prefetcher.close()
                # retained for the postmortem verdict: which round's host
                # epilogue last FINISHED before this run ended
                self._last_epilogue_round = consumer.last_completed_round
                self._consumer = None
                self._prefetcher = None
                self._registry_scatter_event = None

    def _run_cohort_round(self, rnd: int) -> None:
        """Producer half of one cohort-slot round: staged slot data in,
        registry state rows gathered and installed, fit+eval dispatched,
        epilogue (fused pull + registry scatter + records/reports)
        submitted to the consumer."""
        obs = self.observability
        consumer = self._consumer
        prefetcher = self._prefetcher
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            compiles_before = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        t0 = time.time()
        # per-round host boundary: admin retunes rebind server_state before
        # this round's programs read it (data staging has no dependency)
        self._apply_admin_retunes(rnd)
        with obs.span("round", round=rnd, kind="cohort"):
            with obs.span("configure_fit", round=rnd):
                staged = (prefetcher.take(rnd) if prefetcher is not None
                          else self._stage_cohort_round(rnd))
            if prefetcher is not None and rnd < self._fit_n_rounds:
                # round r+1's DATA staging overlaps round r's device work
                # (it has no state dependency); only the state gather below
                # waits for the previous scatter
                prefetcher.schedule(rnd + 1)
            self._await_registry_scatter()
            idx, valid = staged["idx"], staged["valid"]
            sup = self._recovery_supervisor
            if sup is not None:
                # supervisor quarantine in REGISTRY-id space: a sampled
                # slot whose id is on the roster is masked out (its row
                # still gathers/scatters — zero-weight, exactly like an
                # unsampled client); pass-through while idle
                drop = sup.quarantined_ids(rnd)
                if drop:
                    keep = (~np.isin(np.asarray(idx),
                                     np.asarray(drop))).astype(np.float32)
                    staged["mask"] = staged["mask"] * jnp.asarray(keep)
            with obs.span("cohort_gather", round=rnd,
                          valid=valid) as gather_span:
                g0 = time.perf_counter()
                b = self._program_builder
                client_rows = self.registry.gather_client_states(idx)
                if b.mesh is not None:
                    self.client_states = b.put(
                        client_rows, b.client_state_shardings(
                            self.client_states
                        )
                    )
                else:
                    self.client_states = jax.device_put(client_rows)
                srows = self.registry.gather_strategy_rows(idx)
                if srows is not None:
                    srows_dev = (b.put(srows, b.client_sharding())
                                 if b.mesh is not None
                                 else jax.device_put(srows))
                    self.server_state = self.strategy.scatter_state_rows(
                        self.server_state, srows_dev
                    )
                gather_ms = (time.perf_counter() - g0) * 1e3
                gather_span.set(gather_ms=gather_ms)
            telemetry = None
            fit_args = [
                self.server_state, self.client_states, staged["batches"],
                staged["mask"], jnp.asarray(rnd, jnp.int32),
                staged["val_batches"], staged["sample_counts"],
            ]
            with obs.span("fit_round", round=rnd) as fit_span:
                if self._telemetry_enabled:
                    (self.server_state, self.client_states, fit_losses,
                     fit_metrics, per_client_fit_losses,
                     telemetry) = self._fit_round_t(*fit_args)
                else:
                    (self.server_state, self.client_states, fit_losses,
                     fit_metrics,
                     per_client_fit_losses) = self._fit_round(*fit_args)
                _, device_wait_s = obs.fence(
                    (fit_losses, fit_metrics, per_client_fit_losses)
                )
                fit_span.set(device_wait_s=device_wait_s)
            need_pre = any(m == CheckpointMode.PRE_AGGREGATION
                           for m, _ in self.model_checkpointers)
            need_post = any(m == CheckpointMode.POST_AGGREGATION
                            for m, _ in self.model_checkpointers)
            pre_agg_params = None
            if need_pre:
                with obs.span("state_snapshot", round=rnd, what="pre_agg"):
                    pre_agg_params = jax.tree_util.tree_map(
                        jnp.copy, self.client_states.params
                    )
            t1 = time.time()
            with obs.span("eval_round", round=rnd) as eval_span:
                ev_args = (self.server_state, self.client_states,
                           staged["val_batches"], staged["val_counts"])
                if self._telemetry_enabled:
                    (self.client_states, eval_losses, eval_metrics, _pl,
                     _pm, ev_nonfinite) = self._eval_round_t(*ev_args)
                    telemetry = telemetry.replace(
                        nonfinite_eval_loss=ev_nonfinite
                    )
                else:
                    (self.client_states, eval_losses, eval_metrics, _pl,
                     _pm) = self._eval_round(*ev_args)
                _, eval_wait = obs.fence((eval_losses, eval_metrics))
                device_wait_s += eval_wait
                eval_span.set(device_wait_s=eval_wait)
            post_agg_params = None
            state_trees = None
            snapshot_state = (
                self.state_checkpointer is not None
                and self._checkpoint_due(rnd)
            )
            if need_post or snapshot_state:
                with obs.span("state_snapshot", round=rnd, what="post_agg"):
                    if need_post:
                        post_agg_params = jax.tree_util.tree_map(
                            jnp.copy, self.global_params
                        )
                    if snapshot_state:
                        state_trees = jax.tree_util.tree_map(
                            jnp.copy,
                            {"server_state": self.server_state,
                             "client_states": self.client_states},
                        )
            t2 = time.time()
            compiles_after = compile_s_after = None
            if obs.enabled:
                compiles_after = obs.registry.counter(
                    "jax_backend_compiles_total").value
                compile_s_after = obs.registry.counter(
                    "jax_backend_compiles_seconds_total").value
            device_results = {
                "mask": staged["mask"],
                "fit_losses": fit_losses,
                "fit_metrics": fit_metrics,
                "per_client_fit_losses": per_client_fit_losses,
                "eval_losses": eval_losses,
                "eval_metrics": eval_metrics,
                # the updated persistent rows ride the consumer's fused
                # transfer; no copies needed — the producer's scatter gate
                # keeps these buffers alive until the pull completes
                "_registry_rows": {
                    "client_states": self.client_states,
                    "strategy_rows": self.strategy.state_rows(
                        self.server_state
                    ),
                },
            }
            if telemetry is not None:
                device_results["telemetry"] = telemetry
            q_fn = getattr(self.strategy, "quarantine_mask", None)
            if q_fn is not None and obs.enabled:
                device_results["_quarantine"] = jnp.copy(
                    q_fn(self.server_state)
                )
            if pre_agg_params is not None:
                device_results["_pre_agg_params"] = pre_agg_params
            if post_agg_params is not None:
                device_results["_post_agg_params"] = post_agg_params
            if state_trees is not None:
                device_results["_state_trees"] = state_trees
            scatter_event = threading.Event()
            self._registry_scatter_event = scatter_event
            work = _RoundWork(
                round=rnd,
                device_results=device_results,
                fit_elapsed_s=t1 - t0,
                eval_elapsed_s=t2 - t1,
                device_wait_s=device_wait_s,
                compiles_before=compiles_before,
                compile_s_before=compile_s_before,
                compiles_after=compiles_after,
                compile_s_after=compile_s_after,
                cohort_meta={
                    "idx": idx, "valid": valid,
                    "slots": self.n_clients,
                    "registry_size": self.registry_size,
                    "stage_ms": staged["stage_ms"],
                    "gather_ms": gather_ms,
                    "staged_bytes": staged["staged_bytes"],
                    "scatter_event": scatter_event,
                    "rounds_per_dispatch": 1,
                    "cohort_draw": "host",
                },
            )
            self._count_cohort_roundtrip()
            if consumer is not None:
                consumer.submit_round(
                    rnd, functools.partial(self._finish_round, work))
                if not self.failure_policy.accept_failures:
                    consumer.flush()
            else:
                self._finish_round(work)

    # -- cohort chunked route (in-graph draw + window exchange) ---------
    def _make_cohort_chunk(self):
        """Compile the cohort chunked scan: R federated rounds per
        dispatch over the virtualized registry, with ZERO host touches
        between rounds. Each scan step (1) draws the round's cohort ids
        IN-GRAPH via the manager's ``draw_cohort`` — a pure function of
        ``fold_in(seed, 2000+round)``, bit-identical to the host sampler
        the pipelined path runs — (2) resolves the ids against the
        device-staged registry WINDOW (``searchsorted`` over the sorted
        window ids; pad slots repeat a real id, so every slot gathers a
        real row), (3) runs the exact slot ``fit_round``/``eval_round``
        sequence of one pipelined cohort round, and (4) scatters the
        post-eval rows (client states + strategy rows) back into the
        window (pad destinations drop). The window is the chunk's
        double-buffered stand-in for the host registry: rows enter it
        once per chunk and leave once per chunk, so host round-trips
        shrink from O(rounds) to O(rounds/R).

        The scan outputs carry each round's drawn ids/valid count so the
        driver can assert in-graph/host draw parity at the pull — the
        window was built from the HOST mirror's draws, and any divergence
        would silently corrupt the exchange."""
        if self._cohort_chunk_jit is not None:
            return self._cohort_chunk_jit
        telemetry_on = self._telemetry_enabled
        fit_round = (self._fit_round_fn_t if telemetry_on
                     else self._fit_round_fn)
        eval_round = (self._eval_round_fn_t if telemetry_on
                      else self._eval_round_fn)
        quarantine_fn = (getattr(self.strategy, "quarantine_mask", None)
                         if self.observability.enabled else None)
        strategy = self.strategy
        draw = self.client_manager.draw_cohort
        slots = self.n_clients
        has_srows = self.registry.has_strategy_rows

        def chunk(server_state, client_states, w_client, w_srows,
                  base_rng, window_ids, batches, masks, sample_counts,
                  val_batches, val_counts, start_round):
            w = window_ids.shape[0]

            def body(carry, per_round):
                server_state, client_states, w_client, w_srows, r = carry
                batches_r, mask_r, sc_r, vb_r, vc_r = per_round
                ids, valid = draw(
                    jax.random.fold_in(base_rng, 2000 + r), r, slots
                )
                with stage_attr.stage("cohort_exchange"):
                    pos = jnp.searchsorted(window_ids, ids).astype(jnp.int32)
                    client_states = jax.tree_util.tree_map(
                        lambda t: t[pos], w_client
                    )
                    if has_srows:
                        server_state = strategy.scatter_state_rows(
                            server_state,
                            jax.tree_util.tree_map(
                                lambda t: t[pos], w_srows
                            ),
                        )
                fit_outs = fit_round(
                    server_state, client_states, batches_r, mask_r, r,
                    vb_r, sc_r,
                )
                round_telemetry = None
                if telemetry_on:
                    (server_state, client_states, fit_losses, fit_metrics,
                     per_fit, round_telemetry) = fit_outs
                else:
                    (server_state, client_states, fit_losses, fit_metrics,
                     per_fit) = fit_outs
                ev_outs = eval_round(
                    server_state, client_states, vb_r, vc_r
                )
                if telemetry_on:
                    (client_states, ev_losses, ev_metrics, _pl, _pm,
                     ev_nonfinite) = ev_outs
                    round_telemetry = round_telemetry.replace(
                        nonfinite_eval_loss=ev_nonfinite
                    )
                else:
                    client_states, ev_losses, ev_metrics, _pl, _pm = ev_outs
                out = {
                    "fit_losses": fit_losses,
                    "fit_metrics": fit_metrics,
                    "per_client_fit_losses": per_fit,
                    "eval_losses": ev_losses,
                    "eval_metrics": ev_metrics,
                    "cohort_ids": ids,
                    "cohort_valid": valid,
                }
                if round_telemetry is not None:
                    out["telemetry"] = round_telemetry
                if quarantine_fn is not None:
                    out["quarantine"] = quarantine_fn(server_state)
                # write-back: post-eval rows land at their window position;
                # pad slots (>= valid) target index w — dropped, exactly
                # like an unsampled client on the pipelined path
                with stage_attr.stage("cohort_exchange"):
                    dest = jnp.where(
                        jnp.arange(slots, dtype=jnp.int32) < valid, pos, w
                    )
                    w_client = jax.tree_util.tree_map(
                        lambda wt, c: wt.at[dest].set(c, mode="drop"),
                        w_client, client_states,
                    )
                    if has_srows:
                        w_srows = jax.tree_util.tree_map(
                            lambda wt, c: wt.at[dest].set(c, mode="drop"),
                            w_srows, strategy.state_rows(server_state),
                        )
                return (server_state, client_states, w_client, w_srows,
                        r + 1), out

            (server_state, client_states, w_client, w_srows, _), outs = (
                jax.lax.scan(
                    body,
                    (server_state, client_states, w_client, w_srows,
                     start_round),
                    (batches, masks, sample_counts, val_batches,
                     val_counts),
                )
            )
            return server_state, client_states, w_client, w_srows, outs

        # donate the carried states AND the window trees: the caller
        # replaces all four with the scan outputs, so XLA updates the
        # large [W, ...] window buffers in place (mesh never reaches this
        # path — mesh+cohort demotes to pipelined)
        self._cohort_chunk_jit = self._program_builder.jit(
            chunk, donate=(0, 1, 2, 3)
        )
        return self._cohort_chunk_jit

    def _stage_cohort_chunk(self, start_round: int, k: int) -> dict:
        """One chunk's host staging: sample rounds ``[start_round,
        start_round+k)`` from the dense path's exact PRNG stream (the HOST
        mirror of the in-graph draw — it also fails fast on sampler
        overflow, before any device work), stack their slot tensors, build
        the chunk window and ``device_put`` the lot. Pure function of
        (rng, rounds, registry data) — safe on the prefetcher's worker
        thread, overlapping the previous chunk's device work. Window
        STATE rows are absent here (read-after-write on the previous
        chunk's scatter — the driver gathers them)."""
        draws = []
        for i in range(k):
            r = start_round + i
            idx, valid = self.client_manager.sample_indices(
                jax.random.fold_in(self.rng, 2000 + r), r, self.n_clients
            )
            draws.append((np.asarray(idx), int(valid)))
        t0 = time.perf_counter()
        with self.observability.span(
            "cohort_stage_chunk", start_round=start_round, rounds=k
        ) as sp:
            staged = self.registry.stage_chunk(
                draws, self._base_entropy, start_round
            )
            window_ids, w_real = self.registry.chunk_window(
                [d[0] for d in draws], [d[1] for d in draws],
                self.n_clients, k,
            )
            staged["window_ids"] = window_ids
            staged["w_real"] = w_real
            staged["mask_np"] = staged["mask"]
            staged["batches"] = jax.device_put(staged["batches"])
            staged["val_batches"] = jax.device_put(staged["val_batches"])
            staged["mask"] = jnp.asarray(staged["mask"])
            staged["sample_counts"] = jnp.asarray(staged["sample_counts"])
            staged["val_counts"] = jnp.asarray(staged["val_counts"])
            # int32 on device: draw_cohort ids are int32, and searchsorted
            # wants one dtype on both sides
            staged["window_ids_dev"] = jnp.asarray(
                window_ids.astype(np.int32)
            )
            staged["stage_ms"] = (time.perf_counter() - t0) * 1e3
            sp.set(stage_ms=round(staged["stage_ms"], 3),
                   staged_bytes=staged["staged_bytes"],
                   window=len(window_ids), window_real=w_real)
        return staged

    def _fit_cohort_chunked(self, n_rounds: int, start_round: int = 1
                            ) -> None:
        """fit()'s cohort chunked route: ``checkpoint_every``-round (or
        whole-run) chunks dispatch over the registry window while the
        prefetcher stages the NEXT chunk's draws + slot tensors behind the
        device work. Chunk boundaries keep the PR 12 semantics: the window
        rows scatter back into the registry first, then the cohort
        snapshot (slot states + registry dirty rows) persists exactly as
        the pipelined consumer would have written it."""
        obs = self.observability
        if start_round > n_rounds:
            return
        sc = self.state_checkpointer
        chunk_ckpt = sc is not None
        self._fit_n_rounds = n_rounds
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        prefetcher = self._prefetcher = RoundPrefetcher(self)
        try:
            with self._ckpt_writer_scope(chunk_ckpt) as writer:
                s = start_round
                prefetcher.schedule_chunk(
                    s, self._rounds_per_dispatch(n_rounds, s)
                )
                while s <= n_rounds:
                    k = self._rounds_per_dispatch(n_rounds, s)
                    staged = prefetcher.take_chunk(s, k)
                    if s + k <= n_rounds:
                        # chunk c+1's draws/staging overlap chunk c's
                        # device work; only the window ROW gather waits
                        # for c's boundary scatter (in _run_cohort_chunk)
                        prefetcher.schedule_chunk(
                            s + k,
                            self._rounds_per_dispatch(n_rounds, s + k),
                        )
                    with obs.span("cohort_chunk", start_round=s, rounds=k):
                        self._run_cohort_chunk(s, k, staged)
                    if chunk_ckpt:
                        trees = jax.device_get({
                            "server_state": self.server_state,
                            "client_states": self.client_states,
                        })
                        sc.save_cohort_snapshot(
                            trees, s + k - 1, self.n_clients,
                            self.registry_size, self.registry.export_rows(),
                            list(self.history), writer=writer,
                            fleet=self._fleet_snapshot_doc(),
                        )
                    s += k
        finally:
            prefetcher.close()
            self._prefetcher = None

    def _run_cohort_chunk(self, start_round: int, k: int,
                          staged: dict) -> None:
        """Dispatch one cohort chunk and run its host epilogue: window
        row gather (after the previous chunk's scatter — same-thread, so
        the ordering is structural), ONE compiled scan over k rounds, the
        in-graph/host draw-parity check, the boundary scatter back into
        the registry, then the shared chunked epilogue with per-round
        cohort facts."""
        obs = self.observability
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            compiles_before = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        t_start = time.time()
        chunked = self._make_cohort_chunk()
        with obs.span("cohort_gather", start_round=start_round,
                      window=int(staged["w_real"])) as gather_span:
            g0 = time.perf_counter()
            w_client_h, w_srows_h = self.registry.gather_window(
                staged["window_ids"]
            )
            w_client = jax.device_put(w_client_h)
            w_srows = (jax.device_put(w_srows_h)
                       if w_srows_h is not None else {})
            gather_ms = (time.perf_counter() - g0) * 1e3
            gather_span.set(gather_ms=gather_ms)
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        args = [self.server_state, self.client_states, w_client, w_srows,
                self.rng, staged["window_ids_dev"], staged["batches"],
                staged["mask"], staged["sample_counts"],
                staged["val_batches"], staged["val_counts"],
                jnp.asarray(start_round, jnp.int32)]
        with obs.span("fit_cohort_chunk", cat="fit", rounds=k,
                      start_round=start_round) as chunk_span:
            (self.server_state, self.client_states, w_client, w_srows,
             outs) = chunked(*args)
            _, device_wait_total = obs.fence(
                (outs["fit_losses"], outs["eval_losses"])
            )
            stacked = jax.device_get(outs)  # the chunk's ONE fused pull
            rows_back = jax.device_get((w_client, w_srows))
            if obs.enabled:
                chunk_span.set(device_wait_s=device_wait_total)
        self._count_cohort_roundtrip()
        # in-graph/host draw parity: the window was built from the host
        # mirror's draws; a divergent in-graph draw would gather/scatter
        # the WRONG rows — fail loudly, never train through it
        ids_host = np.asarray(staged["idx"])
        valid_host = np.asarray(staged["valid"], np.int64)
        ids_dev = np.asarray(stacked.pop("cohort_ids"), np.int64)
        valid_dev = np.asarray(stacked.pop("cohort_valid"), np.int64)
        if not (np.array_equal(ids_dev, np.asarray(ids_host, np.int64))
                and np.array_equal(valid_dev, valid_host)):
            raise RuntimeError(
                "in-graph cohort draw diverged from the host sampler for "
                f"rounds [{start_round}, {start_round + k}): the "
                f"{type(self.client_manager).__name__}.draw_cohort "
                "contract (bit-identical to sample_indices) is broken — "
                "the chunk's window exchange cannot be trusted"
            )
        with obs.span("registry_scatter", start_round=start_round,
                      valid=int(staged["w_real"])) as sc_span:
            s0 = time.perf_counter()
            wc_back, ws_back = rows_back
            self.registry.scatter(
                staged["window_ids"], int(staged["w_real"]), wc_back,
                ws_back if w_srows_h is not None else None,
            )
            scatter_ms = (time.perf_counter() - s0) * 1e3
            sc_span.set(scatter_ms=scatter_ms)
        compiles_after = compile_s_after = None
        if obs.enabled:
            compiles_after = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_after = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        per_round_s = (time.time() - t_start) / max(k, 1)
        device_wait_round = device_wait_total / max(k, 1)
        # per-round cohort facts: walls amortize over the chunk; the
        # rounds_per_dispatch/cohort_draw pair is what the perf report's
        # host-barrier columns read
        cohort_infos = [
            {
                "cohort_slots": self.n_clients,
                "cohort_valid": int(valid_host[i]),
                "registry_size": self.registry_size,
                "registry_dirty_rows": self.registry.dirty_rows,
                "stage_ms": round(staged["stage_ms"] / k, 3),
                "gather_ms": round(gather_ms / k, 3),
                "scatter_ms": round(scatter_ms / k, 3),
                "staged_bytes": int(staged["staged_bytes"] // k),
                "rounds_per_dispatch": k,
                "cohort_draw": "in_graph",
            }
            for i in range(k)
        ]
        self._chunked_epilogue(
            k, stacked, np.asarray(staged["mask_np"]),
            compiles_before, compile_s_before, compiles_after,
            compile_s_after, per_round_s, device_wait_round,
            start_round=start_round,
            cohort_infos=cohort_infos, registry_ids=ids_host,
        )

    # -- buffered-async path (server/async_schedule.py) -----------------
    @staticmethod
    def _async_event_info(plan, i: int) -> dict:
        """One event's host facts for the round record, plus the raw
        per-update staleness row (popped by ``_record_round_metrics``
        into the staleness histogram)."""
        info = plan.summarize_event(i)
        arr = plan.arrivals[i] > 0
        info["_staleness_values"] = [
            float(s) for s in plan.staleness[i][arr]
        ]
        return info

    def _fit_async(self, n_rounds: int, mode: str, plan,
                   start_event: int = 1) -> None:
        """fit()'s buffered-async route: the virtual-clock arrival
        schedule was resolved to a static event plan at fit() entry (pure
        function of the async config's seed, the FaultPlan and the cohort
        — identical across execution modes, resumes and processes); run
        the remaining buffer-fill EVENTS as compiled programs. Each event
        is one RoundRecord: cadence is set by arrival rate, not the tail.
        ``start_event`` > 1 continues a restored run whose pending buffer,
        event cursor and plan-prefix fingerprint ``_maybe_resume``
        verified."""
        obs = self.observability
        if obs.enabled:
            obs.log_event(
                "async_plan", events=n_rounds,
                buffer_size=self.async_config.buffer_size,
                staleness_mean=float(
                    plan.staleness[plan.arrivals > 0].mean()
                ) if n_rounds else 0.0,
                virtual_wall_s=float(plan.event_times[-1]),
                mean_cadence_vs=float(plan.cadences().mean()),
            )
        if start_event > n_rounds:
            return  # restored state already covers the requested events
        self._async_prefix_fps = None
        if self._ckpt_every() is not None:
            from fl4health_tpu.server.async_schedule import (
                plan_prefix_fingerprints,
            )

            self._async_prefix_fps = plan_prefix_fingerprints(plan)
        if self._cohort_active:
            # FedBuff over the registry: per-event occupancy swaps are
            # host work, so this composition is pipelined-only (the
            # chunked route demotes at _chunk_ineligibility)
            self._fit_async_registry(n_rounds, plan, start_event)
        elif mode == EXEC_CHUNKED:
            self._fit_async_chunked(n_rounds, plan, start_event)
        else:
            self._fit_async_pipelined(n_rounds, plan, start_event)

    def _staleness_exponent_input(self) -> jax.Array:
        """The staleness exponent as a traced PROGRAM INPUT, read from the
        live (outermost FedBuff) strategy attribute at each dispatch — so a
        rebind of ``strategy.staleness_exponent`` (the sweep engine's
        scalar hoisting) reaches the compiled async programs with zero
        recompiles. Falls back to 0.0 for exotic async strategies without
        the attribute (a legacy 2-arg ``async_aggregation_mask`` never
        receives it — ``_build_async_fns`` shims the call arity)."""
        return jnp.asarray(
            float(getattr(self.strategy, "staleness_exponent", 0.0)),
            jnp.float32,
        )

    def _stage_prologue_batches(self):
        """Data-plan-1 batches for the async prologue, staged with the
        builder's clients sharding (no-op unsharded)."""
        return self._program_builder.put(
            self._round_batches(1), self._program_builder.client_sharding()
        )

    def _fit_async_pipelined(self, n_rounds: int, plan,
                             start_event: int = 1) -> None:
        """Per-event async path: prologue dispatch fills the pending
        buffer, then each buffer-fill event dispatches one fused
        aggregate->eval->restart program while the RoundConsumer runs the
        previous event's host epilogue and the prefetcher stages the next
        event's restart batches (data plan e+1). On resume
        (``start_event`` > 1) the restored pending buffer replaces the
        prologue — the interrupted run's in-flight updates pick up
        mid-plan."""
        obs = self.observability
        prologue_jit, _ = self._make_async_programs()
        with obs.span("setup", cat="fit"):
            val_batches, val_counts = self._val_batches()
        self._fit_n_rounds = n_rounds
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        with self._ckpt_writer_scope(self._ckpt_every() is not None):
            consumer = self._consumer = RoundConsumer(
                maxsize=self.pipeline_depth
            )
            prefetcher = self._prefetcher = RoundPrefetcher(self)
            try:
                if start_event == 1:
                    with obs.span("async_prologue", cat="fit"):
                        batches1 = self._stage_prologue_batches()
                        (self.client_states,
                         self._async_pending) = prologue_jit(
                            self.server_state, self.client_states, batches1,
                            val_batches,
                        )
                # event e restarts its clients on data plan e+1
                prefetcher.schedule(start_event + 1)
                for e in range(start_event, n_rounds + 1):
                    consumer.raise_pending()
                    with obs.maybe_profile(e):
                        self._run_async_event(e, plan, val_batches,
                                              val_counts)
                consumer.flush()
            finally:
                consumer.close()
                prefetcher.close()
                # retained for the postmortem verdict: which round's host
                # epilogue last FINISHED before this run ended
                self._last_epilogue_round = consumer.last_completed_round
                self._consumer = None
                self._prefetcher = None
                self._async_pending = None

    def _run_async_event(self, e: int, plan, val_batches, val_counts) -> None:
        """Producer half of one buffer-fill event (mirrors ``_run_round``):
        one fused dispatch consumes the event's arrivals, evaluates the
        fresh global and restarts the consumed clients; the host epilogue
        (failure screen, records, metrics, reports, watchdog) runs on the
        RoundConsumer thread."""
        obs = self.observability
        consumer = self._consumer
        prefetcher = self._prefetcher
        _, event_jit = self._make_async_programs()
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            compiles_before = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        t0 = time.time()
        # per-event host boundary: state-kind retunes rebind server_state;
        # a staleness_exponent setattr lands via the live dispatch input
        # (_staleness_exponent_input) this very event
        self._apply_admin_retunes(e)
        with obs.span("round", round=e, kind="async_event"):
            arrivals = jnp.asarray(plan.arrivals[e - 1])
            staleness = jnp.asarray(plan.staleness[e - 1])
            batches_next = (prefetcher.take(e + 1) if prefetcher is not None
                            else self._round_batches(e + 1))
            if prefetcher is not None and e < self._fit_n_rounds:
                prefetcher.schedule(e + 2)
            args = [self.server_state, self.client_states,
                    self._async_pending, batches_next, arrivals, staleness,
                    jnp.asarray(e, jnp.int32), val_batches, val_counts,
                    self._staleness_exponent_input()]
            test = self._test_batches()
            if test is not None:
                args.extend(test)
            with obs.span("async_event", round=e) as ev_span:
                (self.server_state, self.client_states, self._async_pending,
                 out) = event_jit(*args)
                _, device_wait_s = obs.fence(
                    (out["fit_losses"], out["eval_losses"])
                )
                ev_span.set(device_wait_s=device_wait_s)
            compiles_after = compile_s_after = None
            if obs.enabled:
                compiles_after = obs.registry.counter(
                    "jax_backend_compiles_total").value
                compile_s_after = obs.registry.counter(
                    "jax_backend_compiles_seconds_total").value
            device_results = {
                "mask": plan.arrivals[e - 1],
                "fit_losses": out["fit_losses"],
                "fit_metrics": out["fit_metrics"],
                "per_client_fit_losses": out["per_client_fit_losses"],
                "eval_losses": out["eval_losses"],
                "eval_metrics": out["eval_metrics"],
            }
            if "telemetry" in out:
                device_results["telemetry"] = out["telemetry"]
            if "quarantine" in out:
                device_results["_quarantine"] = out["quarantine"]
            if "test_losses" in out:
                device_results["test_losses"] = out["test_losses"]
                device_results["test_metrics"] = out["test_metrics"]
            resume_meta = None
            if self._checkpoint_due(e):
                # async snapshot: server + client stack + the in-flight
                # pending buffer — device-side copies (all three are
                # donated into the next event) riding the consumer's
                # fused transfer, with the plan-prefix fingerprint and
                # virtual clock the resume verifies
                with obs.span("state_snapshot", round=e, what="async"):
                    device_results["_state_trees"] = jax.tree_util.tree_map(
                        jnp.copy,
                        {"server_state": self.server_state,
                         "client_states": self.client_states,
                         "pending": self._async_pending},
                    )
                resume_meta = {
                    "plan_fingerprint": self._async_prefix_fps[e - 1],
                    "virtual_time_s": float(plan.event_times[e - 1]),
                }
            work = _RoundWork(
                round=e,
                device_results=device_results,
                fit_elapsed_s=time.time() - t0,
                eval_elapsed_s=0.0,  # eval is fused into the event program
                device_wait_s=device_wait_s,
                compiles_before=compiles_before,
                compile_s_before=compile_s_before,
                compiles_after=compiles_after,
                compile_s_after=compile_s_after,
                async_info=self._async_event_info(plan, e - 1),
                resume_meta=resume_meta,
            )
            if consumer is not None:
                consumer.submit_round(
                    e, functools.partial(self._finish_round, work))
                if not self.failure_policy.accept_failures:
                    # the failure screen must be able to terminate BEFORE
                    # the next event mutates state — same rule as sync
                    consumer.flush()
            else:
                self._finish_round(work)

    def _fit_async_chunked(self, n_rounds: int, plan,
                           start_event: int = 1) -> None:
        """Async chunked route: prologue dispatch + lax.scan dispatches
        over the buffer-fill events, then the shared chunked epilogue
        reconstructs per-event records (with staleness/cadence facts) from
        each stacked pull. Like the sync chunked route, an attached
        snapshot checkpointer splits the scan at ``checkpoint_every``
        boundaries and persists (server, clients, pending) there; on
        resume the restored pending buffer replaces the prologue."""
        obs = self.observability
        sc = self.state_checkpointer
        chunk_ckpt = self._ckpt_every() is not None
        self._fit_n_rounds = n_rounds
        val_batches, val_counts = self._val_batches()
        test = self._test_batches()
        prologue_jit, _ = self._make_async_programs()
        chunked = self._make_async_chunked()
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        if start_event == 1:
            with obs.span("async_prologue", cat="fit"):
                batches1 = self._stage_prologue_batches()
                self.client_states, pending = prologue_jit(
                    self.server_state, self.client_states, batches1,
                    val_batches,
                )
        else:
            pending = self._async_pending  # restored mid-plan buffer
        # the attribute's job is done (the local carries the buffer from
        # here); clear it so no stale device tree outlives this fit()
        self._async_pending = None
        x_bank, y_bank = self._sharded_train_banks()
        with self._ckpt_writer_scope(chunk_ckpt) as writer:
            s = start_event
            while s <= n_rounds:
                k = self._rounds_per_dispatch(n_rounds, s)
                compiles_before = compile_s_before = 0.0
                if obs.enabled:
                    compiles_before = obs.registry.counter(
                        "jax_backend_compiles_total").value
                    compile_s_before = obs.registry.counter(
                        "jax_backend_compiles_seconds_total").value
                t_start = time.time()
                # event e restarts on data plan e+1: stack plans s+1..s+k
                plans = [self._round_plan(e + 1)
                         for e in range(s, s + k)]
                idx = jnp.asarray(np.stack([p[0] for p in plans]))
                em = jnp.asarray(np.stack([p[1] for p in plans]))
                sm = jnp.asarray(np.stack([p[2] for p in plans]))
                args = [self.server_state, self.client_states, pending,
                        x_bank, y_bank, idx, em, sm,
                        jnp.asarray(plan.arrivals[s - 1:s - 1 + k]),
                        jnp.asarray(plan.staleness[s - 1:s - 1 + k]),
                        jnp.asarray(s, jnp.int32),
                        val_batches, val_counts,
                        self._staleness_exponent_input()]
                if test is not None:
                    args.extend(test)
                with obs.span("fit_async_chunk", cat="fit", rounds=k,
                              start_event=s) as chunk_span:
                    (self.server_state, self.client_states, pending,
                     outs) = chunked(*args)
                    _, device_wait_total = obs.fence(outs)
                    stacked = jax.device_get(outs)
                    if obs.enabled:
                        chunk_span.set(device_wait_s=device_wait_total)
                compiles_after = compile_s_after = None
                if obs.enabled:
                    compiles_after = obs.registry.counter(
                        "jax_backend_compiles_total").value
                    compile_s_after = obs.registry.counter(
                        "jax_backend_compiles_seconds_total").value
                per_round_s = (time.time() - t_start) / max(k, 1)
                device_wait_round = device_wait_total / max(k, 1)
                self._chunked_epilogue(
                    k, stacked, plan.arrivals[s - 1:s - 1 + k],
                    compiles_before, compile_s_before, compiles_after,
                    compile_s_after, per_round_s, device_wait_round,
                    async_plan=plan, start_round=s,
                )
                if chunk_ckpt:
                    e_done = s + k - 1
                    trees = jax.device_get({
                        "server_state": self.server_state,
                        "client_states": self.client_states,
                        "pending": pending,
                    })
                    sc.save_async_snapshot(
                        trees, e_done, self.n_clients, list(self.history),
                        plan_fingerprint=self._async_prefix_fps[e_done - 1],
                        virtual_time_s=float(plan.event_times[e_done - 1]),
                        writer=writer,
                        fleet=self._fleet_snapshot_doc(),
                    )
                s += k

    # -- buffered-async over the registry (FedBuff x cohort slots) -------
    def _fit_async_registry(self, n_rounds: int, plan,
                            start_event: int = 1) -> None:
        """FedBuff over the virtualized registry: the K buffer slots are
        SEATS, and the static :class:`RegistryEventPlan` decides which
        registry client occupies each seat at every buffer-fill event.
        When event *e* consumes a seat's update, the evicted occupant's
        persistent row scatters back into the host registry and the
        incoming occupant's row gathers in — O(K) host work per event, so
        the compiled event program never sees the registry size. The
        occupants' sample counts ride the pending buffer with their
        packets (``_build_async_fns``), so aggregation always weights a
        packet by the counts it TRAINED under, even after its seat was
        reassigned.

        Degenerate parity case (pinned by tests): ``K == N`` with
        FullParticipation seats every client forever — the plan's swaps
        are identities, the staged data plans match the dense ones, and
        the run is bit-identical to dense buffered-async fit()."""
        obs = self.observability
        prologue_jit, _ = self._make_async_programs()
        slots = self.n_clients
        self._fit_n_rounds = n_rounds
        self.server_state, self.client_states = _dedupe_donated(
            self.server_state, self.client_states
        )
        occ = np.asarray(plan.slot_ids[start_event - 1])
        with obs.span("cohort_gather", round=0, valid=slots):
            # seat the initial occupancy: persistent rows in
            self.client_states = jax.device_put(
                self.registry.gather_client_states(occ)
            )
            if self.registry.has_strategy_rows:
                self.server_state = self.strategy.scatter_state_rows(
                    self.server_state,
                    jax.device_put(self.registry.gather_strategy_rows(occ)),
                )
        with self._ckpt_writer_scope(
            bool(self.model_checkpointers), attach_model_ckpts=True,
        ):
            consumer = self._consumer = RoundConsumer(
                maxsize=self.pipeline_depth
            )
            try:
                # the prologue trains every seat's occupant on data plan 1
                with obs.span("async_prologue", cat="fit"):
                    staged = self.registry.stage_round(
                        occ, slots, self._base_entropy, 1
                    )
                    (self.client_states,
                     self._async_pending) = prologue_jit(
                        self.server_state, self.client_states,
                        jax.device_put(staged["batches"]),
                        jax.device_put(staged["val_batches"]),
                        jnp.asarray(staged["sample_counts"]),
                    )
                self._count_cohort_roundtrip()
                for e in range(start_event, n_rounds + 1):
                    consumer.raise_pending()
                    with obs.maybe_profile(e):
                        occ = self._run_async_registry_event(e, plan, occ)
                consumer.flush()
                # end of plan: every seat's live row persists — the
                # registry is the durable store, seats are transient
                rows = jax.device_get(self.client_states)
                srows = None
                if self.registry.has_strategy_rows:
                    srows = jax.device_get(
                        self.strategy.state_rows(self.server_state)
                    )
                self.registry.scatter(occ, slots, rows, srows)
            finally:
                consumer.close()
                self._last_epilogue_round = consumer.last_completed_round
                self._consumer = None
                self._async_pending = None

    def _run_async_registry_event(self, e: int, plan,
                                  occ_prev: np.ndarray) -> np.ndarray:
        """Producer half of one buffer-fill event over the registry:
        swap the consumed seats' occupants (scatter evicted rows, gather
        incoming rows), stage the restart wave's data for the new
        occupancy, dispatch the fused consume->eval->restart program, and
        hand the epilogue to the consumer with the PRE-swap occupancy —
        the consumed packets belong to the evicted occupants. Returns the
        post-swap occupancy for the next event."""
        obs = self.observability
        consumer = self._consumer
        _, event_jit = self._make_async_programs()
        slots = self.n_clients
        compiles_before = compile_s_before = 0.0
        if obs.enabled:
            compiles_before = obs.registry.counter(
                "jax_backend_compiles_total").value
            compile_s_before = obs.registry.counter(
                "jax_backend_compiles_seconds_total").value
        t0 = time.time()
        # same per-event admin boundary as the dense async path
        self._apply_admin_retunes(e)
        with obs.span("round", round=e, kind="async_event"):
            occ_next = np.asarray(plan.slot_ids[e])
            changed = np.nonzero(occ_prev != occ_next)[0]
            gather_ms = scatter_ms = 0.0
            if changed.size:
                with obs.span("registry_swap", round=e,
                              swapped=int(changed.size)) as swap_span:
                    s0 = time.perf_counter()
                    ch = jnp.asarray(changed)
                    has_srows = self.registry.has_strategy_rows
                    # evict: the consumed seats' occupants persist their
                    # rows under their OLD registry ids
                    out_rows = jax.device_get(jax.tree_util.tree_map(
                        lambda t: t[ch], self.client_states
                    ))
                    out_srows = None
                    srows_live = (self.strategy.state_rows(self.server_state)
                                  if has_srows else None)
                    if has_srows:
                        out_srows = jax.device_get(jax.tree_util.tree_map(
                            lambda t: t[ch], srows_live
                        ))
                    self.registry.scatter(
                        occ_prev[changed], int(changed.size), out_rows,
                        out_srows,
                    )
                    scatter_ms = (time.perf_counter() - s0) * 1e3
                    # seat: the incoming occupants' rows replace them
                    g0 = time.perf_counter()
                    in_rows = jax.device_put(
                        self.registry.gather_client_states(occ_next[changed])
                    )
                    self.client_states = jax.tree_util.tree_map(
                        lambda t, n: t.at[ch].set(n),
                        self.client_states, in_rows,
                    )
                    if has_srows:
                        in_srows = jax.device_put(
                            self.registry.gather_strategy_rows(
                                occ_next[changed]
                            )
                        )
                        self.server_state = self.strategy.scatter_state_rows(
                            self.server_state,
                            jax.tree_util.tree_map(
                                lambda t, n: t.at[ch].set(n),
                                srows_live, in_srows,
                            ),
                        )
                    gather_ms = (time.perf_counter() - g0) * 1e3
                    swap_span.set(scatter_ms=scatter_ms,
                                  gather_ms=gather_ms)
            # restart data for the NEW occupancy on data plan e+1; its
            # val batches/counts also feed this event's eval (the eval
            # runs on the post-swap stack)
            st0 = time.perf_counter()
            staged = self.registry.stage_round(
                occ_next, slots, self._base_entropy, e + 1
            )
            batches_next = jax.device_put(staged["batches"])
            val_batches = jax.device_put(staged["val_batches"])
            val_counts = jnp.asarray(staged["val_counts"])
            wave_counts = jnp.asarray(staged["sample_counts"])
            stage_ms = (time.perf_counter() - st0) * 1e3
            args = [self.server_state, self.client_states,
                    self._async_pending, batches_next,
                    jnp.asarray(plan.arrivals[e - 1]),
                    jnp.asarray(plan.staleness[e - 1]),
                    jnp.asarray(e, jnp.int32), val_batches, val_counts,
                    self._staleness_exponent_input(),
                    None, None,  # no held-out test stacks in cohort mode
                    wave_counts]
            with obs.span("async_event", round=e) as ev_span:
                (self.server_state, self.client_states, self._async_pending,
                 out) = event_jit(*args)
                _, device_wait_s = obs.fence(
                    (out["fit_losses"], out["eval_losses"])
                )
                ev_span.set(device_wait_s=device_wait_s)
            self._count_cohort_roundtrip()
            compiles_after = compile_s_after = None
            if obs.enabled:
                compiles_after = obs.registry.counter(
                    "jax_backend_compiles_total").value
                compile_s_after = obs.registry.counter(
                    "jax_backend_compiles_seconds_total").value
            device_results = {
                "mask": plan.arrivals[e - 1],
                "fit_losses": out["fit_losses"],
                "fit_metrics": out["fit_metrics"],
                "per_client_fit_losses": out["per_client_fit_losses"],
                "eval_losses": out["eval_losses"],
                "eval_metrics": out["eval_metrics"],
            }
            if "telemetry" in out:
                device_results["telemetry"] = out["telemetry"]
            if "quarantine" in out:
                device_results["_quarantine"] = out["quarantine"]
            work = _RoundWork(
                round=e,
                device_results=device_results,
                fit_elapsed_s=time.time() - t0,
                eval_elapsed_s=0.0,
                device_wait_s=device_wait_s,
                compiles_before=compiles_before,
                compile_s_before=compile_s_before,
                compiles_after=compiles_after,
                compile_s_after=compile_s_after,
                async_info=self._async_event_info(plan, e - 1),
                # attribution is by the PRE-swap occupancy: seat s's
                # consumed packet was trained by the occupant seated when
                # s last restarted, who held the seat until this swap
                cohort_meta={"idx": occ_prev},
                cohort_info={
                    "cohort_slots": slots,
                    "cohort_valid": slots,
                    "registry_size": self.registry_size,
                    "registry_dirty_rows": self.registry.dirty_rows,
                    "stage_ms": round(stage_ms, 3),
                    "gather_ms": round(gather_ms, 3),
                    "scatter_ms": round(scatter_ms, 3),
                    "staged_bytes": staged["staged_bytes"],
                    "rounds_per_dispatch": 1,
                    "cohort_draw": "event_plan",
                },
            )
            if consumer is not None:
                consumer.submit_round(
                    e, functools.partial(self._finish_round, work))
                if not self.failure_policy.accept_failures:
                    consumer.flush()
            else:
                self._finish_round(work)
        return occ_next

    def _emit_quarantine_metrics(self, rnd: int, q_np: np.ndarray,
                                 ids: np.ndarray | None = None) -> None:
        """``fl_quarantine_*`` gauges/counters + one ``quarantine`` JSONL
        event from a host copy of the in-graph quarantine mask. Shared by
        the pipelined consumer and the chunked epilogue, so quarantine
        visibility is uniform across execution modes. Transition accounting
        (entered/released) diffs against the previous round's mask.
        ``ids`` (cohort-slot rounds) maps slot positions to registry ids so
        the event names real clients."""
        obs = self.observability
        if not obs.enabled:
            return
        reg = obs.registry
        nz = np.nonzero(np.asarray(q_np) > 0)[0]
        if ids is not None:
            # cohort rounds see only the SAMPLED clients' rows: refresh
            # those ids' standing in the persistent registry-wide view so
            # an unsampled quarantined client doesn't read as "released"
            ids = np.asarray(ids)
            cur = self._cohort_quarantine or set()
            for i in ids:
                cur.discard(int(i))
            cur |= {int(i) for i in ids[nz]}
            self._cohort_quarantine = cur
            active = sorted(cur)
        else:
            active = [int(c) for c in nz]
        prev = self._last_quarantine or []
        entered = sorted(set(active) - set(prev))
        released = sorted(set(prev) - set(active))
        self._last_quarantine = active
        reg.gauge(
            "fl_quarantine_active_clients",
            help="clients currently masked out of aggregation by quarantine",
        ).set(float(len(active)))
        if entered:
            reg.counter(
                "fl_quarantine_entries_total",
                help="clients entering quarantine",
            ).inc(len(entered))
        if released:
            reg.counter(
                "fl_quarantine_releases_total",
                help="clients released from quarantine (probation served)",
            ).inc(len(released))
        if active or entered or released:
            reg.log_event(
                "quarantine", round=rnd, source="strategy",
                active=active, entered=entered, released=released,
            )
        flight = obs.flight_recorder
        if flight is not None:
            # late-attach the round's quarantine evidence to its flight
            # entry (this emitter runs right after _record_round_metrics on
            # both paths); `active` is registry-id-space under cohorts
            flight.attach(
                rnd, quarantine=np.asarray(q_np),
                quarantine_active=list(active),
            )

    def _payload_nbytes(self) -> tuple[int, int]:
        """(broadcast, gather) logical payload bytes per participating client
        — what a wire deployment would serialize each round (the arXiv:
        1610.05492 communication-cost accounting). Computed abstractly via
        ``jax.eval_shape`` (no device work) and cached: payload shapes are
        fixed for the life of the compiled round program."""
        if self._payload_bytes_cache is not None:
            return self._payload_bytes_cache
        tree_bytes = ptu.tree_nbytes
        gp = self.strategy.global_params(self.server_state)
        try:
            payload = jax.eval_shape(
                lambda s: self.strategy.client_payload(s, jnp.zeros((), jnp.int32)),
                self.server_state,
            )
            down_tree = payload.params if hasattr(payload, "params") else payload
        except Exception:  # exotic strategy payloads fall back to the globals
            down_tree = gp
        try:
            up_tree = jax.eval_shape(lambda p: self.exchanger.push(p, p), gp)
        except Exception:
            up_tree = gp
        self._payload_bytes_cache = (tree_bytes(down_tree), tree_bytes(up_tree))
        return self._payload_bytes_cache

    def _compressed_gather_nbytes(self) -> int | None:
        """Estimated compressed client->server wire bytes per participating
        client under the active CompressionConfig — the arithmetic the
        transport codec's compressed frames realize
        (compression.codecs.estimate_wire_nbytes). None without
        compression. Shape-metadata only (eval_shape), cached like
        ``_payload_nbytes``."""
        if not self._compression_active:
            return None
        if self._wire_bytes_cache is not None:
            return self._wire_bytes_cache
        from fl4health_tpu.compression.codecs import estimate_wire_nbytes

        gp = self.strategy.global_params(self.server_state)
        try:
            up_tree = jax.eval_shape(lambda p: self.exchanger.push(p, p), gp)
        except Exception:
            up_tree = gp
        self._wire_bytes_cache = estimate_wire_nbytes(up_tree, self.compression)
        return self._wire_bytes_cache

    # -- fleet ledger (observability/fleet.py) ---------------------------
    def _fleet_absorb_round(
        self, rnd: int, mask, host_fit_losses, telemetry,
        *, registry_ids=None, quarantine_mask=None, failed=(),
        async_info: dict | None = None,
    ) -> "dict | None":
        """Fold one completed round into the fleet ledger. Pure host work
        over arrays this epilogue already materialized (the fused transfer
        / stacked scan outputs) — zero device syncs, so ledger-on runs
        stay bit-identical to ledger-off on every execution mode.

        Called BEFORE the round's state checkpoint is written (both the
        pipelined consumer and the chunked epilogues), so a restored
        ledger is always as-of its frame's round: a resume or supervisor
        rollback replays rounds that absorb exactly once — no
        double-counted participation. Returns the round's fleet facts
        (merged into the round summary), or None when no ledger is armed.
        """
        obs = self.observability
        ledger = obs.fleet_ledger if obs.enabled else None
        if ledger is None:
            return None
        mask_np = np.asarray(mask)
        pos = np.nonzero(mask_np > 0)[0]
        ids_arr = None
        if registry_ids is not None:
            # cohort rounds: slots -> the REGISTRY ids they served
            ids_arr = np.asarray(registry_ids)
            pos = pos[pos < len(ids_arr)]
            part_ids = ids_arr[pos].astype(np.int64)
        else:
            part_ids = pos.astype(np.int64)

        def _sel(row):
            if row is None:
                return None
            arr = np.asarray(row)
            if arr.ndim < 1 or (pos.size and pos.max() >= arr.shape[0]):
                return None
            return arr[pos]

        def _map_ids(idxs):
            if ids_arr is None:
                return [int(c) for c in idxs]
            return [int(ids_arr[int(c)]) for c in idxs
                    if 0 <= int(c) < len(ids_arr)]

        q_in = q_out = None
        if quarantine_mask is not None:
            q = np.asarray(quarantine_mask)
            q_in = _map_ids(np.nonzero(q > 0)[0])
            q_out = _map_ids(np.nonzero(q <= 0)[0])
        fault_ids: list[int] = []
        if self._fault_plan is not None:
            # same seeded host mirror _record_round_metrics logs — a pure
            # recomputation, so absorbing here cannot skew the fault event
            try:
                fault = self._fault_plan.summarize_round(rnd, self.n_clients)
            except Exception:
                fault = None
            if fault:
                fault_ids = _map_ids(sorted(
                    set(fault["dropped"]) | set(fault["corrupted"])
                ))
        down, up = self._payload_nbytes()
        return ledger.absorb_round(
            rnd, part_ids,
            losses=_sel((host_fit_losses or {}).get("backward")),
            update_norms=_sel((telemetry or {}).get("update_norm")),
            nonfinite=_sel((telemetry or {}).get("nonfinite")),
            staleness_pool=(async_info or {}).get("_staleness_values"),
            failed_ids=_map_ids(failed or ()),
            quarantined_ids=q_in,
            unquarantined_ids=q_out,
            fault_ids=fault_ids,
            bytes_down_per_client=down,
            bytes_up_per_client=up,
            registry_size=(self.registry_size if self._cohort_active
                           else self.n_clients),
        )

    def _fleet_snapshot_doc(self) -> "dict | None":
        """The ledger's JSON snapshot for a checkpoint frame's host header
        — None when no ledger is armed, so legacy frames are unchanged."""
        obs = self.observability
        if obs.enabled and obs.fleet_ledger is not None:
            return obs.fleet_ledger.snapshot()
        return None

    def adopt_fleet_snapshot(self, doc: "dict | None") -> None:
        """Checkpoint-resume hook (checkpointing/state.py loaders): adopt
        the frame's fleet-ledger state. A legacy frame (no ``fleet`` key)
        clears the ledger — lifetime history older than the durable record
        is better absent than wrong."""
        ledger = self.observability.fleet_ledger
        if ledger is not None:
            ledger.restore(doc)

    def _record_round_metrics(
        self, rnd: int, rec: RoundRecord, mask, host_fit_losses, failed,
        compiles_before: float, compile_s_before: float, device_wait_s: float,
        *, compiles_after: float | None = None,
        compile_s_after: float | None = None,
        telemetry: dict | None = None,
        async_info: dict | None = None,
        cohort_info: dict | None = None,
        fleet_info: dict | None = None,
        registry_ids: np.ndarray | None = None,
    ) -> dict:
        """Per-round gauges/counters + one JSONL ``round`` event; returns the
        summary dict bridged into every reporter. Runs identically on the
        pipelined path (consumer thread) and the chunked path (post-run
        epilogue), so every per-round gauge is uniform across execution
        modes.

        ``telemetry``: host copy of the round's RoundTelemetry (dict of [C]
        numpy arrays). Scalar summaries merge into the ``round`` event and
        telemetry gauges; the per-client vectors land in one ``telemetry``
        JSONL event.

        ``compiles_after``/``compile_s_after``: counter readings taken by the
        PRODUCER right after the round's dispatches. Under the pipelined loop
        this method runs on the consumer thread while later rounds dispatch;
        reading the live counters here would misattribute their compiles to
        this round, so the producer-captured values win when provided."""
        reg = self.observability.registry
        mask_np = np.asarray(mask)
        participants = int((mask_np > 0).sum())
        down, up = self._payload_nbytes()
        bcast, gather = down * participants, up * participants
        reg.counter("fl_rounds_total", help="completed federated rounds").inc()
        reg.counter(
            "fl_client_failures_total",
            help="clients excluded by the failure policy (non-finite loss)",
        ).inc(len(failed))
        reg.gauge(
            "fl_participating_clients",
            help="clients sampled into the current round",
        ).set(participants)
        row = np.asarray(host_fit_losses.get("backward", np.zeros_like(mask_np)))
        sel = row[(mask_np > 0) & np.isfinite(row)]
        loss_std = float(sel.std()) if sel.size else 0.0
        loss_spread = float(sel.max() - sel.min()) if sel.size else 0.0
        reg.gauge(
            "fl_fit_loss_std",
            help="dispersion of participating clients' training loss",
        ).set(loss_std)
        reg.gauge(
            "fl_fit_loss_spread",
            help="straggler proxy: max-min participating client training loss",
        ).set(loss_spread)
        reg.counter(
            "fl_broadcast_bytes_total",
            help="logical server->client payload bytes (what a wire "
                 "deployment would serialize per round)",
        ).inc(bcast)
        reg.counter(
            "fl_gather_bytes_total",
            help="logical client->server payload bytes",
        ).inc(gather)
        gather_wire = None
        wire_per_client = self._compressed_gather_nbytes()
        if wire_per_client is not None:
            # compressed exchange active: fl_wire_* distinguishes the
            # logical payload from what the compressed frames would ship —
            # the SAME accounting helper the transport codec bumps for
            # real frames, under direction="gather"
            from fl4health_tpu.transport.codec import account_wire

            gather_wire = wire_per_client * participants
            account_wire(gather, gather_wire, "gather")
        if compiles_after is None:
            compiles_after = reg.counter("jax_backend_compiles_total").value
        if compile_s_after is None:
            compile_s_after = reg.counter(
                "jax_backend_compiles_seconds_total").value
        summary = {
            "round": rnd,
            "execution_mode": self._active_execution_mode,
            "compiles": compiles_after - compiles_before,
            "compile_s": compile_s_after - compile_s_before,
            "device_wait_s": device_wait_s,
            "fit_s": rec.fit_elapsed_s,
            "eval_s": rec.eval_elapsed_s,
            "host_s": max(
                0.0, rec.fit_elapsed_s + rec.eval_elapsed_s - device_wait_s
            ),
            "broadcast_bytes": bcast,
            "gather_bytes": gather,
            "participants": participants,
            "failures": len(failed),
            "fit_loss_std": loss_std,
            "fit_loss_spread": loss_spread,
        }
        if gather_wire is not None:
            summary["gather_bytes_wire"] = gather_wire
            summary["wire_compression_ratio"] = (
                gather / gather_wire if gather_wire > 0 else None
            )
        if async_info is not None:
            # buffered-async attribution (absent on sync logs, so legacy
            # perf_report tables stay byte-stable): buffer occupancy,
            # per-update staleness and the virtual arrival-driven cadence
            # of this event — the "round cadence set by arrival rate"
            # numbers the async mode exists for
            stal_values = async_info.pop("_staleness_values", [])
            summary.update(async_info)
            reg.gauge(
                "fl_async_buffer_occupancy",
                help="updates consumed by the current buffer-fill event",
            ).set(float(async_info.get("async_buffer", 0)))
            reg.gauge(
                "fl_async_round_cadence_vs",
                help="virtual seconds between consecutive aggregation "
                     "events (arrival-driven round cadence)",
            ).set(float(async_info.get("async_cadence_vs", 0.0)))
            hist = reg.histogram(
                "fl_async_staleness",
                help="staleness (server versions) of consumed updates",
                buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0),
            )
            for s in stal_values:
                hist.observe(float(s))
        if cohort_info is not None:
            # cohort-slot attribution (absent on dense logs, so legacy
            # perf_report tables stay byte-stable): slot occupancy, the
            # registry's size/dirty-row facts, and the staging/gather/
            # scatter walls the O(K) claim is judged by
            summary.update(cohort_info)
            reg.gauge(
                "fl_registry_clients",
                help="clients in the host-resident cohort registry",
            ).set(float(cohort_info["registry_size"]))
            reg.gauge(
                "fl_registry_dirty_rows",
                help="registry clients with materialized (participated) "
                     "state rows — registry host memory is O(this), not "
                     "O(registry)",
            ).set(float(cohort_info["registry_dirty_rows"]))
            reg.gauge(
                "fl_registry_cohort_valid",
                help="real (non-padded) slots in the current round's "
                     "sampled cohort",
            ).set(float(cohort_info["cohort_valid"]))
            reg.counter(
                "fl_registry_staged_bytes_total",
                help="host bytes staged into slot tensors per round "
                     "(train + val batches)",
            ).inc(int(cohort_info["staged_bytes"]))
        if fleet_info is not None:
            # fleet-ledger attribution (absent with the ledger off, so
            # legacy perf_report tables stay byte-stable): new-client
            # count, participation skew and the lifetime straggler tail
            summary.update({k: v for k, v in fleet_info.items()
                            if v is not None})
            ledger = self.observability.fleet_ledger
            reg.gauge(
                "fl_fleet_clients_seen",
                help="clients with a fleet-ledger lifetime record (ledger "
                     "host memory is O(this), not O(registry))",
            ).set(float(len(ledger)))
            reg.counter(
                "fl_fleet_new_clients_total",
                help="first-ever participations absorbed by the fleet "
                     "ledger",
            ).inc(int(fleet_info.get("participants_new") or 0))
            if fleet_info.get("participation_gini") is not None:
                reg.gauge(
                    "fl_fleet_participation_gini",
                    help="participation skew over seen clients (0 = even, "
                         "->1 = a few clients do everything)",
                ).set(float(fleet_info["participation_gini"]))
            if fleet_info.get("straggler_p99") is not None:
                reg.gauge(
                    "fl_fleet_straggler_p99",
                    help="p99 of the lifetime participation-gap "
                         "distribution, in rounds (sketched)",
                ).set(float(fleet_info["straggler_p99"]))
            reg.gauge(
                "fl_fleet_ledger_bytes",
                help="approximate host bytes held by the fleet ledger + "
                     "its sketches (registry-size-invariant)",
            ).set(float(ledger.nbytes()))
        if self._precision_active:
            # precision attribution (absent on f32 logs, so legacy
            # perf_report tables stay byte-stable): the dtype that produced
            # this round's device time — and thus its MFU/tflops numbers
            summary["compute_dtype"] = self.precision.compute_dtype_name
            if self._precision_scaling:
                summary["loss_scale_mode"] = (
                    self.precision.resolved_loss_scale
                )
        if telemetry is not None:
            t_summary = telem.summarize_host(telemetry, mask_np)
            summary.update(t_summary)
            reg.gauge(
                "fl_fit_grad_norm_max",
                help="max per-client gradient norm this round "
                     "(post transform_gradients)",
            ).set(t_summary["grad_norm_max"])
            reg.gauge(
                "fl_fit_update_norm_min",
                help="min participating client update norm (dead-client "
                     "proxy)",
            ).set(t_summary["update_norm_min"])
            reg.gauge(
                "fl_fit_divergence_max",
                help="max client weight divergence from the aggregated "
                     "global",
            ).set(t_summary["divergence_max"])
            reg.gauge(
                "fl_dp_clip_fraction",
                help="mean fraction of examples clipped by the DP path "
                     "(NaN without DP)",
            ).set(t_summary["clip_fraction"])
            reg.gauge(
                "fl_nonfinite_values",
                help="non-finite entries across participating clients' "
                     "params/losses this round",
            ).set(t_summary["nonfinite"])
            reg.log_event(
                "telemetry", round=rnd,
                **{k: np.asarray(v, np.float64).tolist()
                   for k, v in telemetry.items()},
            )
        # MEASURED throughput denominator: the fenced device-execution time
        # when observability fenced this round (it excludes XLA compiles by
        # construction), else the round wall minus its compile delta — a
        # compile-inflated wall would understate MFU ~100x on exactly the
        # big-compile configs this number exists for (round 1, and every
        # amortized chunked round).
        wall = rec.fit_elapsed_s + rec.eval_elapsed_s
        exec_s = (device_wait_s if device_wait_s > 0
                  else wall - summary["compile_s"])
        n_mesh = self._program_builder.n_devices
        if self._program_builder.mesh is not None:
            # mesh-run extras (absent on single-chip logs, so legacy
            # perf_report tables stay byte-stable): devices/axis facts plus
            # the per-chip local-step throughput over device-execution time
            summary["mesh_devices"] = n_mesh
            summary["mesh_client_axis"] = self._program_builder.client_axis_size
            if self._steps_per_client_cache is None:
                if self._cohort_active:
                    # slot rounds: every valid slot runs the registry-wide
                    # step budget (padding steps are masked no-ops but a
                    # finer per-cohort count would vary per round)
                    self._steps_per_client_cache = np.full(
                        (self.n_clients,), float(self.registry.train_steps)
                    )
                else:
                    self._steps_per_client_cache = np.asarray(
                        self._round_plan(1)[2]
                    ).sum(axis=1)
            steps = float(
                (self._steps_per_client_cache * (mask_np > 0)).sum()
            )
            if steps > 0 and exec_s > 0:
                summary["steps_per_s_per_chip"] = steps / exec_s / n_mesh
                reg.gauge(
                    "fl_round_steps_per_s_per_chip",
                    help="participating clients' local steps per second "
                         "per mesh device (device-execution time)",
                ).set(summary["steps_per_s_per_chip"])
        if self._round_program_flops and exec_s > 0:
            # build-time cost_analysis FLOPs over device-execution time —
            # hardware-grounded, unlike bench.py's old analytic formula.
            # mfu_pct only where the chip's peak is known (device_specs);
            # never a made-up percentage. On a mesh the denominator is the
            # whole mesh's wall, so MFU/tflops divide down to PER-CHIP —
            # the honest utilization of each device, comparable across
            # mesh sizes.
            achieved = self._round_program_flops / exec_s
            summary["program_flops_round"] = self._round_program_flops
            summary["program_exec_s"] = exec_s
            summary["tflops_measured"] = achieved / 1e12
            reg.gauge(
                "fl_round_tflops_measured",
                help="measured TFLOP/s this round (cost-model FLOPs / "
                     "device-execution time, whole mesh)",
            ).set(achieved / 1e12)
            if self._program_builder.mesh is not None:
                summary["tflops_per_chip"] = achieved / n_mesh / 1e12
                reg.gauge(
                    "fl_round_tflops_per_chip",
                    help="measured TFLOP/s per mesh device this round",
                ).set(summary["tflops_per_chip"])
            mfu = device_specs.mfu_pct(achieved / n_mesh, self._device_kind)
            if mfu is not None:
                summary["mfu_pct"] = mfu
                reg.gauge(
                    "fl_round_mfu_pct",
                    help="measured model FLOPs utilization vs the chip's "
                         "bf16 peak (per chip on a mesh)",
                ).set(mfu)
        fault = None
        if self._fault_plan is not None:
            # host mirror of the round's seeded in-graph fault draws — the
            # log reports exactly what the compiled program injected
            try:
                fault = self._fault_plan.summarize_round(rnd, self.n_clients)
            except Exception:
                logging.getLogger(__name__).warning(
                    "fault-plan summary failed for round %d", rnd,
                    exc_info=True,
                )
                fault = None
            if fault:
                reg.counter(
                    "fl_resilience_faults_injected_total",
                    help="client faults injected by the active FaultPlan "
                         "(dropouts + corruptions)",
                ).inc(len(fault["dropped"]) + len(fault["corrupted"]))
                reg.log_event("fault", **fault)
                summary["faults_injected"] = (
                    len(fault["dropped"]) + len(fault["corrupted"])
                )
        reg.log_event("round", **summary)
        flight = self.observability.flight_recorder
        if flight is not None:
            # flight-recorder feed: every array here is host data this
            # epilogue already materialized (the fused transfer / stacked
            # scan outputs) — recording adds zero device syncs, and the
            # ring stays O(window x cohort slots) by construction
            flight.record_round(
                rnd, summary,
                fit_loss=rec.fit_losses.get("backward"),
                eval_loss=rec.eval_losses.get("checkpoint"),
                mask=mask_np,
                telemetry=telemetry,
                registry_ids=registry_ids,
                fault=fault or None,
            )
            reg.counter(
                "fl_flightrec_rounds_total",
                help="rounds captured into the flight-recorder ring",
            ).inc()
            reg.gauge(
                "fl_flightrec_ring_bytes",
                help="host bytes of the flight-recorder ring's array "
                     "payload (bounded: O(window x cohort slots))",
            ).set(float(flight.nbytes()))
            reg.gauge(
                "fl_flightrec_window",
                help="flight-recorder ring capacity in rounds",
            ).set(float(flight.window))
        self.observability.tracer.counter(
            "fl_round_time_s", fit=rec.fit_elapsed_s, eval=rec.eval_elapsed_s
        )
        # operations plane (armed via Observability(slo=/admin_token=)):
        # fold this summary into the serving-KPI time-series and evaluate
        # the SLO policy — same host floats as above, zero extra syncs; a
        # shared no-op when unarmed
        self.observability.observe_round_kpis(
            rnd, summary,
            fit_loss=rec.fit_losses.get("backward"),
            eval_loss=rec.eval_losses.get("checkpoint"),
        )
        return summary

    @property
    def global_params(self):
        return self.strategy.global_params(self.server_state)

    def set_global_params(self, params, broadcast_to_clients: bool = True) -> None:
        """Install externally-produced weights (warm-up injection, pretrained
        checkpoint import — preprocessing/checkpoint_io.py) as the global
        model. With ``broadcast_to_clients`` every client's full local tree
        resets to the same weights, the reference's round-1
        initialize_all_model_weights broadcast (basic_client.py:205) — the
        only path by which never-exchanged subtrees (personal layers, frozen
        LoRA base kernels under a lora_exchanger) can receive pretrained
        values."""
        params = jax.tree_util.tree_map(jnp.asarray, params)
        ref = self.strategy.global_params(self.server_state)
        if (jax.tree_util.tree_structure(params)
                != jax.tree_util.tree_structure(ref)):
            raise ValueError(
                "set_global_params: pytree structure does not match the "
                "model's params (run the checkpoint through WarmedUpModule/"
                "warm_up_from_file against this model's init first)"
            )
        any_dtype_mismatch = False
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(ref)[0],
        ):
            if a.shape != b.shape:
                raise ValueError(
                    f"set_global_params: leaf {pa} has shape {a.shape}, "
                    f"model expects {b.shape}"
                )
            any_dtype_mismatch |= a.dtype != b.dtype
        if any_dtype_mismatch:
            # a float64/float16 checkpoint leaf would silently change the
            # compiled program's input signature (recompile) or its
            # precision; cast to the model's dtype instead (AFTER the full
            # shape loop — a later bad-shape leaf must still raise above)
            params = jax.tree_util.tree_map(
                lambda x, y: x.astype(y.dtype), params, ref
            )
        # nesting-safe: a wrapper strategy's state (compression/quarantine)
        # carries the params inside its .inner chain, not at top level
        from fl4health_tpu.strategies.base import replace_global_params

        self.server_state = replace_global_params(
            self.strategy, self.server_state, params
        )
        if broadcast_to_clients:
            n = self.n_clients
            self.client_states = self.client_states.replace(
                params=jax.tree_util.tree_map(
                    lambda x: jnp.stack([x] * n), params
                )
            )

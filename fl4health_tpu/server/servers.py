"""Specialized servers: polling, failure policy, warm starts, DP wiring,
evaluate-only and model-merge orchestration.

Parity targets (/root/reference/fl4health/servers/):
- polling.py:47,63 ``poll_clients`` — get_properties fan-out.
- base_server.py:104,316-318,443-472 — accept_failures policy and
  ``_terminate_after_unacceptable_failures``.
- scaffold_server.py:21,89-163 — SCAFFOLD warm start: every client runs one
  training pass whose weights are DISCARDED; control variates are
  initialized from the average local gradients.
- instance_level_dp_server.py:19 / client_level_dp_fed_avg_server.py:23 —
  sample-count polling + accountant construction + epsilon logging.
- evaluate_server.py:20 — single federated evaluation round from a
  checkpoint, no training.
- model_merge_server.py:23 — one-shot parameter merge + evaluation.
- fedpm_server.py:14 — periodic Beta-posterior reset (the reset itself is
  compiled into strategies.fedpm.FedPm; the server class here is the
  orchestration-level wrapper).
- adaptive_constraint_servers/*.py:12 — thin wrappers asserting the
  strategy/logic pairing for packed adaptive-constraint algorithms.

TPU-native design: clients are in-process mesh shards, so "polling" is a
host-level property lookup (no RPC, no thread pool) and "client failure"
surfaces as non-finite per-client losses in the stacked result (a crashed
gRPC peer has no SPMD equivalent; a NaN-poisoned shard is the analogous
failure mode and is what the policy screens).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp

from fl4health_tpu.privacy.accountants import (
    FlClientLevelAccountantFixedSamplingNoReplacement,
    FlClientLevelAccountantPoissonSampling,
    FlInstanceLevelAccountant,
)
from fl4health_tpu.server.simulation import (
    ClientFailuresError,
    FailurePolicy,
    FederatedSimulation,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Polling protocol
# ---------------------------------------------------------------------------

def poll_clients(
    providers: Sequence[Callable[[Mapping[str, Any]], Mapping[str, Any]]],
    request: Mapping[str, Any],
) -> list[dict[str, Any]]:
    """get_properties fan-out (polling.py:63-98). Providers are per-client
    callables (in-process stand-ins for the gRPC ``get_properties`` handler);
    the reference's thread pool is unnecessary without network latency."""
    return [dict(provider(request)) for provider in providers]


def poll_sample_counts(sim: FederatedSimulation) -> list[int]:
    """poll_clients_for_sample_counts (base_server.py:327-356): ask every
    client for its training-set size."""
    providers = [
        (lambda req, d=d: {"num_train_samples": int(d.n_train)})
        for d in sim.datasets
    ]
    return [p["num_train_samples"] for p in poll_clients(providers, {})]


# ---------------------------------------------------------------------------
# Failure policy
# ---------------------------------------------------------------------------

# Failure policy lives in simulation.py (wired into the round loop there);
# re-exported here because the reference groups it with the server layer.
# ---------------------------------------------------------------------------
# Wrapper-strategy plumbing
# ---------------------------------------------------------------------------

def _unwrap_strategy(strategy):
    """Innermost strategy through any wrapper nesting (CompressingStrategy,
    QuarantiningStrategy, ... — wrappers expose ``.inner``)."""
    while hasattr(strategy, "inner"):
        strategy = strategy.inner
    return strategy


def _set_global_params(strategy, server_state, params):
    """Nesting-safe params install — strategies.base.replace_global_params
    (one shared definition with FederatedSimulation.set_global_params)."""
    from fl4health_tpu.strategies.base import replace_global_params

    return replace_global_params(strategy, server_state, params)


# ---------------------------------------------------------------------------
# SCAFFOLD warm start
# ---------------------------------------------------------------------------

def _keep_warmed_variates(strategy, warmed_state, pre_state, pre_params):
    """Post-warm-up server state: the innermost (Scaffold) state keeps its
    warmed control variates with the ORIGINAL params restored; every
    wrapper layer's bookkeeping (compression EF residual, quarantine
    strikes) rolls back to its pre-warm-up value — the discarded warm-up
    round must not leak into round 1."""
    if hasattr(strategy, "inner") and hasattr(warmed_state, "inner"):
        return pre_state.replace(inner=_keep_warmed_variates(
            strategy.inner, warmed_state.inner, pre_state.inner, pre_params
        ))
    return warmed_state.replace(params=pre_params)


def scaffold_warm_start(sim: FederatedSimulation) -> None:
    """ScaffoldServer warm start (scaffold_server.py:89-163): run one local
    training pass per client, DISCARD the trained weights/optimizer state,
    and keep the resulting control variates (average local gradients:
    c_i = (x - y_i) / (K * lr), which is exactly the round-0 variate update
    with c = 0). The server's variates are warm-started from the aggregated
    deltas while its weights x remain the initial ones."""
    pre_client_states = sim.client_states
    pre_server_state = sim.server_state
    pre_params = sim.global_params
    mask = jnp.ones((sim.n_clients,), jnp.float32)
    batches = sim._round_batches(0)
    val_batches, _ = sim._val_batches()
    # A fresh NON-donating jit of the round program: warm start needs BOTH
    # the pre-round states (rolled back below) and the warmed outputs, so
    # sim._fit_round — which donates its state arguments and invalidates
    # the passed-in buffers — cannot be used here. One extra compile,
    # one-time cost at warm start. Constructed by the sim's program
    # builder so a mesh run's warm start keeps the client axis sharded
    # (same in/out shardings as the round program, donation off).
    fit_once = sim._program_builder.jit(
        sim._fit_round_fn,
        in_shardings=sim._fit_in_sh, out_shardings=sim._fit_out_sh,
    )
    server_state, client_states, _, _, _ = fit_once(
        sim.server_state, sim.client_states, batches, mask,
        jnp.asarray(0, jnp.int32), val_batches,
    )
    # Keep only the warmed variates: client weights/opt/rng/step roll back.
    sim.client_states = pre_client_states.replace(extra=client_states.extra)
    # Server keeps warmed c, original x (scaffold_server.py:139-158 discards
    # the aggregated weights from the warm-up round); wrapper layers
    # (compression residual, quarantine) roll back wholesale.
    sim.server_state = _keep_warmed_variates(
        sim.strategy, server_state, pre_server_state, pre_params
    )
    logger.info("SCAFFOLD warm start complete: control variates initialized "
                "from average local gradients; model weights unchanged.")


class ScaffoldServer:
    """Server wrapper running SCAFFOLD with optional warm start
    (scaffold_server.py:21)."""

    def __init__(self, sim: FederatedSimulation, warm_start: bool = False):
        from fl4health_tpu.strategies.scaffold import Scaffold

        assert isinstance(_unwrap_strategy(sim.strategy), Scaffold), (
            "ScaffoldServer requires the Scaffold strategy (possibly "
            "wrapped, e.g. by compression)"
        )
        self.sim = sim
        self.warm_start = warm_start

    def fit(self, n_rounds: int):
        if self.warm_start:
            scaffold_warm_start(self.sim)
        return self.sim.fit(n_rounds)


# ---------------------------------------------------------------------------
# DP servers
# ---------------------------------------------------------------------------

class InstanceLevelDpServer:
    """Instance-level DP orchestration (instance_level_dp_server.py:19):
    polls per-client sample counts, configures the FL instance-level
    accountant, and logs/returns epsilon for the run."""

    def __init__(self, sim: FederatedSimulation, noise_multiplier: float,
                 batch_size: int, local_epochs: int | None = None,
                 local_steps: int | None = None, delta: float | None = None):
        self.sim = sim
        self.noise_multiplier = noise_multiplier
        self.batch_size = batch_size
        self.local_epochs = local_epochs if local_epochs is not None else sim.local_epochs
        self.local_steps = local_steps if local_steps is not None else sim.local_steps
        self.delta = delta
        self.accountant: FlInstanceLevelAccountant | None = None

    def setup_accountant(self, n_rounds: int) -> FlInstanceLevelAccountant:
        counts = poll_sample_counts(self.sim)
        # Client sampling ratio: expected fraction of clients per round.
        q_client = getattr(self.sim.client_manager, "fraction", 1.0)
        self.accountant = FlInstanceLevelAccountant(
            client_sampling_rate=q_client,
            noise_multiplier=self.noise_multiplier,
            epochs_per_round=self.local_epochs,
            client_batch_sizes=[self.batch_size] * len(counts),
            client_dataset_sizes=counts,
            steps_per_round=self.local_steps,
        )
        return self.accountant

    def fit(self, n_rounds: int, extra_full_participation_rounds: int = 0):
        # extra_full_participation_rounds: additional privacy-budget rounds
        # where EVERY client touches data (no client-subsampling
        # amplification), e.g. DP-SCAFFOLD's warm-start pass.
        self.setup_accountant(n_rounds)
        assert self.accountant is not None
        # Default delta = 1/total_samples across the federation
        # (instance_level_dp_server.py:163) — NOT 1/max(client size), which
        # would silently report a much weaker guarantee.
        delta = self.delta if self.delta is not None else 1.0 / sum(
            poll_sample_counts(self.sim)
        )
        epsilon = self.accountant.get_epsilon(
            n_rounds, delta,
            full_participation_rounds=extra_full_participation_rounds,
        )
        logger.info(
            "Instance-level DP run: epsilon=%.4f at delta=%.2e over %d rounds"
            " (+%d full-participation)",
            epsilon, delta, n_rounds, extra_full_participation_rounds,
        )
        history = self.sim.fit(n_rounds)
        return history, epsilon


class DpScaffoldServer(InstanceLevelDpServer):
    """DP-SCAFFOLD orchestration (scaffold_server.py:184 ``DPScaffoldServer``):
    SCAFFOLD control-variate warm start composed with instance-level DP
    accounting — the warm-start pass runs under the same DP-SGD client logic,
    matching the reference's ordering (warm start, then accountant setup +
    training rounds)."""

    def __init__(self, sim: FederatedSimulation, noise_multiplier: float,
                 batch_size: int, warm_start: bool = False, **kwargs):
        from fl4health_tpu.strategies.scaffold import Scaffold

        assert isinstance(sim.strategy, Scaffold), (
            "DpScaffoldServer requires the Scaffold strategy"
        )
        super().__init__(sim, noise_multiplier, batch_size, **kwargs)
        self.warm_start = warm_start

    def fit(self, n_rounds: int):
        if self.warm_start:
            scaffold_warm_start(self.sim)
        # The warm-start pass is a full DP-SGD sweep over private data whose
        # control variates ARE later exchanged, so it spends one round of
        # privacy budget; count it (the reference DPScaffoldServer omits it —
        # its printed epsilon understates the true spend when warm-starting).
        # It runs with EVERY client participating, so it is composed WITHOUT
        # the client-subsampling amplification the training rounds get.
        return super().fit(
            n_rounds,
            extra_full_participation_rounds=1 if self.warm_start else 0,
        )


class ClientLevelDpFedAvgServer:
    """Client-level DP orchestration (client_level_dp_fed_avg_server.py:23):
    counts clients, builds the client-level accountant matching the sampling
    scheme, logs epsilon."""

    def __init__(self, sim: FederatedSimulation, noise_multiplier: float,
                 delta: float | None = None):
        self.sim = sim
        self.noise_multiplier = noise_multiplier
        self.delta = delta

    def _accountant(self):
        from fl4health_tpu.server.client_manager import PoissonSamplingManager

        manager = self.sim.client_manager
        n = self.sim.n_clients
        fraction = getattr(manager, "fraction", 1.0)
        if isinstance(manager, PoissonSamplingManager):
            return FlClientLevelAccountantPoissonSampling(
                client_sampling_rate=fraction, noise_multiplier=self.noise_multiplier
            )
        return FlClientLevelAccountantFixedSamplingNoReplacement(
            n_total_clients=n,
            n_clients_sampled=max(int(round(fraction * n)), 1),
            noise_multiplier=self.noise_multiplier,
        )

    def fit(self, n_rounds: int):
        accountant = self._accountant()
        delta = self.delta if self.delta is not None else 1.0 / self.sim.n_clients
        epsilon = accountant.get_epsilon(n_rounds, delta)
        logger.info("Client-level DP run: epsilon=%.4f at delta=%.2e over %d rounds",
                    epsilon, delta, n_rounds)
        history = self.sim.fit(n_rounds)
        return history, epsilon


# ---------------------------------------------------------------------------
# Evaluate-only server
# ---------------------------------------------------------------------------

class EvaluateServer:
    """Single federated evaluation round (evaluate_server.py:20): load model
    weights (e.g. from a checkpointer), broadcast, evaluate on every client,
    aggregate. No training rounds."""

    def __init__(self, sim: FederatedSimulation, params=None):
        self.sim = sim
        self.params = params

    def fit(self):
        sim = self.sim
        if self.params is not None:
            # Hydrate the server model from the provided checkpoint params
            # (evaluate_server.py loads from model checkpoint path) —
            # through any strategy wrappers (compression/quarantine).
            sim.server_state = _set_global_params(
                sim.strategy, sim.server_state, self.params
            )
        val_batches, val_counts = sim._val_batches()
        # _eval_round donates the client stack — re-assign the returned one
        # (value-identical modulo the pulled params) so the sim stays usable.
        (
            sim.client_states, losses, metrics, per_losses, per_metrics,
        ) = sim._eval_round(
            sim.server_state, sim.client_states, val_batches, val_counts
        )
        host = jax.device_get((losses, metrics))  # one fused transfer
        out_losses = {k: float(v) for k, v in host[0].items()}
        out_metrics = {k: float(v) for k, v in host[1].items()}
        return out_losses, out_metrics


# ---------------------------------------------------------------------------
# Model-merge server
# ---------------------------------------------------------------------------

class ModelMergeServer:
    """One-shot parameter merge + federated evaluation
    (model_merge_server.py:23): clients send their locally-trained weights
    once; the merge strategy averages them; the merged model is evaluated on
    all clients."""

    def __init__(self, sim: FederatedSimulation):
        self.sim = sim

    def fit(self):
        sim = self.sim
        # One "round" with zero local steps is not meaningful here; instead
        # merge the clients' CURRENT parameters directly (the reference's
        # clients train locally before connecting).
        from fl4health_tpu.core import aggregate as agg

        stacked = sim.client_states.params
        weights = jnp.ones((sim.n_clients,), jnp.float32)
        merged = jax.tree_util.tree_map(
            lambda s: jnp.sum(
                s * weights.reshape((-1,) + (1,) * (s.ndim - 1)), axis=0
            ) / jnp.sum(weights),
            stacked,
        )
        evaluator = EvaluateServer(sim, params=merged)
        losses, metrics = evaluator.fit()
        return merged, losses, metrics


# ---------------------------------------------------------------------------
# Thin parity wrappers
# ---------------------------------------------------------------------------

class FedPmServer:
    """FedPM orchestration (fedpm_server.py:14). The periodic Beta reset is
    compiled into strategies.fedpm.FedPm(reset_frequency=...); this wrapper
    asserts the pairing."""

    def __init__(self, sim: FederatedSimulation):
        from fl4health_tpu.strategies.fedpm import FedPm

        assert isinstance(sim.strategy, FedPm), "FedPmServer requires the FedPm strategy"
        self.sim = sim

    def fit(self, n_rounds: int):
        return self.sim.fit(n_rounds)


class FedProxServer:
    """adaptive_constraint_servers/fedprox_server.py:12 — asserts the
    adaptive-constraint strategy pairing."""

    def __init__(self, sim: FederatedSimulation):
        from fl4health_tpu.strategies.fedprox import FedAvgWithAdaptiveConstraint

        assert isinstance(sim.strategy, FedAvgWithAdaptiveConstraint), (
            "FedProxServer requires FedAvgWithAdaptiveConstraint"
        )
        self.sim = sim

    def fit(self, n_rounds: int):
        return self.sim.fit(n_rounds)


class DittoServer(FedProxServer):
    """adaptive_constraint_servers/ditto_server.py — same packing contract."""


class MrMtlServer(FedProxServer):
    """adaptive_constraint_servers/mrmtl_server.py — same packing contract."""

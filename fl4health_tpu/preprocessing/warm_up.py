"""Warm-start weight injection with name-mapping surgery.

Parity: WarmedUpModule (/root/reference/fl4health/preprocessing/
warmed_up_module.py:10): copy a pretrained model's states into a target
model wherever keys (after optional prefix remapping) and shapes match;
non-matching leaves keep their fresh initialization.

TPU-native design: operates on params pytrees; keys are '.'-joined tree
paths (flax param naming). The mapping may contain PARTIAL prefixes — the
longest-prefix match rewrites the head of the path, exactly like the
reference's get_matching_component (:57-84).
"""

from __future__ import annotations

import logging

import jax

from fl4health_tpu.core.types import Params

logger = logging.getLogger(__name__)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return ".".join(parts)


class WarmedUpModule:
    """Pretrained-weight injection (warmed_up_module.py:10)."""

    def __init__(self, pretrained_params: Params,
                 weights_mapping: dict[str, str] | None = None):
        flat = jax.tree_util.tree_flatten_with_path(pretrained_params)[0]
        self.pretrained = {_path_str(path): leaf for path, leaf in flat}
        self.weights_mapping = weights_mapping

    def get_matching_component(self, key: str) -> str | None:
        """Prefix-rewrite a target key into the pretrained namespace
        (warmed_up_module.py:57-84)."""
        if self.weights_mapping is None:
            return key
        components = key.split(".")
        prefix = ""
        for i, component in enumerate(components):
            prefix = component if i == 0 else f"{prefix}.{component}"
            if prefix in self.weights_mapping:
                # lstrip handles empty-string replacements ({"global_model":
                # ""} -> "Dense_0.kernel", not ".Dense_0.kernel").
                return (self.weights_mapping[prefix] + key[len(prefix):]).lstrip(".")
        return None

    def load_from_pretrained(self, params: Params) -> Params:
        """Return ``params`` with every matchable leaf replaced by its
        pretrained counterpart (warmed_up_module.py:85-120)."""
        matched = [0]

        def inject(path, leaf):
            key = _path_str(path)
            pretrained_key = self.get_matching_component(key)
            if pretrained_key is None or pretrained_key not in self.pretrained:
                return leaf
            candidate = self.pretrained[pretrained_key]
            if candidate.shape != leaf.shape:
                logger.warning(
                    "state not loaded, mismatched shapes %s -> %s for %s",
                    leaf.shape, candidate.shape, key,
                )
                return leaf
            matched[0] += 1
            return candidate

        out = jax.tree_util.tree_map_with_path(inject, params)
        total = len(jax.tree_util.tree_leaves(params))
        logger.info("%d/%d states were matched.", matched[0], total)
        return out

"""Pretrained-checkpoint import: on-disk weights -> flax param pytrees.

Parity surface: the reference fine-tunes from actually-pretrained weights
(/root/reference/examples/bert_finetuning_example loads HF
``BertForSequenceClassification``; /root/reference/fl4health/preprocessing/
warmed_up_module.py:10 injects saved torch state dicts by name). This module
is the file half of that story for the TPU stack: read a checkpoint file
into a flat {dotted.path: array} namespace, hand it to ``WarmedUpModule``'s
name-mapping surgery, and start training from weights instead of noise.

Formats:
- ``.npz`` — the native format (``save_checkpoint`` writes it): keys are
  '.'-joined flax tree paths.
- ``.safetensors`` — read via the ``safetensors`` package when installed
  (gated import; absent in this image).
- ``.pt`` / ``.bin`` — torch state dicts (HF checkpoint files) via the baked
  -in cpu torch, ``weights_only=True`` so loading is data-not-code.

Torch Linear stores ``weight`` as [out, in]; flax Dense kernels are
[in, out]. ``torch_linear_convention=True`` transposes every 2-D tensor
whose key ends in ``.weight`` and renames ``.weight``/``.bias`` to
``.kernel``/``.bias`` so torch-exported dense layers line up with flax
naming before the prefix surgery runs.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Mapping

import numpy as np

from fl4health_tpu.preprocessing.warm_up import WarmedUpModule, _path_str


def flatten_params(params: Any) -> dict[str, np.ndarray]:
    """Params pytree -> {dotted.path: host array}."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return {_path_str(path): np.asarray(leaf) for path, leaf in flat}


def save_checkpoint(path: str | Path, params: Any) -> Path:
    """Write a params pytree as a flat .npz checkpoint (the native format
    ``load_flat_checkpoint`` round-trips)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez(path, **flatten_params(params))
    # np.savez appends .npz when absent; normalize the returned path.
    return path if path.suffix == ".npz" else path.with_suffix(
        path.suffix + ".npz"
    )


def load_flat_checkpoint(
    path: str | Path, torch_linear_convention: bool = False
) -> dict[str, np.ndarray]:
    """Read a checkpoint file -> flat {dotted.path: np.ndarray} namespace."""
    path = Path(path)
    if not path.exists():
        raise FileNotFoundError(f"checkpoint not found: {path}")
    suffix = path.suffix.lower()
    if suffix == ".npz":
        with np.load(path) as z:
            flat = {k: z[k] for k in z.files}
    elif suffix == ".safetensors":
        try:
            from safetensors.numpy import load_file
        except ImportError as e:  # pragma: no cover - absent in this image
            raise ImportError(
                "reading .safetensors requires the safetensors package; "
                "convert to .npz (save_checkpoint) instead"
            ) from e
        flat = dict(load_file(str(path)))
    elif suffix in (".pt", ".bin", ".pth"):
        import torch

        state = torch.load(path, map_location="cpu", weights_only=True)
        if hasattr(state, "state_dict"):
            state = state.state_dict()
        flat = {k: v.detach().cpu().numpy() for k, v in state.items()}
    else:
        raise ValueError(
            f"unsupported checkpoint format {suffix!r} "
            "(expected .npz, .safetensors, .pt, .bin)"
        )
    if torch_linear_convention:
        flat = _torchify_to_flax(flat)
    return flat


def _torchify_to_flax(flat: Mapping[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Widen the namespace toward flax convention: every key keeps its raw
    torch form, and 2-D ``*.weight`` tensors ADDITIONALLY appear as a
    transposed ``*.kernel`` alias (torch Linear is [out, in]; flax Dense is
    [in, out]) unless the key path mentions an embedding (embedding tables
    are [num, dim] in BOTH frameworks — transposing one would pass or fail
    the warm-up shape check for exactly the wrong reason). Keeping the raw
    key alongside the alias means a caller's ``weights_mapping`` can always
    target whichever orientation its model needs; WarmedUpModule's shape
    check arbitrates per leaf."""
    out: dict[str, np.ndarray] = dict(flat)
    for k, v in flat.items():
        if ((k == "weight" or k.endswith(".weight")) and v.ndim == 2
                and "embed" not in k.lower()):
            out[k[: -len("weight")] + "kernel"] = v.T
    return out


def warm_up_from_file(
    params: Any,
    path: str | Path,
    weights_mapping: dict[str, str] | None = None,
    torch_linear_convention: bool = False,
) -> Any:
    """One-call warm start: load ``path``, run WarmedUpModule's longest-
    prefix name surgery, and return ``params`` with every matchable,
    shape-compatible leaf replaced (mismatches keep fresh init and log —
    warmed_up_module.py:85-120 semantics)."""
    flat = load_flat_checkpoint(path, torch_linear_convention)
    return WarmedUpModule(flat, weights_mapping).load_from_pretrained(params)

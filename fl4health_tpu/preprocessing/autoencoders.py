"""Autoencoder data plumbing: dataset converter + dim-reduction processors.

Parity targets:
- AutoEncoderDatasetConverter (/root/reference/fl4health/utils/
  dataset_converter.py:68): rewires a supervised (x, y) dataset for
  self-supervised AE training — target becomes the input, and an optional
  condition (fixed vector or per-sample label, optionally one-hot) is packed
  into the input tensor; provides the matching unpacking function the CVAE
  consumes (``unpack_input_condition``, :204).
- AeProcessor / VaeProcessor / CvaeFixedConditionProcessor /
  CvaeVariableConditionProcessor (/root/reference/fl4health/preprocessing/
  autoencoders/dim_reduction.py:42-144): map samples into the latent space of
  a trained encoder.
- PcaPreprocessor (/root/reference/fl4health/preprocessing/
  pca_preprocessor.py:10): dimensionality reduction through saved principal
  components.

TPU-native design: converters are array->array transforms applied to whole
stacked datasets (one vectorized op instead of per-item __getitem__ hooks);
processors close over (apply_fn, params) pairs instead of loading torch
checkpoints.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from fl4health_tpu.models.autoencoders import PcaModule, PcaState, reparameterize


class AutoEncoderDatasetConverter:
    """Pack (x, y) into AE-training form (dataset_converter.py:68).

    condition: None (plain AE/VAE), "label" (per-sample label condition,
    optionally one-hot), or a fixed 1-D array shared by all samples.
    """

    def __init__(self, condition: str | jax.Array | None = None,
                 do_one_hot_encoding: bool = False,
                 custom_converter: Callable | None = None,
                 condition_vector_size: int | None = None):
        self.condition = condition
        self.do_one_hot_encoding = do_one_hot_encoding
        self.custom_converter = custom_converter
        self._condition_vector_size = condition_vector_size
        self.data_shape: tuple[int, ...] | None = None
        self._n_classes: int | None = None
        if custom_converter is not None and condition_vector_size is None:
            raise ValueError("condition_vector_size is required with a custom converter")

    def get_condition_vector_size(self) -> int:
        """(dataset_converter.py:124)"""
        if self._condition_vector_size is not None:
            return self._condition_vector_size
        if self.condition is None:
            return 0
        if isinstance(self.condition, str) and self.condition == "label":
            if self._n_classes is None:
                raise RuntimeError("convert_dataset must run before the size is known")
            return self._n_classes
        return int(jnp.asarray(self.condition).shape[0])

    def convert_dataset(self, x: jax.Array, y: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Vectorized equivalent of the reference's per-item converter
        functions (:162-193): returns (packed_inputs, targets=original x)."""
        self.data_shape = tuple(x.shape[1:])
        if self.custom_converter is not None:
            return self.custom_converter(x, y)
        flat = x.reshape(x.shape[0], -1)
        if self.condition is None:
            return x, x  # self-supervised: target is the data (:162-168)
        if isinstance(self.condition, str) and self.condition == "label":
            if self.do_one_hot_encoding:
                self._n_classes = int(jnp.max(y)) + 1
                cond = jax.nn.one_hot(y, self._n_classes)
            else:
                cond = y.reshape(y.shape[0], -1)
                self._n_classes = cond.shape[1]
            return jnp.concatenate([flat, cond], axis=1), x  # (:182-193)
        cond = jnp.broadcast_to(
            jnp.asarray(self.condition)[None, :], (x.shape[0], len(self.condition))
        )
        return jnp.concatenate([flat, cond], axis=1), x  # (:169-180)

    def get_unpacking_function(self) -> Callable[[jax.Array], tuple[jax.Array, jax.Array]]:
        """For ConditionalVae.unpack_input_condition (:195-215)."""
        cond_size = self.get_condition_vector_size()
        data_shape = self.data_shape

        def unpack(packed: jax.Array) -> tuple[jax.Array, jax.Array]:
            if cond_size == 0:
                return packed, jnp.zeros((packed.shape[0], 0), packed.dtype)
            data = packed[:, :-cond_size].reshape(packed.shape[0], *data_shape)
            cond = packed[:, -cond_size:]
            return data, cond

        return unpack


class AeProcessor:
    """Encode samples into the latent space (dim_reduction.py:42): sample ->
    encoder(sample)."""

    def __init__(self, encode_fn: Callable[[jax.Array], jax.Array]):
        self.encode_fn = encode_fn

    def __call__(self, sample: jax.Array) -> jax.Array:
        return self.encode_fn(sample)


class VaeProcessor:
    """VAE latent processor (dim_reduction.py:51): returns mu, or mu + eps*std
    when return_mu_only=False."""

    def __init__(self, encode_fn: Callable[[jax.Array], tuple[jax.Array, jax.Array]],
                 return_mu_only: bool = False, seed: int = 0):
        self.encode_fn = encode_fn
        self.return_mu_only = return_mu_only
        self._rng = jax.random.PRNGKey(seed)

    def __call__(self, sample: jax.Array) -> jax.Array:
        mu, logvar = self.encode_fn(sample)
        if self.return_mu_only:
            return mu
        self._rng, sub = jax.random.split(self._rng)
        return reparameterize(mu, logvar, sub)


class CvaeFixedConditionProcessor:
    """CVAE latent processor with one condition for every sample
    (dim_reduction.py:81)."""

    def __init__(self, encode_fn: Callable[[jax.Array, jax.Array], tuple[jax.Array, jax.Array]],
                 condition: jax.Array, return_mu_only: bool = False, seed: int = 0):
        self.encode_fn = encode_fn
        self.condition = condition
        self.return_mu_only = return_mu_only
        self._rng = jax.random.PRNGKey(seed)

    def __call__(self, sample: jax.Array) -> jax.Array:
        cond = jnp.broadcast_to(
            self.condition[None, :], (sample.shape[0], self.condition.shape[0])
        )
        mu, logvar = self.encode_fn(sample, cond)
        if self.return_mu_only:
            return mu
        self._rng, sub = jax.random.split(self._rng)
        return reparameterize(mu, logvar, sub)


class CvaeVariableConditionProcessor:
    """CVAE latent processor with per-sample conditions (dim_reduction.py:124)."""

    def __init__(self, encode_fn: Callable[[jax.Array, jax.Array], tuple[jax.Array, jax.Array]],
                 return_mu_only: bool = False, seed: int = 0):
        self.encode_fn = encode_fn
        self.return_mu_only = return_mu_only
        self._rng = jax.random.PRNGKey(seed)

    def __call__(self, sample: jax.Array, condition: jax.Array) -> jax.Array:
        mu, logvar = self.encode_fn(sample, condition)
        if self.return_mu_only:
            return mu
        self._rng, sub = jax.random.split(self._rng)
        return reparameterize(mu, logvar, sub)


class PcaPreprocessor:
    """Dimensionality reduction through saved principal components
    (pca_preprocessor.py:10)."""

    def __init__(self, pca_state: PcaState, pca_module: PcaModule | None = None):
        self.state = pca_state
        self.module = pca_module or PcaModule()

    def reduce_dimension(self, x: jax.Array, new_dimension: int,
                         center_data: bool = False) -> jax.Array:
        """(pca_preprocessor.py:26)"""
        return self.module.project_lower_dim(self.state, x, new_dimension, center_data)

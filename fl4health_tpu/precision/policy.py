"""PrecisionConfig + the in-graph mechanics of mixed-precision training.

The recipe (Micikevicius et al., "Mixed Precision Training",
arXiv:1710.03740), engine-native:

- **compute dtype**: float params and float inputs are cast to
  ``compute_dtype`` at model-apply time — inside the compiled train step,
  via a shallow wrapper around the logic's :class:`ModelDef` — so the
  forward/backward runs on the MXU-native bf16/fp16 path for EVERY model,
  including ones with no ``dtype`` knob, and every client logic that
  routes its forward through ``logic.model.apply`` (the default
  ``predict``, the DP per-example path, APFL's dual forward, ...).
- **f32 master weights**: ``TrainState.params`` (and the optimizer state
  derived from it) stay f32. Gradients are taken with respect to the f32
  master — the cast's VJP promotes the cotangent back to f32 at the
  parameter boundary — and optax updates apply in f32. Penalty terms that
  read ``params`` directly (FedProx/Ditto prox, SCAFFOLD variates, DP
  clip+noise) therefore compute in f32, untouched by the policy.
- **loss scaling** (fp16): the backward pass is seeded with the scale as
  the loss cotangent (mathematically identical to scaling the loss, zero
  model edits), gradients are unscaled in f32, and a non-finite gradient
  skips the optimizer step. Scale / growth counter / skipped-step count
  live in the carried :class:`TrainState`, so the chunked-scan and
  pipelined execution modes evolve the scale identically.

The ONE promotion rule shared by the engine cast and both conv
implementations (``models/cnn.py`` ``nn.Conv`` / ``MxuConv``) is
:func:`conv_compute_dtype`: compute dtype = ``jnp.result_type`` over the
input and every parameter entering the op. Under the engine cast all of
them are already ``compute_dtype``, so the rule degenerates to the policy
dtype; without a policy it reproduces flax's ``dtype=None`` promotion.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

_DTYPE_ALIASES = {
    "f32": "float32", "fp32": "float32", "float32": "float32",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "f16": "float16", "fp16": "float16", "float16": "float16",
}
_LOSS_SCALE_MODES = ("auto", "none", "static", "dynamic")


def _canonical_dtype_name(dtype: Any) -> str:
    if isinstance(dtype, str):
        name = _DTYPE_ALIASES.get(dtype.lower())
        if name is None:
            raise ValueError(
                f"compute_dtype must be one of f32|bf16|fp16 (got {dtype!r})"
            )
        return name
    name = jnp.dtype(dtype).name
    if name not in _DTYPE_ALIASES:
        raise ValueError(
            f"compute_dtype must be float32, bfloat16 or float16; got {name}"
        )
    return name


@dataclasses.dataclass(frozen=True)
class PrecisionConfig:
    """Static mixed-precision recipe for the cohort engine.

    - ``compute_dtype``: dtype of the forward/backward math
      (``"f32"``/``"bf16"``/``"fp16"`` or the jnp dtypes). ``f32`` builds
      the exact pre-precision program (bit-identical, pinned by tests).
    - ``keep_master_f32``: the master-weight contract. Only ``True`` is
      supported for low-precision compute — params, optimizer state, DP
      noise, EF residuals and ZeRO-1 server shards all assume f32 master
      state; ``False`` is accepted solely for the no-op f32 config.
    - ``loss_scale``: ``"none"`` | ``"static"`` | ``"dynamic"``; the
      default ``"auto"`` resolves to ``"dynamic"`` for fp16 (whose 5-bit
      exponent underflows real gradients) and ``"none"`` otherwise.
    - ``init_scale``/``growth_interval``/``growth_factor``/
      ``backoff_factor``/``min_scale``/``max_scale``: the standard dynamic
      scaler knobs (torch.cuda.amp semantics, evolved per local step).
    """

    compute_dtype: Any = "bfloat16"
    keep_master_f32: bool = True
    loss_scale: str = "auto"
    init_scale: float = 2.0 ** 15
    growth_interval: int = 200
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    min_scale: float = 1.0
    max_scale: float = 2.0 ** 24

    def __post_init__(self):
        name = _canonical_dtype_name(self.compute_dtype)
        object.__setattr__(self, "compute_dtype", name)
        if self.loss_scale not in _LOSS_SCALE_MODES:
            raise ValueError(
                f"loss_scale must be one of {_LOSS_SCALE_MODES}; "
                f"got {self.loss_scale!r}"
            )
        if name == "float32" and self.loss_scale in ("static", "dynamic"):
            raise ValueError(
                "loss_scale with f32 compute is a no-op that still pays the "
                "finite-check and skip machinery — pick a low-precision "
                "compute_dtype or loss_scale='none'"
            )
        if not self.keep_master_f32 and name != "float32":
            raise ValueError(
                "keep_master_f32=False is unsupported for low-precision "
                "compute: the engine's TrainState, DP clip->noise, EF "
                "residuals and ZeRO-1 server shards are all contracted to "
                "f32 master weights (Micikevicius et al.'s recipe). Use "
                "the per-model dtype knob if you truly want low-precision "
                "storage."
            )
        if self.init_scale <= 0 or self.min_scale <= 0:
            raise ValueError("loss scales must be positive")
        if self.growth_interval < 1:
            raise ValueError("growth_interval must be >= 1")
        if self.growth_factor <= 1.0 or not (0.0 < self.backoff_factor < 1.0):
            raise ValueError(
                "growth_factor must exceed 1.0 and backoff_factor lie in "
                "(0, 1) — otherwise the dynamic scale cannot move the right "
                "direction"
            )

    # -- derived facts ---------------------------------------------------
    @property
    def compute_dtype_name(self) -> str:
        return self.compute_dtype  # canonicalized in __post_init__

    @property
    def compute_jnp_dtype(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def casts_compute(self) -> bool:
        return self.compute_dtype != "float32"

    @property
    def resolved_loss_scale(self) -> str:
        if self.loss_scale != "auto":
            return self.loss_scale
        return "dynamic" if self.compute_dtype == "float16" else "none"

    @property
    def scaling_active(self) -> bool:
        return self.resolved_loss_scale != "none"

    @property
    def active(self) -> bool:
        """False == the engine builds the exact pre-precision program."""
        return self.casts_compute or self.scaling_active

    def describe(self) -> dict:
        """JSON-able policy facts (run manifest / round+program events /
        bench artifacts)."""
        return {
            "compute_dtype": self.compute_dtype_name,
            "keep_master_f32": self.keep_master_f32,
            "loss_scale": self.resolved_loss_scale,
        }


def resolve(precision: PrecisionConfig | None) -> PrecisionConfig | None:
    """None-or-inactive -> None, so every consumer has ONE check for "build
    the legacy program"."""
    if precision is None or not precision.active:
        return None
    return precision


# ---------------------------------------------------------------------------
# Casting
# ---------------------------------------------------------------------------

def cast_floats(tree: Any, dtype) -> Any:
    """Cast every floating leaf of a pytree to ``dtype``; integer/bool
    leaves (labels, token ids, masks) pass through untouched."""
    dtype = jnp.dtype(dtype)

    def cast(x):
        if jnp.issubdtype(jnp.result_type(x), jnp.floating):
            return jnp.asarray(x).astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def conv_compute_dtype(x_dtype, *param_dtypes):
    """THE shared promotion rule for ``dtype=None`` ops: compute dtype =
    ``jnp.result_type`` over the input and every parameter entering the op
    (flax's ``promote_dtype`` semantics — bias included). Both conv impls
    (``nn.Conv``, ``MxuConv``) and the engine-side cast agree on this rule,
    so their bf16 outputs are interchangeable."""
    return jnp.result_type(x_dtype, *param_dtypes)


def cast_model_def(model_def: Any, compute_dtype) -> Any:
    """Wrap a :class:`ModelDef`'s ``apply`` to cast float params AND float
    inputs to the compute dtype on TRAIN calls only.

    Casting both sides matters: under flax's ``dtype=None`` promotion
    (``conv_compute_dtype``) a bf16 kernel against an f32 input would
    promote straight back to f32 compute. Eval (``train=False``) runs on
    the f32 master untouched, so checkpoint/early-stop selection scores the
    weights that actually ship. ``model_state`` (batch stats etc.) stays
    f32 — norm statistics in low precision drift, and the promotion rule
    simply computes those ops in f32.
    """
    compute_dtype = jnp.dtype(compute_dtype)
    inner_apply = model_def.apply

    def apply(params, model_state, x, train=True, rng=None, **kwargs):
        if train:
            params = cast_floats(params, compute_dtype)
            x = cast_floats(x, compute_dtype)
        return inner_apply(params, model_state, x, train=train, rng=rng,
                           **kwargs)

    return dataclasses.replace(model_def, apply=apply)


def wrap_logic_compute(logic: Any, compute_dtype) -> Any:
    """Shallow-copy a ClientLogic with its ``model`` apply cast-wrapped.

    The copy keeps the logic's class (so trace-time introspection like the
    ZeRO-2 ``value_and_grads``-override check still sees the real type) and
    every algorithm attribute; only the ``ModelDef`` is replaced. Logics
    that forward through something other than ``self.model`` (custom
    ensembles) simply keep computing in f32 — the policy degrades to a
    no-op there, never to wrong numerics."""
    wrapped = copy.copy(logic)
    wrapped.model = cast_model_def(logic.model, compute_dtype)
    return wrapped


# ---------------------------------------------------------------------------
# Dynamic loss scaling (in-graph; state carried in TrainState.loss_scale)
# ---------------------------------------------------------------------------

def loss_scale_init(precision: PrecisionConfig | None) -> dict | None:
    """The per-client loss-scale pytree carried in ``TrainState``:
    ``{"scale", "growth", "skipped"}``. None when the policy needs no
    scaling — the TrainState keeps its legacy structure (``None`` is an
    empty pytree node), so precision-off checkpoints/programs are
    unchanged."""
    precision = resolve(precision)
    if precision is None or not precision.scaling_active:
        return None
    return {
        "scale": jnp.asarray(precision.init_scale, jnp.float32),
        "growth": jnp.zeros((), jnp.int32),
        "skipped": jnp.zeros((), jnp.float32),
    }


def tree_all_finite(tree: Any) -> jax.Array:
    """1.0 when every floating entry of the pytree is finite, else 0.0 —
    the skip predicate of the dynamic scaler (f32 scalar so it can gate
    ``_mask_tree`` selections directly)."""
    checks = [
        jnp.all(jnp.isfinite(leaf))
        for leaf in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]
    if not checks:
        return jnp.ones((), jnp.float32)
    return jnp.stack(checks).all().astype(jnp.float32)


def loss_scale_step(ls: dict, finite: jax.Array,
                    precision: PrecisionConfig) -> dict:
    """One scaler update (torch.cuda.amp semantics, jit-traceable):
    non-finite gradients back the scale off and zero the growth streak;
    ``growth_interval`` consecutive finite steps double it (clamped to
    [min_scale, max_scale]). ``skipped`` counts skipped optimizer steps —
    the telemetry/round-event ``loss_scale_skips`` statistic. A static
    scale skips and counts identically but never moves."""
    ok = finite > 0
    skipped = ls["skipped"] + (1.0 - finite)
    if precision.resolved_loss_scale == "static":
        return {"scale": ls["scale"], "growth": ls["growth"],
                "skipped": skipped}
    grown = ls["growth"] + 1
    do_grow = grown >= precision.growth_interval
    new_scale = jnp.where(
        ok,
        jnp.where(
            do_grow,
            jnp.minimum(ls["scale"] * precision.growth_factor,
                        precision.max_scale),
            ls["scale"],
        ),
        jnp.maximum(ls["scale"] * precision.backoff_factor,
                    precision.min_scale),
    )
    new_growth = jnp.where(ok, jnp.where(do_grow, 0, grown), 0)
    return {"scale": new_scale, "growth": new_growth, "skipped": skipped}

"""Engine-level mixed-precision policy (Micikevicius et al., arXiv:1710.03740).

One :class:`~fl4health_tpu.precision.policy.PrecisionConfig` describes how
every client algorithm trains: the forward/backward runs in a low-precision
compute dtype (bf16 on the MXU, fp16 with in-graph loss scaling), gradients
come back f32 at the parameter boundary, and optimizer updates apply to f32
master weights — so the trajectory-critical state (params, optimizer
momenta, DP clip/noise, telemetry norms, compression deltas, ZeRO-1 server
shards) never leaves f32. Threaded through the cohort engine
(``clients/engine.py``) at model *apply* time, so it works for every model
and every client logic without a per-model ``dtype`` knob.
"""

from fl4health_tpu.precision.policy import (
    PrecisionConfig,
    cast_floats,
    conv_compute_dtype,
    loss_scale_init,
    loss_scale_step,
    tree_all_finite,
    wrap_logic_compute,
)

__all__ = [
    "PrecisionConfig",
    "cast_floats",
    "conv_compute_dtype",
    "loss_scale_init",
    "loss_scale_step",
    "tree_all_finite",
    "wrap_logic_compute",
]

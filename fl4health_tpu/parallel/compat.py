"""shard_map across jax versions — one call site for the API drift.

``jax.shard_map`` (with ``check_vma=``) is the modern spelling; this
jaxlib generation only ships ``jax.experimental.shard_map.shard_map``
(with ``check_rep=``). Everything mesh-mapped in this repo
(``parallel/zero.py``, ``parallel/ring_attention.py``) routes through
:func:`shard_map` below so the version probe happens exactly once.
"""

from __future__ import annotations

import jax

def axis_size(axis_name: str):
    """``jax.lax.axis_size`` where it exists; the ``psum(1, axis)`` idiom
    (statically folded to the axis size) on older jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


if hasattr(jax, "shard_map"):
    # Probe the SIGNATURE, not just the namespace: there is a jax window
    # where the top-level export exists but still takes check_rep (the
    # check_vma rename came later than the promotion out of experimental).
    import inspect

    _CHECK_KW = (
        "check_vma"
        if "check_vma" in inspect.signature(jax.shard_map).parameters
        else "check_rep"
    )

    def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW: check},
        )

else:  # jax<=0.4.x: experimental namespace, check_rep kwarg

    def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check,
        )

"""Round-program builder — mesh + sharding as a property of the compiled program.

Every compiled round program (``_fit_round[_t]``, ``_eval_round[_t]``,
``fit_chunk``, ``fit_chunk_eval`` and the servers' warm-start jits) is
constructed HERE, so placement policy lives in exactly one place:

- ``mesh=None`` (the default): :meth:`RoundProgramBuilder.jit` is a plain
  ``jax.jit(fn, donate_argnums=...)`` — byte-for-byte the pre-mesh build,
  keeping the single-chip trajectories bit-identical.
- With a :class:`MeshConfig`: the ``[C, ...]`` client-stacked axes get
  ``NamedSharding(P("clients"))`` via ``in_shardings``/``out_shardings``,
  the server state replicates (or ZeRO-1 shards its optimizer vectors over
  the replicas), and XLA inserts the broadcast/reduce collectives — one FL
  client cohort spread over data-parallel devices (ROADMAP item 1; FedJAX's
  massive-cohort regime, arXiv:2108.02117).

Axis semantics follow ``parallel/mesh.py``: "clients" is federated data
parallelism, "model" is tensor parallelism within each client slice
(``parallel/tp.py`` Megatron column/row rules, applied per-leaf when
``tp_rules=True``). Cross-replica sharding of the server optimizer update
(``zero1=True``) wires ``parallel/zero.py`` into a FedOpt-family strategy:
each replica owns 1/N of the server optimizer state and the weight update
gathers once per round (Xu et al., "Automatic Cross-Replica Sharding of
Weight Update").

Donation routes through the same CPU gating as
``simulation._donate_argnums`` (the persistent-cache aliased-executable
bug — wrong numerics when a donated executable is reloaded from a warm
``.jax_test_cache`` on XLA:CPU), so a sharded program is never MORE
donation-prone than the single-chip one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.parallel import mesh as meshlib
from fl4health_tpu.parallel import tp as tplib

CLIENTS_AXIS = "clients"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh request for :class:`FederatedSimulation`.

    ``clients``: devices along the "clients" axis (None = every available
    device after the model axis is carved out). ``model`` > 1 builds the
    hybrid ``(clients, model)`` mesh for tensor-parallel transformer
    configs. ``zero1`` shards the SERVER optimizer state (FedOpt-family
    strategies) over the clients replicas — ZeRO stage 1 applied to the
    server update. ``tp_rules`` applies ``parallel/tp.py``'s Megatron
    column/row rules per param leaf (transformer models; everything
    unmatched replicates over "model"). ``validate_zero1`` runs the
    construction-time sharded-vs-unsharded parity probe of
    ``parallel/zero.py`` against THIS mesh — the one ``fit()`` actually
    dispatches on — so validation reflects the deployed sharding.
    """

    clients: int | None = None
    model: int = 1
    zero1: bool = False
    tp_rules: bool = False
    validate_zero1: bool = True

    def __post_init__(self):
        if self.model < 1:
            raise ValueError(f"MeshConfig.model must be >= 1, got {self.model}")
        if self.clients is not None and self.clients < 1:
            raise ValueError(
                f"MeshConfig.clients must be >= 1, got {self.clients}"
            )
        if self.tp_rules and self.model < 2:
            raise ValueError(
                "MeshConfig.tp_rules needs a model axis (model >= 2): the "
                "TP rules would silently no-op on a 1-wide axis"
            )

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        n_clients_axis = self.clients or max(len(devices) // self.model, 1)
        needed = n_clients_axis * self.model
        if needed > len(devices):
            raise ValueError(
                f"MeshConfig needs {n_clients_axis}x{self.model} = {needed} "
                f"devices but only {len(devices)} are visible"
            )
        if self.model > 1:
            return meshlib.hybrid_mesh(n_clients_axis, self.model,
                                       devices=devices)
        return meshlib.client_mesh(n_clients_axis, devices=devices)


class RoundProgramBuilder:
    """Single construction point for compiled round programs.

    With ``config=None`` every helper returns ``None`` and :meth:`jit`
    degenerates to plain ``jax.jit`` + donation gating — the pre-mesh
    program, bit-identical. With a mesh, the helpers hand back the
    ``NamedSharding`` trees the round programs are jitted with.
    """

    def __init__(self, config: MeshConfig | None = None, *,
                 n_clients: int | None = None,
                 devices: Sequence[Any] | None = None):
        self.config = config
        self.mesh: Mesh | None = None
        if config is not None:
            self.mesh = config.build(devices)
            n_axis = self.client_axis_size
            if n_clients is not None and n_clients % n_axis != 0:
                raise ValueError(
                    f"n_clients={n_clients} must be divisible by the "
                    f"clients mesh axis ({n_axis} devices): XLA shards the "
                    "leading [C] axis evenly — pad the cohort or shrink the "
                    "axis (MeshConfig(clients=...))"
                )

    # -- facts -----------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size) if self.mesh is not None else 1

    @property
    def client_axis_size(self) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape[CLIENTS_AXIS])

    def descriptor(self) -> dict | None:
        """JSON-able mesh + sharding-policy descriptor (manifest /
        ``fl_program_*`` events / bench ``mesh`` block)."""
        if self.mesh is None:
            return None
        desc = meshlib.mesh_descriptor(self.mesh)
        desc["zero1"] = bool(self.config.zero1)
        desc["tp_rules"] = bool(self.config.tp_rules)
        return desc

    # -- donation gating -------------------------------------------------
    @staticmethod
    def donate(*argnums: int) -> tuple[int, ...]:
        """Buffer donation, gated OFF the CPU backend — the SAME rule as
        ``simulation._donate_argnums`` (persistent-cache mis-restore of
        aliased executables on XLA:CPU; see that docstring and the repo
        memory note). Sharded programs go through this too: in_shardings/
        out_shardings do not change the aliasing hazard."""
        return argnums if jax.default_backend() != "cpu" else ()

    # -- sharding trees --------------------------------------------------
    def named(self, spec: P) -> NamedSharding | None:
        return NamedSharding(self.mesh, spec) if self.mesh is not None else None

    def client_sharding(self) -> NamedSharding | None:
        """Leading-[C]-axis sharding for client-stacked trees (states,
        batches, masks, per-client counts)."""
        return self.named(P(CLIENTS_AXIS))

    def stacked_client_sharding(self) -> NamedSharding | None:
        """[rounds, C, ...] chunk inputs: clients on axis 1.

        The cohort chunked route's window trees ([W, ...] registry rows,
        W = min(N, R*K)) deliberately do NOT get a sharding helper: W is
        not a multiple of the device count in general, and the in-graph
        searchsorted gather/scatter against the window would resolve to
        cross-device collectives per scan step. That is why mesh + cohort
        demotes to the pipelined path (simulation._chunk_ineligibility)
        instead of running a sharded window exchange."""
        return self.named(P(None, CLIENTS_AXIS))

    def replicated(self) -> NamedSharding | None:
        return self.named(P())

    def client_state_shardings(self, template: Any) -> Any:
        """Sharding (tree) for the client-stacked ``TrainState``.

        Default: one ``P("clients")`` prefix — every leaf carries a leading
        [C] axis. With ``tp_rules`` the params/opt_state subtrees get
        per-leaf hybrid specs (``P("clients", <tp dims>)``) keyed on the
        transformer module names (``parallel/tp.py``)."""
        if self.mesh is None:
            return None
        cs = self.client_sharding()
        if not self.config.tp_rules:
            return cs
        params_t = template.params

        def place(subtree):
            # optimizer momenta etc. inherit their param's rule by
            # dotted-path SUFFIX — THE tp.py implementation, so a rule
            # change there reaches the mesh-built round programs
            specs = tplib.spec_like_params(
                subtree, params_t, axis=MODEL_AXIS, client_axis=CLIENTS_AXIS,
                default=P(CLIENTS_AXIS),
            )
            return jax.tree_util.tree_map(
                lambda _leaf, spec: self.named(spec), subtree, specs
            )

        return template.replace(
            params=place(params_t),
            opt_state=place(template.opt_state),
            model_state=cs,
            rng=cs,
            step=cs,
            extra=cs if jax.tree_util.tree_leaves(template.extra) else None,
            # fp16 scaler state: [C]-leading scalars, clients-axis like the
            # other per-client bookkeeping (None when precision is off /
            # not scaling, matching the template's empty node)
            loss_scale=(
                cs if jax.tree_util.tree_leaves(template.loss_scale)
                else None
            ),
        )

    def server_state_shardings(self, strategy: Any, template: Any) -> Any:
        """Sharding (tree) for the server state: fully replicated unless the
        strategy declares per-leaf specs via ``state_sharding_spec`` (the
        ZeRO-1 server optimizer, wrapper strategies' per-client [C]
        bookkeeping)."""
        if self.mesh is None:
            return None
        spec_tree = None
        hook = getattr(strategy, "state_sharding_spec", None)
        if hook is not None:
            spec_tree = hook(template, CLIENTS_AXIS)
        if spec_tree is None:
            return self.replicated()
        return jax.tree_util.tree_map(
            lambda s: self.named(s),
            spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    def put(self, tree: Any, sharding: Any) -> Any:
        """``device_put`` a pytree onto a sharding (tree or prefix); no-op
        without a mesh. The prefetcher uses this for per-round sharded data
        staging."""
        if self.mesh is None or sharding is None:
            return tree
        return jax.device_put(tree, sharding)

    # -- the one jit -----------------------------------------------------
    def jit(self, fn, *, donate: tuple[int, ...] = (),
            in_shardings: Any = None, out_shardings: Any = None):
        """``jax.jit`` with the builder's placement policy applied.

        Without a mesh this is EXACTLY ``jax.jit(fn, donate_argnums=
        donate-after-CPU-gating)`` — no sharding arguments are constructed
        at all, so the single-chip programs (and their persistent-cache
        keys) are unchanged. With a mesh, ``in_shardings``/``out_shardings``
        (trees of ``NamedSharding`` / ``None`` = unconstrained) pin the
        client axis split and keep the state outputs sharded — a round
        program can never silently gather the cohort onto one chip."""
        donate_argnums = self.donate(*donate)
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=donate_argnums)
        kwargs: dict[str, Any] = {"donate_argnums": donate_argnums}
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            kwargs["out_shardings"] = out_shardings
        return jax.jit(fn, **kwargs)

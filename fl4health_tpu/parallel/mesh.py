"""Device-mesh utilities — the clients axis as hardware.

Replaces the reference's process/transport runtime (Flower gRPC fan-out,
SURVEY §2.14): simulated clients are shards of a ``clients`` mesh axis; the
round's broadcast/aggregate become XLA collectives over ICI (psum-style),
cross-pod via DCN axes. On one chip the same program runs with a trivial mesh.

Axis conventions:
- "clients": federated data parallelism (one FL client per slice)
- "data":    within-client batch data parallelism
- "model":   tensor parallelism for large models (BERT/LLM configs)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.core.types import PyTree


def client_mesh(n_clients_axis: int | None = None, devices=None) -> Mesh:
    """1-D mesh over all (or n) devices, axis name 'clients'."""
    devices = devices if devices is not None else jax.devices()
    n = n_clients_axis or len(devices)
    mesh_devices = mesh_utils.create_device_mesh((n,), devices=devices[:n])
    return Mesh(mesh_devices, ("clients",))


def hybrid_mesh(n_clients_axis: int, n_model_axis: int = 1, devices=None) -> Mesh:
    """2-D (clients, model) mesh for big-model configs: client DP over ICI,
    tensor parallelism within each client slice."""
    devices = devices if devices is not None else jax.devices()
    mesh_devices = mesh_utils.create_device_mesh(
        (n_clients_axis, n_model_axis), devices=devices[: n_clients_axis * n_model_axis]
    )
    return Mesh(mesh_devices, ("clients", "model"))


def client_data_mesh(n_clients_axis: int, n_data_axis: int = 1, devices=None) -> Mesh:
    """2-D (clients, data) mesh: client DP on the outer axis, within-client
    batch data parallelism on the inner one (SURVEY §2.1 item b)."""
    devices = devices if devices is not None else jax.devices()
    mesh_devices = mesh_utils.create_device_mesh(
        (n_clients_axis, n_data_axis), devices=devices[: n_clients_axis * n_data_axis]
    )
    return Mesh(mesh_devices, ("clients", "data"))


def shard_over_clients(tree: PyTree, mesh: Mesh) -> PyTree:
    """Place a client-stacked pytree with its leading axis split over the
    'clients' mesh axis (the SPMD 'wire')."""
    sharding = NamedSharding(mesh, P("clients"))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def replicate(tree: PyTree, mesh: Mesh) -> PyTree:
    """Fully replicate (server-side state: global params, strategy state)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def client_axis_size(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in ("clients",) if a in mesh.shape]))


def mesh_descriptor(mesh: Mesh | None) -> dict | None:
    """JSON-able description of a mesh — axis names/sizes plus the device
    kinds backing it. This is what the observability run manifest records
    so a scraped metrics page can be matched to its hardware topology."""
    if mesh is None:
        return None
    kinds = sorted({
        getattr(d, "device_kind", "unknown") for d in mesh.devices.flat
    })
    return {
        "axes": {name: int(size) for name, size in mesh.shape.items()},
        "n_devices": int(mesh.devices.size),
        "device_kinds": kinds,
    }

"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO long-context machinery (SURVEY §5 "Long-context /
sequence parallelism: absent"): its BERT/LLM examples run standard attention
and delegate scale to DeepSpeed configs. The task brief makes long-context a
first-class TPU concern, so this module provides the canonical TPU recipe:
blockwise ring attention (Liu et al., "Ring Attention with Blockwise
Transformers") — the sequence axis is sharded over a ``seq`` mesh axis; each
device holds one query block and streams key/value blocks around the ring
with ``lax.ppermute`` over ICI, maintaining an online-softmax accumulator
(flash-attention state: running max, normalizer, weighted sum). Peak memory
per device is O(T/N * T/N) attention scores instead of O(T^2); the K/V
transfers overlap the block matmuls on real hardware.

Semantics: exact (not approximate) softmax attention — the ring test asserts
bitwise-level agreement (atol 1e-5) with dense attention on a virtual mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.parallel.compat import axis_size, shard_map

NEG_INF = -1e30


def _dense_attention(q, k, v, pad_mask=None):
    """Reference dense softmax attention. q,k,v: [B, T, H, D];
    pad_mask: [B, T] with 1 = real token. Used for tests and as the
    single-device fallback."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if pad_mask is not None:
        scores = jnp.where(pad_mask[:, None, None, :] > 0, scores, NEG_INF)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _dense_local_lse(q_blk, k_blk, v_blk, mask_blk):
    """Dense local block returning (out, lse) — the partial-attention pair
    the ring driver merges. lse for an all-masked row is ~NEG_INF (large
    FINITE negative, mirroring the flash kernel's contract) so the merge
    algebra never sees inf-inf."""
    d = q_blk.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q_blk.astype(jnp.float32),
                   k_blk.astype(jnp.float32)) * scale
    )
    scores = jnp.where(mask_blk[:, None, None, :] > 0, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(mask_blk[:, None, None, :] > 0, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    denom = jnp.maximum(l, 1e-20)
    lse = m + jnp.log(denom)
    # stay fp32: the ring driver accumulates in fp32 and casts ONCE at the
    # end, so the DENSE ring adds no per-hop quantization. (The flash local
    # block is different: its kernel writes each hop's output in the io
    # dtype — inherent to its memory layout — so bf16 ring-flash carries
    # one io-dtype rounding per hop into the fp32 merge.)
    return o / denom[..., None].transpose(0, 2, 1, 3), lse


def _ring_body(q_blk, k_blk, v_blk, mask_blk, local_fn, axis_name: str):
    """Shared ring driver (shard_map body): the local [B, Tq, H, D] query
    block attends over all key blocks as they rotate around the ring via
    ``ppermute``. ``local_fn(q, k, v, mask) -> (out, lse)`` computes one
    block's exact partial attention; hops merge through the logsumexp
    identity (running max M, normalizer S, weighted numerator ACC — the
    online-softmax algebra one level up), so the driver is the ONE copy of
    the rotation/merge logic for both the dense and the flash local block.
    """
    ring = axis_size(axis_name)
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    # local block first, then n-1 hops: rotate-THEN-compute so no transfer's
    # result is ever discarded (n hops would waste 3 collectives per call).
    o0, lse0 = local_fn(q_blk, k_blk, v_blk, mask_blk)
    m0 = lse0  # [B, H, Tq]
    s0 = jnp.ones_like(lse0)
    acc0 = o0.astype(jnp.float32)

    def hop(_, carry):
        acc, m, s, k_cur, v_cur, mask_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_cur = jax.lax.ppermute(mask_cur, axis_name, perm)
        o_j, lse_j = local_fn(q_blk, k_cur, v_cur, mask_cur)
        m_new = jnp.maximum(m, lse_j)
        c = jnp.exp(m - m_new)      # rescale old accumulators
        w = jnp.exp(lse_j - m_new)  # weight of this hop
        s = s * c + w
        cw = jnp.transpose(c, (0, 2, 1))[..., None]
        ww = jnp.transpose(w, (0, 2, 1))[..., None]
        acc = acc * cw + ww * o_j.astype(jnp.float32)
        return acc, m_new, s, k_cur, v_cur, mask_cur

    acc, m, s, _, _, _ = jax.lax.fori_loop(
        0, ring - 1, hop, (acc0, m0, s0, k_blk, v_blk, mask_blk)
    )
    denom = jnp.maximum(jnp.transpose(s, (0, 2, 1))[..., None], 1e-20)
    return (acc / denom).astype(q_blk.dtype)


def _ring_shard_map(local_fn, mesh, axis_name, q, k, v, pad_mask):
    qkv_spec = P(None, axis_name, None, None)
    mask_spec = P(None, axis_name)
    fn = shard_map(
        functools.partial(_ring_body, local_fn=local_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check=False,
    )
    return fn(q, k, v, pad_mask)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "seq",
    pad_mask: jax.Array | None = None,
) -> jax.Array:
    """Exact softmax attention with the sequence axis sharded over
    ``axis_name``. q,k,v: [B, T, H, D] global arrays (T divisible by the axis
    size); pad_mask: [B, T] (1 = token). Returns [B, T, H, D] sharded the
    same way.
    """
    if pad_mask is None:
        pad_mask = jnp.ones(q.shape[:2], jnp.float32)
    return _ring_shard_map(_dense_local_lse, mesh, axis_name, q, k, v,
                           pad_mask)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "seq",
    pad_mask: jax.Array | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Ring attention whose LOCAL block is the Pallas flash kernel — the
    full long-context recipe: the sequence axis shards over ``axis_name``
    (ring hops via ``ppermute``), and within each hop the [Tq/N, Tk/N]
    block runs through ``kernels.flash_attention_lse`` so the block score
    matrix never touches HBM either. Per-hop partials merge exactly via the
    logsumexp statistic: ``L = max_j lse_j`` running-max, weights
    ``exp(lse_j - L)`` — the same online-softmax algebra as the dense ring,
    one level up. Differentiable end-to-end (lse carries a first-class
    cotangent through the kernel's custom VJP).

    Same contract as ring_self_attention; additionally the local length T/N
    must be divisible by usable block sizes: each block shrinks to
    gcd(T/N, block) and a degenerate shrink (below 8 on a real-sized
    shard) raises rather than compiling a pathological Mosaic tile.
    """
    import math as _math

    from fl4health_tpu.kernels.flash_attention import flash_attention_lse

    if pad_mask is None:
        pad_mask = jnp.ones(q.shape[:2], jnp.float32)
    n = mesh.shape[axis_name]
    t_local = q.shape[1] // n
    # Each block shrinks independently to a divisor of the local length
    # (lcm of two divisors of t_local still divides it). A degenerate
    # shrink (< 8 on a real-sized shard) is an error, not a silent
    # pathological Mosaic tile — pick T and block sizes that agree.
    bq, bk = _math.gcd(t_local, block_q), _math.gcd(t_local, block_k)
    if min(bq, bk) < 8 and t_local >= 8:
        raise ValueError(
            f"ring_flash_attention: local length {t_local} is incompatible "
            f"with block sizes ({block_q}, {block_k}) — the divisor shrink "
            f"degenerates to ({bq}, {bk}); choose T/N divisible by the "
            "block sizes"
        )

    def local(q_blk, k_cur, v_cur, mask_cur):
        return flash_attention_lse(
            q_blk, k_cur, v_cur, mask_cur,
            block_q=bq, block_k=bk, interpret=interpret,
        )

    return _ring_shard_map(local, mesh, axis_name, q, k, v, pad_mask)


def sequence_parallel_sharding(mesh: Mesh, axis_name: str = "seq"):
    """NamedSharding placing [B, T, ...] activations with T over the seq
    axis — the placement companion for feeding ring attention."""
    return NamedSharding(mesh, P(None, axis_name))

"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO long-context machinery (SURVEY §5 "Long-context /
sequence parallelism: absent"): its BERT/LLM examples run standard attention
and delegate scale to DeepSpeed configs. The task brief makes long-context a
first-class TPU concern, so this module provides the canonical TPU recipe:
blockwise ring attention (Liu et al., "Ring Attention with Blockwise
Transformers") — the sequence axis is sharded over a ``seq`` mesh axis; each
device holds one query block and streams key/value blocks around the ring
with ``lax.ppermute`` over ICI, maintaining an online-softmax accumulator
(flash-attention state: running max, normalizer, weighted sum). Peak memory
per device is O(T/N * T/N) attention scores instead of O(T^2); the K/V
transfers overlap the block matmuls on real hardware.

Semantics: exact (not approximate) softmax attention — the ring test asserts
bitwise-level agreement (atol 1e-5) with dense attention on a virtual mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _dense_attention(q, k, v, pad_mask=None):
    """Reference dense softmax attention. q,k,v: [B, T, H, D];
    pad_mask: [B, T] with 1 = real token. Used for tests and as the
    single-device fallback."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if pad_mask is not None:
        scores = jnp.where(pad_mask[:, None, None, :] > 0, scores, NEG_INF)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_block(q_blk, k_blk, v_blk, mask_blk, axis_name: str):
    """shard_map body: local [B, Tq, H, D] query block attends over all key
    blocks as they rotate around the ring."""
    n = jax.lax.axis_size(axis_name)
    b, tq, h, d = q_blk.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    # online-softmax accumulators (fp32 for stability regardless of io dtype)
    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(o, m, l, k_cur, v_cur, mask_cur):
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q_blk.astype(jnp.float32),
                       k_cur.astype(jnp.float32)) * scale
        )
        scores = jnp.where(mask_cur[:, None, None, :] > 0, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # guard: a block of all-padding keys keeps m at NEG_INF; exp(0)=1
        # terms would pollute l, so compute p against the updated max.
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask_cur[:, None, None, :] > 0, p, 0.0)
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        o = (
            o * jnp.transpose(correction, (0, 2, 1))[..., None]
            + jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        )
        return o, m_new, l

    # local block first, then n-1 hops: rotate-THEN-compute so no transfer's
    # result is ever discarded (n hops would waste 3 collectives per call).
    o, m, l = accumulate(o0, m0, l0, k_blk, v_blk, mask_blk)

    def body(_, carry):
        o, m, l, k_cur, v_cur, mask_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_cur = jax.lax.ppermute(mask_cur, axis_name, perm)
        o, m, l = accumulate(o, m, l, k_cur, v_cur, mask_cur)
        return o, m, l, k_cur, v_cur, mask_cur

    o, m, l, _, _, _ = jax.lax.fori_loop(
        0, n - 1, body, (o, m, l, k_blk, v_blk, mask_blk)
    )
    denom = jnp.maximum(jnp.transpose(l, (0, 2, 1))[..., None], 1e-20)
    return (o / denom).astype(q_blk.dtype)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "seq",
    pad_mask: jax.Array | None = None,
) -> jax.Array:
    """Exact softmax attention with the sequence axis sharded over
    ``axis_name``. q,k,v: [B, T, H, D] global arrays (T divisible by the axis
    size); pad_mask: [B, T] (1 = token). Returns [B, T, H, D] sharded the
    same way.
    """
    if pad_mask is None:
        pad_mask = jnp.ones(q.shape[:2], jnp.float32)
    qkv_spec = P(None, axis_name, None, None)
    mask_spec = P(None, axis_name)
    fn = jax.shard_map(
        functools.partial(_ring_block, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, pad_mask)


def sequence_parallel_sharding(mesh: Mesh, axis_name: str = "seq"):
    """NamedSharding placing [B, T, ...] activations with T over the seq
    axis — the placement companion for feeding ring attention."""
    return NamedSharding(mesh, P(None, axis_name))

"""ZeRO-style sharded optimizer state — shard_map over a mesh axis.

The reference delegates optimizer-state sharding to DeepSpeed ZeRO configs in
the FedLLM example (/root/reference/examples/fedllm_example README's
zero2/zero3 JSONs; SURVEY §2.1 item d names the TPU equivalent a first-class
component). TPU-native design: ZeRO-1 as a wrapper around ANY optax
transformation — the flat parameter vector is partitioned over a mesh axis;
each device holds and updates only its 1/N slice of optimizer state (momenta
etc.); the updates come back as one logically-full (sharded) vector that
optax.apply_updates consumes, XLA inserting the all-gather where the
consumer needs it. This is exactly the memory split of ZeRO stage 1: O(P/N)
optimizer state per device at the cost of one gather per step over ICI.

SCOPE: the wrapped transform must be ELEMENTWISE over the flat parameter
vector (sgd, momentum, adam/adamw, rmsprop, ...). Transforms that reduce
across ALL parameters — clip_by_global_norm, lamb/lars trust ratios,
adafactor row/col stats — would compute shard-local statistics inside
shard_map and silently diverge from the unsharded optimizer. Apply such
transforms OUTSIDE the wrapper (their state is O(1), there is nothing to
shard) and wrap only the elementwise tail. This contract is CHECKED at
construction: the factory runs one-step sharded-vs-unsharded parity probes
at two gradient magnitudes on the params template and raises on divergence
(``validate=False`` skips). The probe is a strong guard, not a proof — a
coupling active only at untested scales can slip through; the elementwise
rule remains the contract.

ZeRO-2 (``Zero2ShardedOptimizer``): additionally shards the gradient
REDUCTION. ``update`` takes per-device UNREDUCED gradient trees (leading
[n_shards] axis); inside shard_map the sum happens as a ``psum_scatter`` so
each device only ever materializes its 1/N slice of the summed gradient —
the ZeRO stage-2 memory split (grads O(P/N) + optimizer state O(P/N)) — and
the updated slices return through one tiled ``all_gather``. Role of the
reference's DeepSpeed zero2 config in the fedllm example.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.parallel.compat import shard_map


@dataclasses.dataclass(frozen=True)
class ZeroShardedOptimizer:
    """optax-compatible (init/update) with state sharded over ``axis_name``.

    Built from a ``params_template`` so the flat<->tree transforms are static
    (shard_map needs static specs). Use ``state_sharding(state)`` to inspect
    placement in tests.
    """

    tx: optax.GradientTransformation
    mesh: Mesh
    axis_name: str = "model"
    params_template: Params | None = None

    def _flat_size(self) -> tuple[int, int]:
        flat, _ = ptu.ravel(self.params_template)
        n_shards = self.mesh.shape[self.axis_name]
        padded = -(-flat.shape[0] // n_shards) * n_shards
        return flat.shape[0], padded

    # -- optax surface ------------------------------------------------------
    def init(self, params: Params) -> Any:
        size, padded = self._flat_size()
        flat, _ = ptu.ravel(params)
        flat = jnp.concatenate([flat, jnp.zeros((padded - size,), flat.dtype)])
        state = self.tx.init(flat)
        # Shard every vector-shaped state leaf; scalars (counts) replicate.
        shard = NamedSharding(self.mesh, P(self.axis_name))
        rep = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf, shard if getattr(leaf, "ndim", 0) >= 1 else rep
            ),
            state,
        )

    def update(self, grads: Params, opt_state: Any, params: Params | None = None):
        size, padded = self._flat_size()
        pad = padded - size
        flat_g, unravel = ptu.ravel(grads)
        flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), flat_g.dtype)])
        if params is not None:
            flat_p, _ = ptu.ravel(params)
            flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        else:
            flat_p = None

        vec_spec = P(self.axis_name)
        state_specs = jax.tree_util.tree_map(
            lambda leaf: vec_spec if getattr(leaf, "ndim", 0) >= 1 else P(),
            opt_state,
        )

        def shard_update(g, state, p):
            return self.tx.update(g, state, p)

        updates_flat, new_state = shard_map(
            shard_update,
            mesh=self.mesh,
            in_specs=(vec_spec, state_specs, vec_spec if flat_p is not None else None),
            out_specs=(vec_spec, state_specs),
            check=False,
        )(flat_g, opt_state, flat_p)
        return unravel(updates_flat[:size]), new_state

    # -- introspection ------------------------------------------------------
    def state_bytes_per_device(self, opt_state: Any) -> int:
        """Bytes of optimizer state resident per device (the ZeRO win)."""
        n = self.mesh.shape[self.axis_name]
        total = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(opt_state)
            if getattr(leaf, "ndim", 0) >= 1
        )
        return total // n


@dataclasses.dataclass(frozen=True)
class Zero2ShardedOptimizer:
    """ZeRO-2: sharded gradient reduction + sharded optimizer state.

    ``update(local_grads, opt_state, params)`` takes a grads pytree whose
    leaves carry a leading [n_shards] axis — one UNREDUCED gradient per mesh
    slot (e.g. per-microbatch or per-client grads destined for averaging).
    The reduction runs as ``psum_scatter`` inside shard_map, so the full
    summed gradient vector is never materialized on any device.

    ``reduce="mean"`` divides by n_shards (the data-parallel convention);
    ``"sum"`` leaves the psum as-is.
    """

    tx: optax.GradientTransformation
    mesh: Mesh
    axis_name: str = "model"
    params_template: Params | None = None
    reduce: str = "mean"

    # Engine handshake (clients/engine.py make_train_step): optimizers that
    # set this receive a [n_shards]-leading stack of UNREDUCED gradient
    # trees instead of one reduced tree — the engine computes per-microbatch
    # grads and lets the psum_scatter below do the reduction.
    expects_unreduced_grads = True

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.axis_name]

    def _flat_size(self) -> tuple[int, int]:
        flat, _ = ptu.ravel(self.params_template)
        n_shards = self.mesh.shape[self.axis_name]
        padded = -(-flat.shape[0] // n_shards) * n_shards
        return flat.shape[0], padded

    def init(self, params: Params) -> Any:
        # Same state layout as ZeRO-1: each device owns 1/N of every vector
        # leaf (ZeRO-2 differs in how gradients ARRIVE, not in what is kept).
        return ZeroShardedOptimizer(
            self.tx, self.mesh, self.axis_name, self.params_template
        ).init(params)

    def update(self, local_grads: Params, opt_state: Any,
               params: Params | None = None):
        size, padded = self._flat_size()
        pad = padded - size
        n_shards = self.mesh.shape[self.axis_name]

        # [n_shards, padded] stack of flat local grads.
        def flatten_one(i):
            g_i = jax.tree_util.tree_map(lambda x: x[i], local_grads)
            flat, _ = ptu.ravel(g_i)
            return jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

        flat_stack = jnp.stack([flatten_one(i) for i in range(n_shards)])
        _, unravel = ptu.ravel(self.params_template)
        if params is not None:
            flat_p, _ = ptu.ravel(params)
            flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        else:
            flat_p = None

        vec_spec = P(self.axis_name)
        stack_spec = P(self.axis_name, None)
        state_specs = jax.tree_util.tree_map(
            lambda leaf: vec_spec if getattr(leaf, "ndim", 0) >= 1 else P(),
            opt_state,
        )
        scale = 1.0 / n_shards if self.reduce == "mean" else 1.0

        def shard_update(g_local, state, p):
            # g_local: [1, padded] — this device's unreduced gradient.
            # psum_scatter sums across devices AND hands each device only its
            # 1/N slice of the result: the full summed vector never exists.
            g_shard = jax.lax.psum_scatter(
                g_local[0], self.axis_name, scatter_dimension=0, tiled=True
            ) * scale
            upd_shard, new_state = self.tx.update(g_shard, state, p)
            upd_full = jax.lax.all_gather(
                upd_shard, self.axis_name, tiled=True
            )
            return upd_full, new_state

        updates_flat, new_state = shard_map(
            shard_update,
            mesh=self.mesh,
            in_specs=(stack_spec, state_specs,
                      vec_spec if flat_p is not None else None),
            out_specs=(P(), state_specs),
            check=False,
        )(flat_stack, opt_state, flat_p)
        return unravel(updates_flat[:size]), new_state

    def grad_bytes_per_device(self) -> int:
        """Bytes of summed gradient resident per device during the update —
        the stage-2 claim: 1/N of the full vector."""
        size, padded = self._flat_size()
        flat, _ = ptu.ravel(self.params_template)
        return (padded // self.mesh.shape[self.axis_name]) * flat.dtype.itemsize

    def state_bytes_per_device(self, opt_state: Any) -> int:
        n = self.mesh.shape[self.axis_name]
        total = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(opt_state)
            if getattr(leaf, "ndim", 0) >= 1
        )
        return total // n


def _probe_grads(params_template: Params, scale: float):
    """Deterministic, value-varied probe gradients: catches transforms whose
    update depends on cross-parameter statistics (norms, trust ratios) that
    a shard-local computation would get wrong."""
    flat, unravel = ptu.ravel(params_template)
    g = jnp.sin(jnp.arange(flat.shape[0], dtype=flat.dtype) * 0.37) * scale
    return unravel(g), flat, g


def _validate_elementwise(wrapper, tx, params_template, n_local=None):
    """One-step sharded-vs-unsharded parity probe. Raises ValueError when the
    wrapped transform is not elementwise over the flat vector (e.g.
    clip_by_global_norm, adafactor).

    Probes run at a SMALL and a LARGE gradient magnitude: cross-parameter
    couplings are often conditional (a clip threshold binds only above it, a
    trust ratio saturates below it), and a single-scale probe would certify a
    transform whose coupling simply wasn't active at that scale. Two scales
    are a strong heuristic, not an exhaustive proof — a transform whose
    reduction activates only in some exotic band can still slip through, so
    the SCOPE rule remains the contract."""
    for scale in (1e-2, 1e3):
        gtree, flat_p, flat_g = _probe_grads(params_template, scale)
        ref_state = tx.init(flat_p)
        ref_upd, _ = tx.update(flat_g, ref_state, flat_p)

        sharded_state = wrapper.init(params_template)
        if n_local is None:
            upd_tree, _ = wrapper.update(gtree, sharded_state, params_template)
        else:
            # ZeRO-2 consumes per-device unreduced grads. n identical copies
            # of g reduce to g under "mean"; n copies of g/n reduce to g
            # under "sum" — either way the effective gradient matches the
            # unsharded reference.
            div = 1.0 if wrapper.reduce == "mean" else float(n_local)
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.stack([x / div] * n_local), gtree
            )
            upd_tree, _ = wrapper.update(stacked, sharded_state,
                                         params_template)
        got, _ = ptu.ravel(upd_tree)
        # Tolerance scales with the update magnitude: a fixed atol would
        # swallow small-update divergences (e.g. a tightly-clipped gradient,
        # exactly the class of transform the probe exists to catch).
        atol = 1e-5 * float(jnp.max(jnp.abs(ref_upd))) + 1e-30
        if not bool(jnp.allclose(got, ref_upd, rtol=1e-4, atol=atol)):
            err = float(jnp.max(jnp.abs(got - ref_upd)))
            raise ValueError(
                "ZeRO parity probe failed at gradient scale "
                f"{scale:g} (max |Δupdate| = "
                f"{err:.3e}): the wrapped transform is not elementwise over "
                "the flat parameter vector (global-norm clipping, trust "
                "ratios and adafactor-style factored stats reduce ACROSS "
                "parameters and diverge silently when sharded). Apply such "
                "transforms outside the wrapper and wrap only the "
                "elementwise tail, or pass validate=False if you know "
                "better."
            )


def zero_sharded_optimizer(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    params_template: Params,
    axis_name: str = "model",
    validate: bool = True,
) -> ZeroShardedOptimizer:
    opt = ZeroShardedOptimizer(
        tx=tx, mesh=mesh, axis_name=axis_name, params_template=params_template
    )
    if validate:
        _validate_elementwise(opt, tx, params_template)
    return opt


def zero2_sharded_optimizer(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    params_template: Params,
    axis_name: str = "model",
    reduce: str = "mean",
    validate: bool = True,
) -> Zero2ShardedOptimizer:
    if reduce not in ("mean", "sum"):
        raise ValueError(f"reduce must be 'mean' or 'sum', got {reduce!r}")
    opt = Zero2ShardedOptimizer(
        tx=tx, mesh=mesh, axis_name=axis_name,
        params_template=params_template, reduce=reduce,
    )
    if validate:
        _validate_elementwise(
            opt, tx, params_template, n_local=mesh.shape[axis_name]
        )
    return opt

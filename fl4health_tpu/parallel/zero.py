"""ZeRO-style sharded optimizer state — shard_map over a mesh axis.

The reference delegates optimizer-state sharding to DeepSpeed ZeRO configs in
the FedLLM example (/root/reference/examples/fedllm_example README's
zero2/zero3 JSONs; SURVEY §2.1 item d names the TPU equivalent a first-class
component). TPU-native design: ZeRO-1 as a wrapper around ANY optax
transformation — the flat parameter vector is partitioned over a mesh axis;
each device holds and updates only its 1/N slice of optimizer state (momenta
etc.); the updates come back as one logically-full (sharded) vector that
optax.apply_updates consumes, XLA inserting the all-gather where the
consumer needs it. This is exactly the memory split of ZeRO stage 1: O(P/N)
optimizer state per device at the cost of one gather per step over ICI.

SCOPE: the wrapped transform must be ELEMENTWISE over the flat parameter
vector (sgd, momentum, adam/adamw, rmsprop, ...). Transforms that reduce
across ALL parameters — clip_by_global_norm, lamb/lars trust ratios,
adafactor row/col stats — would compute shard-local statistics inside
shard_map and silently diverge from the unsharded optimizer. Apply such
transforms OUTSIDE the wrapper (their state is O(1), there is nothing to
shard) and wrap only the elementwise tail.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params


@dataclasses.dataclass(frozen=True)
class ZeroShardedOptimizer:
    """optax-compatible (init/update) with state sharded over ``axis_name``.

    Built from a ``params_template`` so the flat<->tree transforms are static
    (shard_map needs static specs). Use ``state_sharding(state)`` to inspect
    placement in tests.
    """

    tx: optax.GradientTransformation
    mesh: Mesh
    axis_name: str = "model"
    params_template: Params | None = None

    def _flat_size(self) -> tuple[int, int]:
        flat, _ = ptu.ravel(self.params_template)
        n_shards = self.mesh.shape[self.axis_name]
        padded = -(-flat.shape[0] // n_shards) * n_shards
        return flat.shape[0], padded

    # -- optax surface ------------------------------------------------------
    def init(self, params: Params) -> Any:
        size, padded = self._flat_size()
        flat, _ = ptu.ravel(params)
        flat = jnp.concatenate([flat, jnp.zeros((padded - size,), flat.dtype)])
        state = self.tx.init(flat)
        # Shard every vector-shaped state leaf; scalars (counts) replicate.
        shard = NamedSharding(self.mesh, P(self.axis_name))
        rep = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf, shard if getattr(leaf, "ndim", 0) >= 1 else rep
            ),
            state,
        )

    def update(self, grads: Params, opt_state: Any, params: Params | None = None):
        size, padded = self._flat_size()
        pad = padded - size
        flat_g, unravel = ptu.ravel(grads)
        flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), flat_g.dtype)])
        if params is not None:
            flat_p, _ = ptu.ravel(params)
            flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        else:
            flat_p = None

        vec_spec = P(self.axis_name)
        state_specs = jax.tree_util.tree_map(
            lambda leaf: vec_spec if getattr(leaf, "ndim", 0) >= 1 else P(),
            opt_state,
        )

        def shard_update(g, state, p):
            return self.tx.update(g, state, p)

        updates_flat, new_state = jax.shard_map(
            shard_update,
            mesh=self.mesh,
            in_specs=(vec_spec, state_specs, vec_spec if flat_p is not None else None),
            out_specs=(vec_spec, state_specs),
            check_vma=False,
        )(flat_g, opt_state, flat_p)
        return unravel(updates_flat[:size]), new_state

    # -- introspection ------------------------------------------------------
    def state_bytes_per_device(self, opt_state: Any) -> int:
        """Bytes of optimizer state resident per device (the ZeRO win)."""
        n = self.mesh.shape[self.axis_name]
        total = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(opt_state)
            if getattr(leaf, "ndim", 0) >= 1
        )
        return total // n


def zero_sharded_optimizer(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    params_template: Params,
    axis_name: str = "model",
) -> ZeroShardedOptimizer:
    return ZeroShardedOptimizer(
        tx=tx, mesh=mesh, axis_name=axis_name, params_template=params_template
    )

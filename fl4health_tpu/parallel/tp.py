"""Tensor-parallel sharding rules for transformer params (hybrid mesh).

The reference's only big-model scaling is DeepSpeed ZeRO config JSON in the
FedLLM example (/root/reference/examples/fedllm_example, SURVEY §2.1) — no
in-repo tensor parallelism. For the TPU build, TP is a first-class axis:
``hybrid_mesh(clients, model)`` (parallel/mesh.py:32) splits every client's
transformer across the "model" axis with the standard Megatron pairing —
column-parallel into the nonlinearity, row-parallel out of it — so each
attention/MLP block needs exactly one psum on its output, which XLA inserts
from the shardings.

These are RULES (path -> PartitionSpec), not a parallel module zoo: the same
flax model runs unsharded on one chip or TP-sharded on a mesh purely by
changing the placement of its pytree (models/transformer.py names its
projections to be keyed on here).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fl4health_tpu.core.types import PyTree

# Column-parallel: output features sharded (kernel [in, out] -> P(None, ax)).
COLUMN_PARALLEL = ("q_proj", "k_proj", "v_proj", "ff_in")
# Row-parallel: input features sharded (kernel [in, out] -> P(ax, None)).
ROW_PARALLEL = ("o_proj", "ff_out")


def tp_spec(path: str, ndim: int, axis: str = "model") -> P:
    """PartitionSpec for one transformer param leaf (unstacked shape)."""
    segs = path.split(".")
    module = segs[-2] if len(segs) >= 2 else ""
    leaf = segs[-1]
    if module in COLUMN_PARALLEL:
        if leaf in ("kernel", "lora_b") and ndim == 2:
            return P(None, axis)
        if leaf == "bias" and ndim == 1:
            return P(axis)
        # lora_a of a column-parallel layer stays replicated (it's rank-r).
        return P(*([None] * ndim))
    if module in ROW_PARALLEL:
        if leaf in ("kernel", "lora_a") and ndim == 2:
            return P(axis, None)
        # row-parallel bias adds after the psum -> replicated.
        return P(*([None] * ndim))
    # Embeddings, layer norms, classifier head: replicated over "model".
    return P(*([None] * ndim))


def shard_transformer_params(
    params: PyTree,
    mesh: Mesh,
    axis: str = "model",
    client_axis: str | None = None,
) -> PyTree:
    """Place a transformer param pytree by the TP rules. With ``client_axis``
    set, leaves are client-stacked ([clients, ...]) and the leading dim is
    sharded over that axis — the hybrid (clients x model) layout."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for key_path, leaf in flat:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        if client_axis is not None:
            spec = tp_spec(dotted, leaf.ndim - 1, axis)
            spec = P(client_axis, *spec)
        else:
            spec = tp_spec(dotted, leaf.ndim, axis)
        placed.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, placed)


def spec_like_params(tree: PyTree, params_template: PyTree,
                     axis: str = "model", client_axis: str | None = None,
                     default: P = P()) -> PyTree:
    """``PartitionSpec`` pytree for a tree holding params-shaped sub-trees
    (optimizer momenta, drift anchors) under the TP rules — THE one
    implementation of the inheritance rule, used by both the device_put
    placer below and the round-program builder's in/out shardings
    (``parallel/program.py``).

    Leaves are matched to template params by dotted-path SUFFIX — an adam
    ``mu`` leaf at ``0.mu.layer_0.attn.o_proj.kernel`` inherits the rule of
    ``layer_0.attn.o_proj.kernel``. Path matching (not shape matching) keeps
    same-shaped leaves with different rules distinct (q/k/v vs o_proj are all
    [d, d] but shard on opposite axes). Unmatched leaves (step counts, EMA
    scalars) get ``default`` (replicate, unless the caller's tree is
    client-stacked and needs ``P(client_axis)``).
    """
    flat_t, _ = jax.tree_util.tree_flatten_with_path(params_template)
    param_specs: list[tuple[str, Any, P]] = []
    for key_path, leaf in flat_t:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        if client_axis is not None:
            spec = P(client_axis, *tp_spec(dotted, leaf.ndim - 1, axis))
        else:
            spec = tp_spec(dotted, leaf.ndim, axis)
        param_specs.append((dotted, leaf.shape, spec))

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for key_path, leaf in flat:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        spec = default
        for ppath, pshape, pspec in param_specs:
            if (dotted == ppath or dotted.endswith("." + ppath)) and (
                getattr(leaf, "shape", ()) == pshape
            ):
                spec = pspec
                break
        specs.append(spec)
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_like_params(tree: PyTree, params_template: PyTree, mesh: Mesh,
                      axis: str = "model", client_axis: str | None = None) -> PyTree:
    """``device_put`` a params-shaped tree by :func:`spec_like_params`'s
    TP-inheritance rule (see its docstring for the matching semantics)."""
    specs = spec_like_params(tree, params_template,
                             axis=axis, client_axis=client_axis)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tree, specs,
    )

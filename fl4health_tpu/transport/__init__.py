"""Cross-silo transport: wire codec (native C++ framing + JSON/array
payloads), COO boundary for sparse packets, and a host RPC loopback.

See codec.py for the wire contract and SURVEY §2.14 for the role split:
in-process simulation rides the device mesh (XLA collectives); this package
is the host-level seam for deployments that cannot share a mesh.
"""

from fl4health_tpu.transport.codec import (
    decode,
    decode_sparse,
    encode,
    encode_sparse,
)
from fl4health_tpu.transport.coordinator import (
    AsyncReply,
    BroadcastReport,
    QuorumError,
    SiloResult,
    SiloUpdateBuffer,
    broadcast_round,
    broadcast_round_detailed,
    weighted_merge,
)
from fl4health_tpu.transport.loopback import LoopbackServer, call
from fl4health_tpu.transport.native import FrameError, get_framing

__all__ = [
    "encode", "decode", "encode_sparse", "decode_sparse",
    "LoopbackServer", "call", "FrameError", "get_framing",
    "broadcast_round", "broadcast_round_detailed", "weighted_merge",
    "BroadcastReport", "QuorumError", "SiloResult",
    "SiloUpdateBuffer", "AsyncReply",
]

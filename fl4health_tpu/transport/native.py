"""Loader for the native framing codec (_codec.cpp) with a pure-Python twin.

The shared object is compiled on first use with the system C++ toolchain and
cached next to the source; environments without a compiler (or with
FL4HEALTH_NO_NATIVE=1) run the ``PyFraming`` fallback — identical wire
format, zlib's C crc32, ~same speed for small frames, slower for giant ones.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
import zlib
from pathlib import Path

logger = logging.getLogger(__name__)

_MAGIC = 0x464C3448
_VERSION = 1
_FIXED = struct.Struct("<IHHIQ")  # magic, version, flags, header_len, payload_len

_lock = threading.Lock()
_native = None
_native_tried = False


def _compile_native() -> ctypes.CDLL | None:
    src = Path(__file__).with_name("_codec.cpp")
    so = Path(__file__).with_name("_codec.so")
    if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", str(so), str(src)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            logger.info("native codec build failed (%s); using Python framing", e)
            return None
    try:
        lib = ctypes.CDLL(str(so))
    except OSError as e:
        logger.info("native codec load failed (%s); using Python framing", e)
        return None
    lib.fl4h_crc32.restype = ctypes.c_uint32
    lib.fl4h_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.fl4h_frame_size.restype = ctypes.c_int64
    lib.fl4h_frame_size.argtypes = [ctypes.c_uint32, ctypes.c_uint64]
    lib.fl4h_frame.restype = ctypes.c_int64
    lib.fl4h_frame.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_uint16, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.fl4h_unframe.restype = ctypes.c_int64
    lib.fl4h_unframe.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint16),
    ]
    # nibble helpers are newer than the framing ABI: a stale cached .so
    # (rebuilt lazily off mtime) may not export them — fall back per-symbol
    try:
        lib.fl4h_pack_nibbles.restype = ctypes.c_int64
        lib.fl4h_pack_nibbles.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.fl4h_unpack_nibbles.restype = ctypes.c_int64
        lib.fl4h_unpack_nibbles.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64,
        ]
    except AttributeError:
        logger.info("native codec lacks nibble helpers; int4 packing uses "
                    "the NumPy fallback")
    return lib


def get_native() -> ctypes.CDLL | None:
    global _native, _native_tried
    if os.environ.get("FL4HEALTH_NO_NATIVE"):
        return None
    with _lock:
        if not _native_tried:
            _native = _compile_native()
            _native_tried = True
        return _native


class FrameError(ValueError):
    pass


_ERRORS = {-1: "short frame", -2: "bad magic", -3: "bad version", -4: "bad crc"}


class NativeFraming:
    """ctypes bridge over _codec.so."""

    def __init__(self, lib: ctypes.CDLL):
        self.lib = lib

    def frame(self, header: bytes, payload: bytes, flags: int = 0) -> bytes:
        size = self.lib.fl4h_frame_size(len(header), len(payload))
        out = ctypes.create_string_buffer(size)
        n = self.lib.fl4h_frame(
            header, len(header), payload, len(payload), flags, out, size
        )
        if n < 0:
            raise FrameError("frame buffer sizing failed")
        return out.raw[:n]

    def unframe(self, buf: bytes) -> tuple[bytes, bytes, int]:
        ho = ctypes.c_uint32()
        hl = ctypes.c_uint32()
        po = ctypes.c_uint64()
        pl = ctypes.c_uint64()
        fl = ctypes.c_uint16()
        rc = self.lib.fl4h_unframe(
            buf, len(buf), ctypes.byref(ho), ctypes.byref(hl),
            ctypes.byref(po), ctypes.byref(pl), ctypes.byref(fl),
        )
        if rc != 0:
            raise FrameError(_ERRORS.get(rc, f"unframe error {rc}"))
        h = buf[ho.value : ho.value + hl.value]
        p = buf[po.value : po.value + pl.value]
        return h, p, fl.value

    def crc32(self, data: bytes) -> int:
        return self.lib.fl4h_crc32(data, len(data), 0)


class PyFraming:
    """Pure-Python twin (same bytes on the wire)."""

    def frame(self, header: bytes, payload: bytes, flags: int = 0) -> bytes:
        body = _FIXED.pack(_MAGIC, _VERSION, flags, len(header), len(payload))
        body += header + payload
        return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)

    def unframe(self, buf: bytes) -> tuple[bytes, bytes, int]:
        if len(buf) < _FIXED.size + 4:
            raise FrameError("short frame")
        magic, version, flags, hlen, plen = _FIXED.unpack_from(buf)
        if magic != _MAGIC:
            raise FrameError("bad magic")
        if version != _VERSION:
            raise FrameError("bad version")
        total = _FIXED.size + hlen + plen + 4
        if len(buf) < total:
            raise FrameError("short frame")
        (expect,) = struct.unpack_from("<I", buf, total - 4)
        if expect != (zlib.crc32(buf[: total - 4]) & 0xFFFFFFFF):
            raise FrameError("bad crc")
        return (
            buf[_FIXED.size : _FIXED.size + hlen],
            buf[_FIXED.size + hlen : _FIXED.size + hlen + plen],
            flags,
        )

    def crc32(self, data: bytes) -> int:
        return zlib.crc32(data) & 0xFFFFFFFF


def get_framing():
    lib = get_native()
    return NativeFraming(lib) if lib is not None else PyFraming()


# ---------------------------------------------------------------------------
# int4 nibble packing (compressed wire frames, codec.py encode_compressed)
# ---------------------------------------------------------------------------

def _pack_int4_py(vals) -> bytes:
    import numpy as np

    u = np.asarray(vals, np.int8).view(np.uint8) & 0xF
    if u.size % 2:
        u = np.concatenate([u, np.zeros((1,), np.uint8)])
    return (u[0::2] | (u[1::2] << 4)).astype(np.uint8).tobytes()


def _unpack_int4_py(packed: bytes, n: int):
    import numpy as np

    b = np.frombuffer(packed, np.uint8)
    out = np.empty(2 * b.size, np.int16)
    out[0::2] = b & 0xF
    out[1::2] = b >> 4
    return (((out[:n] ^ 0x8) - 0x8)).astype(np.int8)


def pack_int4(vals) -> bytes:
    """Pack signed int4 values (int8 array, each in [-8, 7]) two per byte,
    low nibble first — native C++ when available, NumPy twin otherwise
    (byte-identical; tests/transport/test_native.py pins the parity)."""
    import numpy as np

    v = np.ascontiguousarray(vals, np.int8)
    lib = get_native()
    if lib is None or not hasattr(lib, "fl4h_pack_nibbles"):
        return _pack_int4_py(v)
    out = ctypes.create_string_buffer((v.size + 1) // 2)
    n = lib.fl4h_pack_nibbles(v.tobytes(), v.size, out, len(out))
    if n < 0:
        raise FrameError("int4 pack buffer sizing failed")
    return out.raw[:n]


def unpack_int4(packed: bytes, n: int):
    """Inverse of :func:`pack_int4`: ``n`` sign-extended int8 values."""
    import numpy as np

    if len(packed) < (n + 1) // 2:
        raise FrameError(
            f"int4 payload too short: {len(packed)} bytes for {n} values"
        )
    lib = get_native()
    if lib is None or not hasattr(lib, "fl4h_unpack_nibbles"):
        return _unpack_int4_py(packed, n)
    out = ctypes.create_string_buffer(max(n, 1))
    rc = lib.fl4h_unpack_nibbles(packed, n, out, len(out))
    if rc < 0:
        raise FrameError("int4 unpack buffer sizing failed")
    return np.frombuffer(out.raw[:n], np.int8).copy()

// Native framing codec for the cross-silo transport (fl4health_tpu.transport).
//
// Role: the hot host-side byte work of the wire path — CRC-32 integrity
// checksums and frame assembly/validation — in C++, replacing the grpcio
// C-core's framing role in the reference stack (SURVEY §2.14: Flower ships
// serialized NumPy arrays over gRPC; the C core does the byte handling).
// The array math stays in XLA; this is the runtime seam around it.
//
// Frame layout (little-endian):
//   magic   u32  = 0x464C3448  ("FL4H")
//   version u16  = 1
//   flags   u16  (bit 0: payload is COO-sparse)
//   header_len u32
//   payload_len u64
//   header  [header_len]   (JSON metadata, produced by Python)
//   payload [payload_len]  (raw array bytes)
//   crc     u32  (CRC-32 over everything above)
//
// Exposed C ABI (ctypes):
//   u32  fl4h_crc32(const u8* data, u64 len, u32 seed)
//   i64  fl4h_frame_size(u32 header_len, u64 payload_len)
//   i64  fl4h_frame(const u8* header, u32 header_len,
//                   const u8* payload, u64 payload_len,
//                   u16 flags, u8* out, u64 out_cap)
//   i64  fl4h_unframe(const u8* buf, u64 len,
//                     u32* header_off, u32* header_len,
//                     u64* payload_off, u64* payload_len, u16* flags)
//     returns 0 ok; -1 short; -2 bad magic; -3 bad version; -4 bad crc
//   i64  fl4h_pack_nibbles(const i8* vals, u64 n, u8* out, u64 out_cap)
//     packs signed int4 values (each in [-8, 7]) two per byte (low nibble
//     first); returns packed byte count or -1 on short buffer
//   i64  fl4h_unpack_nibbles(const u8* packed, u64 n_vals,
//                            i8* out, u64 out_cap)
//     inverse (sign-extends each nibble); returns n_vals or -1
// The nibble helpers are the hot byte loop of the compressed int4 wire
// frames (codec.py encode_compressed) — the Python twin matches them
// byte-for-byte (tests/transport/test_native.py).

#include <cstdint>
#include <cstring>

extern "C" {

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t fl4h_crc32(const uint8_t* data, uint64_t len, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (uint64_t i = 0; i < len; i++)
        c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

static const uint32_t kMagic = 0x464C3448u;
static const uint16_t kVersion = 1;
static const uint64_t kHeaderFixed = 4 + 2 + 2 + 4 + 8;

int64_t fl4h_frame_size(uint32_t header_len, uint64_t payload_len) {
    return (int64_t)(kHeaderFixed + header_len + payload_len + 4);
}

static void put_u16(uint8_t* p, uint16_t v) { memcpy(p, &v, 2); }
static void put_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
static void put_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
static uint16_t get_u16(const uint8_t* p) { uint16_t v; memcpy(&v, p, 2); return v; }
static uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
static uint64_t get_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

int64_t fl4h_frame(const uint8_t* header, uint32_t header_len,
                   const uint8_t* payload, uint64_t payload_len,
                   uint16_t flags, uint8_t* out, uint64_t out_cap) {
    uint64_t total = kHeaderFixed + header_len + payload_len + 4;
    if (out_cap < total) return -1;
    uint8_t* p = out;
    put_u32(p, kMagic); p += 4;
    put_u16(p, kVersion); p += 2;
    put_u16(p, flags); p += 2;
    put_u32(p, header_len); p += 4;
    put_u64(p, payload_len); p += 8;
    if (header_len) { memcpy(p, header, header_len); p += header_len; }
    if (payload_len) { memcpy(p, payload, payload_len); p += payload_len; }
    uint32_t crc = fl4h_crc32(out, (uint64_t)(p - out), 0);
    put_u32(p, crc);
    return (int64_t)total;
}

int64_t fl4h_unframe(const uint8_t* buf, uint64_t len,
                     uint32_t* header_off, uint32_t* header_len,
                     uint64_t* payload_off, uint64_t* payload_len,
                     uint16_t* flags) {
    if (len < kHeaderFixed + 4) return -1;
    if (get_u32(buf) != kMagic) return -2;
    if (get_u16(buf + 4) != kVersion) return -3;
    uint16_t fl = get_u16(buf + 6);
    uint32_t hlen = get_u32(buf + 8);
    uint64_t plen = get_u64(buf + 12);
    uint64_t total = kHeaderFixed + hlen + plen + 4;
    if (len < total) return -1;
    uint32_t expect = get_u32(buf + total - 4);
    uint32_t actual = fl4h_crc32(buf, total - 4, 0);
    if (expect != actual) return -4;
    *header_off = (uint32_t)kHeaderFixed;
    *header_len = hlen;
    *payload_off = kHeaderFixed + hlen;
    *payload_len = plen;
    *flags = fl;
    return 0;
}

int64_t fl4h_pack_nibbles(const int8_t* vals, uint64_t n,
                          uint8_t* out, uint64_t out_cap) {
    uint64_t packed = (n + 1) / 2;
    if (out_cap < packed) return -1;
    for (uint64_t i = 0; i < packed; i++) {
        uint8_t lo = (uint8_t)(vals[2 * i]) & 0xF;
        uint8_t hi = (2 * i + 1 < n) ? ((uint8_t)(vals[2 * i + 1]) & 0xF) : 0;
        out[i] = (uint8_t)(lo | (hi << 4));
    }
    return (int64_t)packed;
}

int64_t fl4h_unpack_nibbles(const uint8_t* packed, uint64_t n_vals,
                            int8_t* out, uint64_t out_cap) {
    if (out_cap < n_vals) return -1;
    for (uint64_t i = 0; i < n_vals; i++) {
        uint8_t nib = (i & 1) ? (packed[i / 2] >> 4) : (packed[i / 2] & 0xF);
        // sign-extend the 4-bit two's-complement value
        out[i] = (int8_t)((nib ^ 0x8) - 0x8);
    }
    return (int64_t)n_vals;
}

}  // extern "C"

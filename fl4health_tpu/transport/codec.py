"""Pytree <-> wire codec for the cross-silo transport.

Parity surface (SURVEY §2.14): the reference's wire format is Flower's
``Parameters`` — a list of NumPy arrays serialized per round over gRPC
(strategies own pack/unpack; grpcio's C core does the byte handling). For
cross-silo deployments (real hospitals, no shared mesh) the TPU build keeps
a host-level wire with the same contract.

Design:
- header = JSON metadata (dotted leaf paths, shapes, dtypes) — code never
  executes from the wire (no pickle);
- payload = the raw little-endian array bytes, concatenated in path order;
- framing (magic/version/flags/lengths/CRC-32) is the native C++ codec
  (transport/native.py) with a byte-identical Python fallback;
- sparse packets cross as real COO (values + int32 indices) — the dense
  0/1-mask encoding used on-device (exchange/packer.py SparseMaskPacket)
  converts at this host boundary, reproducing the reference's
  SparseCooParameterPacker wire compactness (parameter_packer.py:94,124);
- ``decode(data, like=template)`` restores the EXACT pytree structure
  (flax struct dataclasses included) by unflattening into the template's
  treedef; without a template the result is nested dicts.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import numpy as np

from fl4health_tpu.core.types import PyTree
from fl4health_tpu.exchange.packer import SparseMaskPacket
from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.transport.native import get_framing

FLAG_COO = 1


def _account(direction: str, nbytes: int, kind: str) -> None:
    """Wire byte accounting (arXiv:1610.05492-style per-round cost) into the
    process-wide registry. Host-side counter bumps only — no device work, so
    the codec hot path cost is unchanged to first order."""
    reg = get_registry()
    reg.counter(
        f"transport_bytes_{direction}_total",
        help=f"total wire bytes {direction} by the codec",
    ).inc(nbytes)
    reg.counter(
        f"transport_frames_{direction}_total",
        help=f"wire frames {direction} by the codec",
        labels={"kind": kind},
    ).inc()


def _paths_and_leaves(tree: PyTree) -> list[tuple[str, np.ndarray]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for key_path, leaf in flat:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        out.append((dotted, np.asarray(leaf)))
    return out


def encode(tree: PyTree) -> bytes:
    """Dense pytree -> one wire frame."""
    entries = _paths_and_leaves(tree)
    meta, chunks = [], []
    for path, arr in entries:
        data = np.ascontiguousarray(arr)
        if data.dtype.byteorder == ">":
            data = data.astype(data.dtype.newbyteorder("<"))
        # dtype recorded AFTER the little-endian conversion — the header must
        # describe the payload bytes, not the caller's original layout.
        meta.append({"path": path, "shape": list(arr.shape), "dtype": str(data.dtype)})
        chunks.append(data.tobytes())
    header = json.dumps({"leaves": meta}).encode("utf-8")
    frame = get_framing().frame(header, b"".join(chunks), flags=0)
    _account("encoded", len(frame), "dense")
    return frame


def _rebuild_nested(items: list[tuple[str, np.ndarray]]) -> dict:
    root: dict = {}
    for path, arr in items:
        node = root
        parts = path.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return root


def decode(data: bytes, like: PyTree | None = None) -> PyTree:
    """Wire frame -> pytree. With ``like``, leaves are unflattened into the
    template's exact treedef (paths must match); otherwise nested dicts."""
    header, payload, flags = get_framing().unframe(data)
    meta = json.loads(header.decode("utf-8"))
    if flags & FLAG_COO:
        raise ValueError("COO frame: use decode_sparse()")
    _account("decoded", len(data), "dense")
    items: list[tuple[str, np.ndarray]] = []
    off = 0
    for entry in meta["leaves"]:
        dt = np.dtype(entry["dtype"])
        n = int(np.prod(entry["shape"], dtype=np.int64)) if entry["shape"] else 1
        nbytes = n * dt.itemsize
        arr = np.frombuffer(payload, dt, count=n, offset=off).reshape(entry["shape"])
        items.append((entry["path"], arr))
        off += nbytes
    if like is None:
        return _rebuild_nested(items)
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(like)
    by_path = dict(items)
    leaves = []
    for key_path, template_leaf in flat_t:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        if dotted not in by_path:
            raise ValueError(f"wire frame missing leaf {dotted!r}")
        leaves.append(by_path[dotted])
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Sparse (COO) boundary
# ---------------------------------------------------------------------------

def encode_sparse(packet: SparseMaskPacket) -> bytes:
    """SparseMaskPacket (dense 0/1 element masks, the device encoding) ->
    COO wire frame shipping only selected values + their flat indices."""
    params = _paths_and_leaves(packet.params)
    masks = dict(_paths_and_leaves(packet.element_mask))
    meta, chunks = [], []
    for path, arr in params:
        mask = masks[path]
        flat_idx = np.nonzero(mask.ravel() > 0)[0].astype(np.int32)
        values = np.ascontiguousarray(arr.ravel()[flat_idx])
        meta.append(
            {
                "path": path,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "nnz": int(flat_idx.size),
            }
        )
        chunks.append(flat_idx.tobytes())
        chunks.append(values.tobytes())
    header = json.dumps({"coo": meta}).encode("utf-8")
    frame = get_framing().frame(header, b"".join(chunks), flags=FLAG_COO)
    _account("encoded", len(frame), "coo")
    return frame


def decode_sparse(data: bytes, like: SparseMaskPacket | None = None) -> SparseMaskPacket:
    """COO wire frame -> dense params + element masks (zeros where absent)."""
    header, payload, flags = get_framing().unframe(data)
    if not flags & FLAG_COO:
        raise ValueError("dense frame: use decode()")
    _account("decoded", len(data), "coo")
    meta = json.loads(header.decode("utf-8"))
    items, mask_items = [], []
    off = 0
    for entry in meta["coo"]:
        dt = np.dtype(entry["dtype"])
        nnz = entry["nnz"]
        idx = np.frombuffer(payload, np.int32, count=nnz, offset=off)
        off += nnz * 4
        vals = np.frombuffer(payload, dt, count=nnz, offset=off)
        off += nnz * dt.itemsize
        n = int(np.prod(entry["shape"], dtype=np.int64)) if entry["shape"] else 1
        dense = np.zeros((n,), dt)
        dense[idx] = vals
        mask = np.zeros((n,), np.float32)
        mask[idx] = 1.0
        items.append((entry["path"], dense.reshape(entry["shape"])))
        mask_items.append((entry["path"], mask.reshape(entry["shape"])))
    if like is None:
        return SparseMaskPacket(
            params=_rebuild_nested(items), element_mask=_rebuild_nested(mask_items)
        )
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(like.params)
    by_path, by_path_m = dict(items), dict(mask_items)
    leaves, mask_leaves = [], []
    for key_path, _ in flat_t:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        leaves.append(by_path[dotted])
        mask_leaves.append(by_path_m[dotted])
    return SparseMaskPacket(
        params=jax.tree_util.tree_unflatten(treedef, leaves),
        element_mask=jax.tree_util.tree_unflatten(treedef, mask_leaves),
    )
